"""SLO vocabulary + burn-rate engine for the serving gateway.

The load-driven autoscaler reacts to QUEUES; queues are a symptom.
What the fleet actually promises users is per-priority **objectives**
— a TTFT target, an end-to-end target, and the fraction of requests
that must meet them (:class:`SloObjective`) — and what an operator
actually pages on is the **error-budget burn rate**: how fast the
band is consuming its allowance of slow/failed requests, measured
over two windows (the Google SRE multi-window rule: a FAST window
(~5 min) catches a cliff quickly, a SLOW window (~1 h) keeps a blip
from paging — an alert needs BOTH burning).

:class:`SloEngine` computes all of it from the router's own completion
stream (the same observations that feed the traced TTFT/e2e
histograms), using O(1)-memory time-bucketed counters per band — at
10k QPS an event deque over a one-hour window would hold 36M entries;
a 60-bucket ring holds 60.

Exported families (per band, optionally per window):

- ``serving_slo_compliance{band,window}``       — fraction of requests
  meeting BOTH targets over the window (1.0 when idle);
- ``serving_slo_burn_rate{band,window}``        — error-budget
  consumption rate: 1.0 = exactly on budget, >1 = burning toward
  exhaustion, e.g. 14.4 = the classic page-now threshold;
- ``serving_slo_budget_remaining{band}``        — unspent error budget
  over the slow window, 1.0 = untouched, 0.0 = exhausted;
- ``serving_slo_class_burn_rate{tenant_class,window}`` — the same burn
  arithmetic per TENANT CLASS (the bounded tenancy vocabulary, never
  raw tenant ids — DL010): a premium class burning while the band
  aggregate looks healthy is exactly the noisy-neighbor signature,
  and the class burns feed :meth:`SloEngine.pressure` so autoscale
  reacts to it.

The engine's :meth:`pressure` (max over bands of the multi-window
burn) feeds :class:`~dlrover_tpu.brain.serving.ServingScalePolicy` as
``ServingSignal.slo_pressure`` — scale-ups fire on budget burn, not
just queue depth: a band whose p99 TTFT is violating its objective
scales out even while the queue stays shallow (slow replicas keep the
queue drained *and* the users waiting).

All observation paths are lock-guarded O(#bands) arithmetic with no
allocation and no I/O — safe from under the router's step lock
(DL003/DL007 clean).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Tuple

from dlrover_tpu.serving.router.gateway import (
    PRIORITY_BATCH,
    PRIORITY_HIGH,
    PRIORITY_NORMAL,
)
from dlrover_tpu.serving.tenancy import TENANT_CLASSES

BAND_NAMES = {
    PRIORITY_HIGH: "HIGH",
    PRIORITY_NORMAL: "NORMAL",
    PRIORITY_BATCH: "BATCH",
}


@dataclasses.dataclass(frozen=True)
class SloObjective:
    """One priority band's service-level objective."""

    band: int                     # gateway priority (PRIORITY_*)
    ttft_target_s: float          # first token within this
    e2e_target_s: float           # completion within this
    target: float = 0.99          # required compliance ratio

    @property
    def name(self) -> str:
        return BAND_NAMES.get(self.band, str(self.band))

    @property
    def error_budget(self) -> float:
        """Allowed bad fraction (1 - target), floored so a target of
        1.0 cannot divide burn rates by zero."""
        return max(1e-9, 1.0 - self.target)


def default_objectives() -> Tuple[SloObjective, ...]:
    """The stock ladder: HIGH pays for tight latency, BATCH trades it
    for throughput — mirroring the brown-out shed order."""
    return (
        SloObjective(PRIORITY_HIGH, ttft_target_s=0.5,
                     e2e_target_s=5.0, target=0.999),
        SloObjective(PRIORITY_NORMAL, ttft_target_s=1.0,
                     e2e_target_s=10.0, target=0.99),
        SloObjective(PRIORITY_BATCH, ttft_target_s=5.0,
                     e2e_target_s=60.0, target=0.95),
    )


@dataclasses.dataclass(frozen=True)
class ClassObjective:
    """One TENANT CLASS's objective — same targets, keyed on the
    bounded tenancy vocabulary instead of a priority band.  The class
    dimension cuts ACROSS bands: it answers "are premium users getting
    what premium promises" whatever priorities they submit at."""

    tenant_class: str
    ttft_target_s: float
    e2e_target_s: float
    target: float = 0.99

    def __post_init__(self):
        if self.tenant_class not in TENANT_CLASSES:
            raise ValueError(
                f"tenant_class {self.tenant_class!r} not in the "
                f"bounded vocabulary {TENANT_CLASSES}")

    @property
    def name(self) -> str:
        return self.tenant_class

    @property
    def error_budget(self) -> float:
        return max(1e-9, 1.0 - self.target)


def default_class_objectives() -> Tuple[ClassObjective, ...]:
    """Stock per-class ladder, mirroring the band defaults: premium
    pays for tight latency, background trades it away."""
    return (
        ClassObjective("premium", ttft_target_s=0.5,
                       e2e_target_s=5.0, target=0.999),
        ClassObjective("standard", ttft_target_s=1.0,
                       e2e_target_s=10.0, target=0.99),
        ClassObjective("background", ttft_target_s=5.0,
                       e2e_target_s=60.0, target=0.95),
    )


class _BucketWindow:
    """Time-bucketed (total, bad) counters over a sliding window —
    O(buckets) memory whatever the request rate.  Buckets older than
    the window are zeroed lazily as time advances."""

    def __init__(self, window_s: float, buckets: int = 30):
        self.window_s = float(window_s)
        self.n = int(buckets)
        self.span = self.window_s / self.n
        # bucket slot -> [epoch_index, total, bad]
        self._slots: List[List[float]] = [
            [-1, 0, 0] for _ in range(self.n)]

    def _slot(self, now: float) -> List[float]:
        epoch = int(now / self.span)
        slot = self._slots[epoch % self.n]
        if slot[0] != epoch:
            slot[0], slot[1], slot[2] = epoch, 0, 0
        return slot

    def observe(self, bad: bool, now: float) -> None:
        slot = self._slot(now)
        slot[1] += 1
        if bad:
            slot[2] += 1

    def totals(self, now: float) -> Tuple[int, int]:
        """(total, bad) over the live window."""
        min_epoch = int(now / self.span) - self.n + 1
        total = bad = 0
        for epoch, t, b in self._slots:
            if epoch >= min_epoch:
                total += t
                bad += b
        return total, bad


class _BandState:
    def __init__(self, objective: SloObjective, fast_window: float,
                 slow_window: float):
        self.objective = objective
        self.fast = _BucketWindow(fast_window, buckets=30)
        self.slow = _BucketWindow(slow_window, buckets=60)
        self.observed_total = 0
        self.violations_total = 0


class SloEngine:
    """Per-priority objective tracking + multi-window burn rates."""

    def __init__(
        self,
        objectives: Optional[Tuple[SloObjective, ...]] = None,
        fast_window_s: float = 300.0,
        slow_window_s: float = 3600.0,
        class_objectives: Optional[Tuple[ClassObjective, ...]] = None,
    ):
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self._lock = threading.Lock()
        self._bands: Dict[int, _BandState] = {}
        for obj in (objectives or default_objectives()):
            self._bands[obj.band] = _BandState(
                obj, self.fast_window_s, self.slow_window_s)
        # tenant-CLASS states: same ring arithmetic keyed on the
        # bounded tenancy vocabulary (never raw tenant ids — DL010)
        self._classes: Dict[str, _BandState] = {}
        for cobj in (class_objectives or default_class_objectives()):
            self._classes[cobj.tenant_class] = _BandState(
                cobj, self.fast_window_s, self.slow_window_s)

    def objective(self, band: int) -> Optional[SloObjective]:
        state = self._bands.get(band)
        return None if state is None else state.objective

    def class_objective(self, tenant_class: str
                        ) -> Optional[ClassObjective]:
        state = self._classes.get(tenant_class)
        return None if state is None else state.objective

    # ------------------------------------------------------- observe
    def observe(self, band: int, ttft_s: Optional[float],
                e2e_s: float, now: float,
                tenant_class: Optional[str] = None) -> None:
        """One completed request: compliant iff BOTH targets held.
        A missing TTFT (non-streaming legacy path) judges on e2e
        alone rather than inventing a violation.  ``tenant_class``
        (when given) judges the same completion AGAIN against the
        class's own objective — band and class are independent
        promises, a request can meet one and violate the other."""
        state = self._bands.get(band)
        if state is not None:
            obj = state.objective
            bad = e2e_s > obj.e2e_target_s or (
                ttft_s is not None and ttft_s > obj.ttft_target_s)
            self._record(state, bad, now)
        cstate = (self._classes.get(tenant_class)
                  if tenant_class is not None else None)
        if cstate is not None:
            cobj = cstate.objective
            cbad = e2e_s > cobj.e2e_target_s or (
                ttft_s is not None and ttft_s > cobj.ttft_target_s)
            self._record(cstate, cbad, now)

    def observe_violation(self, band: int, now: float,
                          tenant_class: Optional[str] = None) -> None:
        """A request that never produced its answer inside the SLO at
        all — deadline expiry.  Counts as one observed, one bad."""
        state = self._bands.get(band)
        if state is not None:
            self._record(state, True, now)
        cstate = (self._classes.get(tenant_class)
                  if tenant_class is not None else None)
        if cstate is not None:
            self._record(cstate, True, now)

    def _record(self, state: _BandState, bad: bool,
                now: float) -> None:
        with self._lock:
            state.fast.observe(bad, now)
            state.slow.observe(bad, now)
            state.observed_total += 1
            if bad:
                state.violations_total += 1

    # --------------------------------------------------------- views
    def _window(self, state: _BandState, window: str) -> _BucketWindow:
        return state.fast if window == "fast" else state.slow

    def compliance(self, band: int, now: float,
                   window: str = "fast") -> float:
        state = self._bands.get(band)
        if state is None:
            return 1.0
        with self._lock:
            total, bad = self._window(state, window).totals(now)
        return 1.0 if total == 0 else 1.0 - bad / total

    def burn_rate(self, band: int, now: float,
                  window: str = "fast") -> float:
        """Error-budget consumption rate over the window: the bad
        fraction divided by the allowed bad fraction.  1.0 = burning
        exactly at budget; an idle window burns 0."""
        state = self._bands.get(band)
        if state is None:
            return 0.0
        with self._lock:
            total, bad = self._window(state, window).totals(now)
        if total == 0:
            return 0.0
        return (bad / total) / state.objective.error_budget

    def budget_remaining(self, band: int, now: float) -> float:
        """Unspent error budget over the SLOW window, clamped [0, 1]:
        0.0 means the band has already served its whole allowance of
        bad requests this window — every further violation is debt."""
        state = self._bands.get(band)
        if state is None:
            return 1.0
        with self._lock:
            total, bad = state.slow.totals(now)
        if total == 0:
            return 1.0
        allowed = total * state.objective.error_budget
        return max(0.0, min(1.0, 1.0 - bad / max(1e-9, allowed)))

    def class_burn_rate(self, tenant_class: str, now: float,
                        window: str = "fast") -> float:
        """Per-tenant-class error-budget burn (same arithmetic as
        :meth:`burn_rate`, keyed on the bounded tenancy vocabulary)."""
        state = self._classes.get(tenant_class)
        if state is None:
            return 0.0
        with self._lock:
            total, bad = self._window(state, window).totals(now)
        if total == 0:
            return 0.0
        return (bad / total) / state.objective.error_budget

    def class_compliance(self, tenant_class: str, now: float,
                         window: str = "fast") -> float:
        state = self._classes.get(tenant_class)
        if state is None:
            return 1.0
        with self._lock:
            total, bad = self._window(state, window).totals(now)
        return 1.0 if total == 0 else 1.0 - bad / total

    def pressure(self, now: float) -> float:
        """The autoscale signal: max over bands AND tenant classes of
        the MULTI-WINDOW burn (min of fast and slow) — both windows
        must be burning, so one bad second cannot trigger a scale-up
        but a sustained violation does even while the queue stays
        shallow.  The class dimension is what lets a flooded premium
        class pull capacity while the band aggregate still looks
        healthy (its violations diluted by the flooding tenant's own
        completions)."""
        worst = 0.0
        for band in self._bands:
            burn = min(self.burn_rate(band, now, "fast"),
                       self.burn_rate(band, now, "slow"))
            worst = max(worst, burn)
        for cls in self._classes:
            burn = min(self.class_burn_rate(cls, now, "fast"),
                       self.class_burn_rate(cls, now, "slow"))
            worst = max(worst, burn)
        return worst

    # ------------------------------------------------------- exports
    def otlp_metrics(self, now: float) -> List[tuple]:
        """``[(name, attrs, value)]`` for the OTLP labeled-gauge push
        (the collector's ``/fleet/slo`` view reads exactly these)."""
        out: List[tuple] = []
        for band, state in sorted(self._bands.items()):
            name = state.objective.name
            for window in ("fast", "slow"):
                attrs = {"band": name, "window": window}
                out.append(("serving_slo_compliance", attrs,
                            self.compliance(band, now, window)))
                out.append(("serving_slo_burn_rate", attrs,
                            self.burn_rate(band, now, window)))
            out.append(("serving_slo_budget_remaining", {"band": name},
                        self.budget_remaining(band, now)))
        for cls in sorted(self._classes):
            for window in ("fast", "slow"):
                out.append((
                    "serving_slo_class_burn_rate",
                    {"tenant_class": cls, "window": window},
                    self.class_burn_rate(cls, now, window)))
        return out

    def render(self) -> str:
        """Prometheus text with band/window labels — wire via
        ``MetricsExporter.add_text_source`` (or ``attach_router``)."""
        import time as _time

        from dlrover_tpu.utils.metric_registry import metric_help
        from dlrover_tpu.utils.profiler import escape_label_value

        now = _time.monotonic()
        lines: List[str] = []
        seen_help = set()
        for name, attrs, value in self.otlp_metrics(now):
            if name not in seen_help:
                seen_help.add(name)
                help_text = metric_help(name)
                if help_text:
                    lines.append(f"# HELP {name} {help_text}")
                lines.append(f"# TYPE {name} gauge")
            inner = ",".join(
                f'{k}="{escape_label_value(str(v))}"'
                for k, v in sorted(attrs.items()))
            lines.append(f"{name}{{{inner}}} {value:.6g}")
        return "\n".join(lines) + "\n"

    def summary(self, now: float) -> Dict[str, dict]:
        """JSON-ready verdict per band (the bench rig's SLO report)."""
        out: Dict[str, dict] = {}
        for band, state in sorted(self._bands.items()):
            obj = state.objective
            out[obj.name] = {
                "ttft_target_s": obj.ttft_target_s,
                "e2e_target_s": obj.e2e_target_s,
                "target": obj.target,
                "observed": state.observed_total,
                "violations": state.violations_total,
                "compliance_fast": round(
                    self.compliance(band, now, "fast"), 6),
                "burn_rate_fast": round(
                    self.burn_rate(band, now, "fast"), 4),
                "burn_rate_slow": round(
                    self.burn_rate(band, now, "slow"), 4),
                "budget_remaining": round(
                    self.budget_remaining(band, now), 6),
                "met": self.compliance(band, now, "slow")
                >= obj.target,
            }
        for cls, state in sorted(self._classes.items()):
            cobj = state.objective
            out[f"class:{cls}"] = {
                "ttft_target_s": cobj.ttft_target_s,
                "e2e_target_s": cobj.e2e_target_s,
                "target": cobj.target,
                "observed": state.observed_total,
                "violations": state.violations_total,
                "burn_rate_fast": round(
                    self.class_burn_rate(cls, now, "fast"), 4),
                "burn_rate_slow": round(
                    self.class_burn_rate(cls, now, "slow"), 4),
                "met": self.class_compliance(cls, now, "slow")
                >= cobj.target,
            }
        return out
