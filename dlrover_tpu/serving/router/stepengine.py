"""The sharded router front: N independent step loops behind one door.

The second candidate behind the step-engine seam (the first is the
consolidated single-threaded event loop, ``ServingRouter(
step_engine="event")`` — see router.py).  The question the ROADMAP
poses — "single-threaded event loop or sharded routers behind a
consistent front — pick per measurement, not per taste" — is answered
by benchmarking BOTH on the full-pipeline open-loop rig
(``bench.py --config router``); PERF.md "Router raw speed" records the
A/B and the shipped default is the measured winner.

Design:

- **requests partition by hashed admission counter**: the front hashes
  a monotonically increasing admission ordinal to pick the shard (the
  "rid hash" discipline — stateless, uniform, no routing table); the
  request's ACTUAL rid is then minted by that shard's gateway, in a
  per-shard disjoint space so fleet-level views never see two shards
  hand out the same rid.  Each admission lands on exactly one shard's
  gateway, so no request is ever visible to two step loops and the
  zero-lost/books discipline holds per shard and therefore globally;
- **replicas partition at join** (least-loaded shard): one replica
  belongs to one shard — two step loops must never race placements
  into one engine's capacity ledger;
- **shared brown-out view**: one :class:`BrownoutPolicy` object serves
  every shard's gateway for admission shedding, but its watermark is
  updated ONLY by the front with fleet-global queued demand and
  capacity (each shard runs ``brownout_external=True``), so the ladder
  cannot flap per-shard on a lopsided queue;
- **two drive modes**: deterministic (``threaded=False``; ``step()``
  steps every shard in order on the caller's thread — what the
  equivalence tests replay seeded workloads through) and threaded
  (``threaded=True``; ``start()`` spawns one loop thread per shard —
  the "N independent step loops" the A/B measures, honestly including
  whatever the GIL takes back on this host).

Cross-shard placement (work stealing from a busy shard's queue onto an
idle shard's replicas) is deliberately absent: it would re-introduce
exactly the shared-ledger locking this front exists to remove.  The
cost is fleet utilization on skewed partitions — rid-hash admission
keeps the skew statistical, and the rig measures the result.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.serving.router.gateway import (
    PRIORITY_NORMAL,
    ServingRequest,
)
from dlrover_tpu.serving.router.router import ServingRouter


def shard_of(rid: int, num_shards: int) -> int:
    """The rid-hash partition (Knuth multiplicative hash so adjacent
    rids spread instead of striping with any stride a caller batches
    in)."""
    return ((rid * 2654435761) >> 16) % num_shards


class ShardedRouterFront:
    """N independent :class:`ServingRouter` step loops behind one
    submit/step/has_work door (duck-compatible with the slice of the
    router surface the rig and the drive helpers use)."""

    def __init__(
        self,
        num_shards: int = 2,
        router_factory=None,
        brownout=None,
        threaded: bool = False,
        step_engine: str = "event",
        tenants=None,
    ):
        if num_shards < 1:
            raise ValueError(f"num_shards must be >= 1: {num_shards}")
        self.num_shards = int(num_shards)
        self.threaded = bool(threaded)
        self.brownout = brownout
        self.tenants = tenants
        factory = router_factory or (
            lambda shard: ServingRouter(step_engine=step_engine))
        self.shards: List[ServingRouter] = [
            factory(i) for i in range(self.num_shards)]
        for i, shard in enumerate(self.shards):
            # disjoint rid spaces: each shard's gateway mints its own
            # request ids, and a front-level results()/books view must
            # never see two shards hand out the same rid
            shard.gateway._next_rid = i * (10 ** 12)
        for shard in self.shards:
            if brownout is not None:
                # ONE policy object: admission shedding on every
                # shard's gateway consults the same (front-updated)
                # stage; the shard applies but never updates it
                shard.brownout = brownout
                shard.gateway.brownout = brownout
                shard.brownout_external = True
            if tenants is not None:
                # ONE registry object shared by every shard's gateway:
                # quotas meter FLEET traffic (a per-shard registry
                # would multiply every quota_qps by num_shards); the
                # registry's own lock makes bucket consumption safe
                # across shard threads
                shard.gateway.tenants = tenants
        # admission ordinal for the shard hash (itertools.count.next
        # is GIL-atomic, so concurrent client submits draw distinct
        # ordinals without a lock)
        self._arrivals = itertools.count()
        self._join_rr = 0
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()

    # ------------------------------------------------------ membership
    def join_replica(self, name: str, engine, node=None,
                     now: Optional[float] = None):
        """Join onto the least-populated shard (ties: round-robin) —
        one replica belongs to exactly one step loop."""
        sizes = [len(s.manager.replicas) for s in self.shards]
        idx = min(range(self.num_shards),
                  key=lambda i: (sizes[i], (i - self._join_rr)
                                 % self.num_shards))
        self._join_rr = (idx + 1) % self.num_shards
        return self.shards[idx].join_replica(
            name, engine, node=node, now=now)

    @property
    def replica_names(self) -> List[str]:
        return [n for s in self.shards for n in s.replica_names]

    def shard_of_replica(self, name: str) -> Optional[ServingRouter]:
        for s in self.shards:
            if name in s.manager.replicas:
                return s
        return None

    # --------------------------------------------------------- client
    def submit(self, prompt_ids, max_new_tokens: int,
               priority: int = PRIORITY_NORMAL,
               timeout: Optional[float] = None,
               now: Optional[float] = None,
               tenant: Optional[str] = None) -> ServingRequest:
        shard = self.shards[
            shard_of(next(self._arrivals), self.num_shards)]
        return shard.submit(prompt_ids, max_new_tokens,
                            priority=priority, timeout=timeout,
                            now=now, tenant=tenant)

    # ----------------------------------------------------------- pump
    def _update_shared_brownout(self, now: float) -> None:
        if self.brownout is None:
            return
        depth = 0
        capacity = 0.0
        for s in self.shards:
            depth += s.gateway.depth()
            # under the shard's step lock: in threaded mode the
            # watermark thread races the shard loop's reap/retire
            # mutations of manager.replicas, and an unguarded
            # iteration would die with "dict changed size" — killing
            # the daemon thread and freezing the fleet's brown-out
            # stage forever.  One shard lock at a time (never nested),
            # so no ordering cycle (DL008).
            with s._lock:
                handles = s.manager.schedulable(now)
            for handle in handles:
                try:
                    capacity += (handle.slots_free()
                                 + len(handle.inflight))
                except Exception:
                    continue  # a dying replica's ledger is not capacity
        prev = self.brownout.stage
        stage = self.brownout.update(now, depth, capacity)
        if stage != prev:
            for s in self.shards:
                s.recorder.record(
                    "brownout_stage", stage=stage, prev=prev,
                    name=self.brownout.stage_name, fleet_global=True,
                    now=now)
            log = logger.warning if stage > prev else logger.info
            log("sharded front brown-out stage %d -> %d (%s): "
                "fleet depth %d, capacity %.0f slots",
                prev, stage, self.brownout.stage_name, depth, capacity)

    def step(self, now: Optional[float] = None) -> List[ServingRequest]:
        """Deterministic drive: one round of every shard, in shard
        order, on the caller's thread.  In threaded mode the loops
        drive themselves and this briefly yields instead (so drive
        loops written against the router surface stay correct)."""
        if self.threaded and self._threads:
            time.sleep(0.0005)
            return []
        now = time.monotonic() if now is None else now
        self._update_shared_brownout(now)
        completed: List[ServingRequest] = []
        for shard in self.shards:
            completed.extend(shard.step(now))
        return completed

    # ------------------------------------------------- threaded drive
    def start(self, poll_seconds: float = 0.0005) -> None:
        """Threaded mode: one independent step loop per shard plus the
        front's brown-out watermark tick.  Each loop owns its shard
        exclusively — the only shared object is the brown-out policy,
        which the shards read and only the front writes."""
        if not self.threaded:
            raise RuntimeError("start() requires threaded=True")
        if self._threads:
            return
        self._stop.clear()

        def _loop(shard: ServingRouter) -> None:
            while not self._stop.is_set():
                shard.step()
                if not shard.has_work:
                    self._stop.wait(poll_seconds)

        def _watermark() -> None:
            while not self._stop.wait(0.005):
                self._update_shared_brownout(time.monotonic())

        for i, shard in enumerate(self.shards):
            t = threading.Thread(
                target=_loop, args=(shard,), daemon=True,
                name=f"router-shard-{i}")
            t.start()
            self._threads.append(t)
        if self.brownout is not None:
            t = threading.Thread(
                target=_watermark, daemon=True,
                name="router-front-watermark")
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = []

    # ------------------------------------------------------ aggregates
    @property
    def has_work(self) -> bool:
        return any(s.has_work for s in self.shards)

    def run_until_idle(self, max_steps: int = 100000,
                       now_fn=None) -> int:
        now_fn = now_fn or time.monotonic
        steps = 0
        while self.has_work:
            if steps >= max_steps:
                depths = [s.gateway.depth() for s in self.shards]
                raise RuntimeError(
                    f"sharded front still busy after {max_steps} "
                    f"steps (depths={depths})")
            self.step(now_fn())
            steps += 1
            if self.threaded and self._threads:
                time.sleep(0.001)
        return steps

    def counters(self) -> Dict[str, float]:
        """Fleet-global lifecycle counters summed across shards — the
        books-balance surface (submitted == completed + timed_out +
        cancelled + poisoned + engine-rejected; shed admissions never
        entered)."""
        keys = (
            "serving_requests_submitted_total",
            "serving_requests_completed_total",
            "serving_requests_rejected_total",
            "serving_requests_timed_out_total",
            "serving_requests_requeued_total",
            "serving_requests_poisoned_total",
            "serving_requests_cancelled_total",
            "serving_cancel_send_failures_total",
            "serving_generated_tokens_total",
            "serving_queue_depth",
            "serving_inflight",
        )
        out: Dict[str, float] = {k: 0.0 for k in keys}
        for s in self.shards:
            m = s.metrics.metrics()
            for k in keys:
                out[k] += float(m.get(k, 0.0))
        return out

    def results(self, requests: List[ServingRequest],
                timeout: Optional[float] = None):
        return {r.rid: r.result(timeout) for r in requests}
