"""Autoscale hooks: router load -> Brain plan -> ScalePlan -> replicas.

The training auto-scaler's loop (speed samples -> Brain optimize ->
ScalePlan -> Scaler), rebuilt for serving:

- the router's :class:`~.metrics.RouterMetrics` windows provide the
  signals (queue depth, TTFT, tokens/sec);
- the DECISION comes from :class:`~dlrover_tpu.brain.serving.
  ServingScalePolicy` — run locally by default, or remotely through a
  ``BrainClient.serving_plan`` query when a Brain deployment is
  configured (same policy code on both paths);
- the EXECUTION is a plain :class:`~dlrover_tpu.master.scaler.base.
  ScalePlan` handed to any cluster ``Scaler`` — the in-memory scheduler
  in tests, pod/actor scalers (scheduler/k8s.py, scheduler/ray.py) in
  deployments;
- the :class:`ReplicaProvisioner` closes the loop: cluster node events
  coming back from the scaler's watcher become router join/leave calls.

Scale-down is drain-first: the victim replica stops taking placements,
finishes its in-flight requests inside the router pump, and only the
DRAINED husk's node is removed from the cluster — no request is ever
cut off by a scale decision.

**Capacity debt (self-healing).**  The load-driven policy reacts to
QUEUES; a quarantined crash-looper or a probationary replica is lost
capacity the queue only reveals minutes later.  So the autoscaler also
polls a *capacity-debt* feed every ``on_step`` — quarantined workers
from the :class:`~dlrover_tpu.serving.remote.supervisor.
WorkerSupervisor` (``supervisor=``), probationary replicas from the
router's :class:`~.replica.ReplicaManager` — and issues a
replacement-node ``ScalePlan`` (a ``launch_nodes`` entry, outside the
cooldown gate) the SAME poll a debt appears, instead of serving
short-handed through the quarantine window.  Each debt retires exactly
once: when its replacement replica joins the router, or when the
source clears first (quarantine served, probation cooled, worker
exited cleanly) — never both, so a healed fleet is not
double-provisioned.  Open debts surface as the
``serving_capacity_debt`` gauge and as ``capacity_debt_opened`` /
``capacity_debt_retired`` flight-recorder events.

Every executed scale decision also opens a control-plane **autoscale
trace** (served at ``/traces/autoscale``): marker spans for the
load-window snapshot, the policy verdict and the ScalePlan emission at
decision time, then milestone spans stitched from the flight
recorder's fabric-event vocabulary as the decision materializes —
``node_create`` (provisioner) → ``worker_spawn`` (supervisor) →
``hello_join`` (router) → ``probation`` (if damped) →
``first_placement`` (the new replica takes traffic); scale-downs trace
``drain`` → ``retired`` per victim.  Replacement decisions get their
own always-sampled trace whose root carries ``replacement_for`` (the
quarantined worker / probationary replica being backfilled), stitched
through the same milestones.  Each milestone span runs from the
previous milestone, so the trace reads as "where did the 9 seconds
between 'queue too deep' and 'new replica serving' actually go".
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from dlrover_tpu.brain.serving import ServingScalePolicy, ServingSignal
from dlrover_tpu.common.constants import NodeEventType, NodeType
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.node import Node, NodeGroupResource, NodeResource
from dlrover_tpu.master.scaler.base import ScalePlan, Scaler
from dlrover_tpu.serving.router.replica import base_replica_name

# replacement nodes get ids/ranks from this offset so they can never
# collide with group-fill ranks, and group-count shrinks (which drop
# the highest ranks first) retire replacements before steady nodes
_REPLACEMENT_RANK_BASE = 9000


class ServingAutoScaler:
    """Periodic replica-count control loop, driven by router steps."""

    def __init__(
        self,
        router,
        scaler: Scaler,
        policy: Optional[ServingScalePolicy] = None,
        brain=None,                    # BrainClient-like (serving_plan)
        supervisor=None,               # WorkerSupervisor-like (debt feed)
        job_name: str = "serving",
        node_type: str = NodeType.SERVING_REPLICA,
        node_resource: Optional[NodeResource] = None,
        decide_interval: float = 5.0,
        cooldown: float = 15.0,
        min_samples: int = 3,
        join_latency_floor: float = 0.0,
    ):
        self.router = router
        self.scaler = scaler
        self.policy = policy or ServingScalePolicy()
        self.brain = brain
        self.supervisor = supervisor
        self.job_name = job_name
        self.node_type = node_type
        self.node_resource = node_resource or NodeResource()
        self.decide_interval = float(decide_interval)
        self.cooldown = float(cooldown)
        self.min_samples = int(min_samples)
        self._samples: List[ServingSignal] = []
        self._last_sample = 0.0
        self._last_scale = 0.0
        # replicas this autoscaler asked to drain, by name -> their Node
        self._pending_removal: Dict[str, Optional[Node]] = {}
        self.plans: List[ScalePlan] = []
        # capacity debt: key -> {kind, source, replacement, node,
        # opened_at, retired}; a retired entry lingers until its source
        # clears so one quarantine episode can never open two debts —
        # UNLESS the joined replacement itself dies while the source is
        # still out, which reopens the episode (see _sweep)
        self.debts: Dict[str, dict] = {}
        # replacements the policy itself drained (never a reopen cue)
        self._policy_drained: set = set()
        self.capacity_debt_retired = 0
        self._next_replacement = 0
        # provisioning-latency-aware probation debts (ROADMAP): a debt
        # whose source self-retires sooner than ANY replacement node
        # has ever managed to join is deferred instead of launched — a
        # ~2s first-flap probation must not pay a full launch+drain
        # cycle.  The floor is the larger of the configured PRIOR and
        # the fastest join ever OBSERVED (opened_at -> joined samples
        # collected in _retire_debt).  The default 0.0 prior means
        # "launch until the cluster has taught us its join latency":
        # the first observed join activates deferral for probations
        # shorter than that measured floor — quarantines always launch.
        self.join_latency_floor = float(join_latency_floor)
        self._join_samples: List[float] = []
        self.capacity_debt_deferred_total = 0
        # replicas beyond the policy's max the LOAD signals still call
        # for — demand the serving pool cannot satisfy from its own
        # capacity; the fleet coordinator's borrow trigger reads this
        self.unmet_demand = 0
        # control-plane tracing: one autoscale trace per executed
        # decision (policy episode OR replacement), milestones stitched
        # from flight-recorder events.  _open_traces holds every trace
        # still materializing; _scale_trace points at the policy
        # episode's record (replans merge into it, a new episode
        # supersedes it) — replacement traces live only in the list.
        self.tracer = getattr(router, "tracer", None)
        self.recorder = getattr(router, "recorder", None)
        self._open_traces: List[dict] = []
        self._scale_trace: Optional[dict] = None
        self._event_cursor = (
            self.recorder.last_seq if self.recorder is not None else -1)
        router.autoscaler = self

    # -------------------------------------------------------- sampling
    def on_step(self, now: Optional[float] = None) -> None:
        """Router pump hook: sample the windows, maybe act."""
        now = time.monotonic() if now is None else now
        if now - self._last_sample >= self.decide_interval / max(
            1, self.min_samples
        ):
            self._last_sample = now
            m = self.router.metrics
            slo = getattr(self.router, "slo", None)
            self._samples.append(ServingSignal(
                queue_depth=m.queue_depth_mean(now),
                ttft_seconds=m.ttft_mean(now),
                tokens_per_sec=m.tokens_per_second(now),
                # SLO error-budget burn next to the load windows: the
                # policy scales up on sustained burn even when slow
                # replicas keep the queue itself shallow
                slo_pressure=(
                    slo.pressure(now) if slo is not None else 0.0),
            ))
            del self._samples[: -8 * self.min_samples]
            # unmet demand refreshes on EVERY sample, not only inside
            # the cooldown-gated decision path: a stale positive value
            # frozen across a 15s cooldown would keep the fleet
            # coordinator borrowing hosts against demand that already
            # subsided (one spurious blocking-checkpoint + shrink +
            # boot + return cycle per dwell)
            if len(self._samples) >= self.min_samples:
                # current is CLAMPED to max_replicas for this reading:
                # borrowed fleet hosts push up_count past the policy
                # cap, and feeding that into raw_desired would latch
                # unmet_demand positive forever (raw >= current in the
                # steady band) — the coordinator would then never
                # return the loan.  Unmet demand means "demand beyond
                # serving-NATIVE capacity", so it is measured as if
                # only the native pool existed.
                eff = min(max(self.router.manager.up_count(), 1),
                          self.policy.max_replicas)
                raw = self.policy.raw_desired(
                    self._samples[-self.min_samples:], eff)
                self.unmet_demand = max(
                    0, raw - self.policy.max_replicas)
            else:
                self.unmet_demand = 0
        self._stitch_scale_traces()
        self._finish_deaths()
        self._finish_drains()
        # capacity debt runs OUTSIDE the cooldown gate: a quarantine is
        # known capacity loss, and making it wait out the policy
        # cooldown is exactly the wait-out this sweep exists to remove
        self._sweep_capacity_debt(now)
        if now - self._last_scale >= self.cooldown:
            self.maybe_scale(now)

    # -------------------------------------------------------- deciding
    def desired_replicas(self, current: int) -> int:
        if len(self._samples) < self.min_samples:
            return current
        samples = self._samples[-self.min_samples:]
        if self.brain is not None:
            try:
                got = self.brain.serving_plan(
                    job_name=self.job_name,
                    current_replicas=current,
                    min_replicas=self.policy.min_replicas,
                    max_replicas=self.policy.max_replicas,
                    queue_high=self.policy.queue_high,
                    queue_low=self.policy.queue_low,
                    ttft_high=self.policy.ttft_high,
                    samples=[s.to_dict() for s in samples],
                )
                if got:
                    return int(got)
            except Exception as e:  # Brain outage must not stop serving
                logger.warning("brain serving_plan failed: %s", e)
        return self.policy.decide(samples, current)

    def maybe_scale(self, now: Optional[float] = None
                    ) -> Optional[ScalePlan]:
        """One control decision; returns the executed plan, if any."""
        now = time.monotonic() if now is None else now
        current = self.router.manager.up_count()
        if current == 0 and self.router.gateway.depth() == 0:
            return None
        desired = self.desired_replicas(max(current, 1))
        self._report_brain(current)
        if desired > current:
            plan = self._scale_up(desired)
        elif desired < current:
            plan = self._scale_down(current - desired)
        else:
            return None
        if plan is not None:
            # trace BEFORE clearing samples: the load-window snapshot
            # span wants the evidence the decision was made from
            self._trace_decision(now, current, desired, plan)
        self._last_scale = now
        self._samples.clear()  # decide from post-change evidence only
        return plan

    # ------------------------------------------------------- executing
    def _scale_up(self, desired: int) -> ScalePlan:
        # ``desired`` counts UP replicas, but the cluster group still
        # contains draining replicas' nodes until their removal plans
        # land, plus replacement nodes that have not joined yet — the
        # group count must include both or the scaler sees "already at
        # count" and silently adds nothing (or worse, shrinks a node
        # the policy never chose)
        count = (desired + len(self._pending_removal)
                 + self._unjoined_replacements())
        plan = ScalePlan(node_group_resources={
            self.node_type: NodeGroupResource(
                count=count, node_resource=self.node_resource)
        })
        logger.info(
            "serving scale-up: -> %d replicas (+%d draining, "
            "+%d replacements in flight)",
            desired, len(self._pending_removal),
            self._unjoined_replacements())
        self.plans.append(plan)
        self.scaler.scale(plan)
        return plan

    def _scale_down(self, n: int) -> Optional[ScalePlan]:
        """Drain-first: pick the least-loaded UP replicas and stop
        placements; node removal happens when each one empties."""
        victims = sorted(
            (
                h for h in self.router.manager.schedulable()
                if h.name not in self._pending_removal
            ),
            key=lambda h: len(h.inflight),
        )[:n]
        if not victims:
            return None
        for handle in victims:
            logger.info(
                "serving scale-down: draining replica %s "
                "(%d in-flight)", handle.name, len(handle.inflight),
            )
            self.router.begin_drain(handle.name)
            self._pending_removal[handle.name] = handle.node
            # a drained replacement must not reopen its capacity debt:
            # the policy decided the fleet is big enough WITH the
            # source still out, so its disappearance is not a new loss
            # (only debt replacements are tracked, base-normalized;
            # entries are pruned when their debt closes)
            base = base_replica_name(handle.name)
            if any(d["replacement"] == base
                   for d in self.debts.values()):
                self._policy_drained.add(base)
        return ScalePlan()  # removal plan follows once drained

    def _finish_deaths(self) -> None:
        """Retire DEAD replicas' cluster nodes.  Without this the
        crashed replica's node stays 'alive' in the cluster, every
        future scale-up count matches the stale node count and adds
        nothing — a crash would permanently cap the fleet.  A drain
        victim dying mid-drain also lands here: its _pending_removal
        entry must not inflate scale-up counts forever."""
        while self.router.dead:
            rec = self.router.dead.popleft()
            node = self._pending_removal.pop(rec.name, None) or rec.node
            if node is not None:
                plan = ScalePlan(remove_nodes=[node])
                self.plans.append(plan)
                self.scaler.scale(plan)
                logger.info(
                    "serving replica %s died; removed its node %s",
                    rec.name, node.name)

    def _finish_drains(self) -> None:
        """Retire drained replicas: emit the remove_nodes plan."""
        if not self._pending_removal:
            return
        for handle in list(self.router.drained):
            if handle.name not in self._pending_removal:
                continue  # drained by someone else; not ours to retire
            node = self._pending_removal.pop(handle.name)
            self.router.drained.remove(handle)
            if node is not None:
                plan = ScalePlan(remove_nodes=[node])
                self.plans.append(plan)
                self.scaler.scale(plan)
                logger.info(
                    "serving scale-down: removed node %s", node.name)

    def _report_brain(self, current: int) -> None:
        if self.brain is None or not self._samples:
            return
        s = self._samples[-1]
        try:
            self.brain.record_serving(
                job_uuid=self.job_name, job_name=self.job_name,
                replicas=current, queue_depth=s.queue_depth,
                ttft_seconds=s.ttft_seconds,
                tokens_per_sec=s.tokens_per_sec,
            )
        except Exception:  # telemetry only; never blocks the loop
            pass

    # --------------------------------------------------- capacity debt
    def _debt_sources(self, now: float) -> Dict[str, dict]:
        """Current capacity-loss feed: supervisor quarantines + replica
        probations, keyed for idempotent debt bookkeeping."""
        sources: Dict[str, dict] = {}
        if self.supervisor is not None:
            feed = getattr(self.supervisor, "capacity_debt", None)
            if feed is not None:
                for src in feed(now):
                    sources[src["key"]] = src
        manager_feed = getattr(self.router.manager, "capacity_debt",
                               None)
        if manager_feed is not None:
            for src in manager_feed(now):
                sources[src["key"]] = src
        return sources

    def _replica_bases(self) -> set:
        """Router replica names normalized to their base — a supervisor
        respawn rejoins as ``name#rN``, and the debt bookkeeping must
        recognize it as the same replacement (every other subsystem
        normalizes through :func:`base_replica_name`)."""
        return {base_replica_name(n) for n in self.router.replica_names}

    def _unjoined_replacements(self) -> int:
        bases = self._replica_bases()
        return sum(
            1 for d in self.debts.values()
            if not d["retired"] and d["replacement"] is not None
            and d["replacement"] not in bases
        )

    def _join_floor(self) -> float:
        """Effective node-join latency floor: the configured prior or
        the fastest opened->joined latency ever observed, whichever is
        larger (observation can only RAISE the bar — a slow cluster
        defers more aggressively, never less safely)."""
        observed = min(self._join_samples) if self._join_samples else 0.0
        return max(self.join_latency_floor, observed)

    def _base_has_live_replica(self, key: str, now: float) -> bool:
        """True when the debt key's base currently has a schedulable,
        off-probation replica in the manager — the signal that a
        probation episode genuinely healed (vs the source merely
        flickering out during a crash-loop's death gap)."""
        base = key.split(":", 1)[1] if ":" in key else key
        for h in self.router.manager.replicas.values():
            if (base_replica_name(h.name) == base and h.schedulable
                    and h.probation_until <= now):
                return True
        return False

    def _drop_debt(self, key: str) -> None:
        debt = self.debts.pop(key)
        self._policy_drained.discard(debt["replacement"])

    @staticmethod
    def _debt_base(key: str) -> str:
        return key.split(":", 1)[1] if ":" in key else key

    def _sweep_capacity_debt(self, now: float) -> None:
        """Reconcile open debts against the feed: retire each debt
        exactly once (replacement joined, or source cleared — whichever
        comes first), open a debt + replacement plan for every NEW
        source, and publish the ``serving_capacity_debt`` gauge.

        Debt identity is the BASE, not the feed key: one lost worker is
        one backfill, even as it moves between feeds across its
        crash-loop life (``probation:<base>`` while it respawns,
        ``quarantine:<base>`` when the budget blows — both can even
        surface in the same poll while a dead respawn awaits reaping).
        The feed is first collapsed to one source per base (quarantine
        outranks probation as the authoritative, longer-lived record),
        and an existing episode follows its base across keys instead of
        a second node being launched."""
        sources = self._debt_sources(now)
        bases = self._replica_bases()
        per_base: Dict[str, dict] = {}
        for src in sources.values():
            b = self._debt_base(src["key"])
            cur = per_base.get(b)
            if cur is None or (cur.get("kind") != "quarantine"
                               and src.get("kind") == "quarantine"):
                per_base[b] = src
        self._sweep_deferred(per_base, now)
        for base, debt in [(self._debt_base(k), d)
                           for k, d in list(self.debts.items())]:
            if debt.get("deferred"):
                continue  # handled by _sweep_deferred (never launched)
            src = per_base.get(base)
            key = debt["key"]
            if src is not None and src["key"] != key:
                # the base moved between feeds: ONE episode, rekeyed
                old_key = key
                del self.debts[old_key]
                debt["key"] = key = src["key"]
                debt["kind"] = src.get("kind", debt["kind"])
                debt["source"] = src.get("source", debt["source"])
                self.debts[key] = debt
                if self.recorder is not None:
                    self.recorder.record(
                        "capacity_debt_rekeyed", key=key,
                        from_key=old_key,
                        replacement=debt["replacement"], now=now)
                logger.info(
                    "capacity debt %s continues as %s (replacement "
                    "%s) — one lost worker is one backfill, not two",
                    old_key, key, debt["replacement"])
            if not debt["retired"]:
                if debt["replacement"] in bases:
                    self._retire_debt(debt, "replacement_joined", now)
                elif src is None:
                    self._retire_debt(debt, "source_cleared", now)
            if debt["retired"] and src is None:
                # the source is gone.  Quarantine feeds are
                # authoritative (the supervisor holds the record for
                # the whole sentence), but a PROBATION source flickers
                # out during every crash-loop death gap — deleting the
                # entry there would launch a fresh replacement node per
                # respawn cycle, one flapping pod provisioning
                # max_respawns surplus nodes.  So a probation episode
                # only closes when the base demonstrably healed (a
                # live off-probation replica); until then the entry
                # lingers and the next flap reuses it.
                if (debt["kind"] != "probation"
                        or self._base_has_live_replica(key, now)):
                    self._drop_debt(key)
            elif (debt["retired"] and src is not None
                  and debt.get("retired_reason") == "replacement_joined"
                  and debt["replacement"] not in bases
                  and debt["replacement"] not in self._policy_drained):
                # the replacement JOINED and then DIED while the source
                # is still out: the loss is back, and the lingering
                # retired entry would otherwise block a backfill for
                # the rest of the quarantine window — drop it so this
                # same sweep opens a fresh debt.  (A policy-drained
                # replacement is exempt: deliberate shrink, not a new
                # loss.  A replacement that never joined is NOT
                # reopened — its launch plan is still in flight and the
                # provisioner retries the join; reopening would
                # double-provision the common slow-provision case.)
                if self.recorder is not None:
                    self.recorder.record(
                        "capacity_debt_reopened", key=key,
                        lost_replacement=debt["replacement"], now=now)
                logger.warning(
                    "capacity debt %s: replacement %s died while %s is "
                    "still out of service — reopening the debt",
                    key, debt["replacement"], debt["source"])
                self._drop_debt(key)
        open_bases = {self._debt_base(k) for k in self.debts}
        for base, src in per_base.items():
            if base not in open_bases:
                self._open_debt(src["key"], src, now)
        metrics = getattr(self.router, "metrics", None)
        if metrics is not None:
            # deferred entries are excluded: the gauge's contract is
            # "replacement launched but not joined", and a deferral
            # deliberately launched nothing
            metrics.capacity_debt = float(sum(
                1 for d in self.debts.values()
                if not d["retired"] and not d.get("deferred")))

    def _sweep_deferred(self, per_base: Dict[str, dict],
                        now: float) -> None:
        """Deferred probation debts: entries that opened no node
        because their source's ``until`` horizon was shorter than the
        node-join latency floor.  Each poll they either clear (source
        healed before any replacement could have arrived — the exact
        launch+drain cycle the deferral saved), follow their base
        across feed keys, or PROMOTE to a real launch the moment the
        horizon stretches past the floor (escalated probation,
        quarantine)."""
        for key, debt in list(self.debts.items()):
            if not debt.get("deferred"):
                continue
            base = self._debt_base(key)
            src = per_base.get(base)
            if src is None:
                # nothing was provisioned, so nothing retires: the
                # episode simply never became a debt
                del self.debts[key]
                if self.recorder is not None:
                    self.recorder.record(
                        "capacity_debt_deferred_cleared", key=key,
                        source=debt["source"], now=now)
                logger.info(
                    "deferred capacity debt %s cleared: %s healed "
                    "faster than a replacement could join (saved one "
                    "launch+drain cycle)", key, debt["source"])
                continue
            if src["key"] != key:
                del self.debts[key]
                debt["key"] = key = src["key"]
                debt["kind"] = src.get("kind", debt["kind"])
                debt["source"] = src.get("source", debt["source"])
                self.debts[key] = debt
            horizon = float(src.get("until", now)) - now
            if src.get("kind") != "probation" or \
                    horizon >= self._join_floor():
                del self.debts[key]
                if self.recorder is not None:
                    self.recorder.record(
                        "capacity_debt_promoted", key=key,
                        horizon_s=round(max(horizon, 0.0), 3), now=now)
                self._open_debt(key, src, now)  # real launch now

    def _open_debt(self, key: str, src: dict, now: float) -> None:
        """A new capacity loss: issue the replacement-node plan NOW (a
        ``launch_nodes`` entry — no waiting for load signals or the
        policy cooldown) and open its always-sampled autoscale trace
        with ``replacement_for`` naming what it backfills.

        Exception — the provisioning-latency guard: a PROBATION whose
        ``until`` horizon is shorter than the observed node-join
        latency floor would self-retire before any replacement could
        take traffic; launching for it pays a full launch+drain cycle
        per flap.  Such a debt opens DEFERRED (bookkept, no node); it
        promotes to a real launch if the episode outlives the horizon
        (escalation, quarantine) and clears silently if it heals
        first (see :meth:`_sweep_deferred`)."""
        horizon = float(src.get("until", now)) - now
        floor = self._join_floor()
        if (src.get("kind") == "probation" and floor > 0.0
                and horizon < floor):
            self.debts[key] = {
                "key": key, "kind": src.get("kind", "?"),
                "source": src.get("source", "?"),
                "replacement": None, "node": None,
                "opened_at": now, "retired": False, "deferred": True,
            }
            self.capacity_debt_deferred_total += 1
            if self.recorder is not None:
                self.recorder.record(
                    "capacity_debt_deferred", key=key,
                    source=src.get("source", "?"),
                    horizon_s=round(max(horizon, 0.0), 3),
                    join_floor_s=round(floor, 3), now=now)
            logger.info(
                "capacity debt %s deferred: probation horizon %.2fs "
                "is shorter than the node-join latency floor %.2fs — "
                "no replacement could arrive in time, so none is "
                "launched unless the episode escalates",
                key, max(horizon, 0.0), floor)
            return
        n = self._next_replacement
        self._next_replacement += 1
        node = Node(
            self.node_type,
            _REPLACEMENT_RANK_BASE + n,
            rank_index=_REPLACEMENT_RANK_BASE + n,
            name=f"{self.node_type}-replacement-{n}",
            config_resource=self.node_resource,
        )
        self.debts[key] = {
            "key": key, "kind": src.get("kind", "?"),
            "source": src.get("source", "?"),
            "replacement": node.name, "node": node,
            "opened_at": now, "retired": False,
        }
        plan = ScalePlan(launch_nodes=[node])
        self.plans.append(plan)
        self.scaler.scale(plan)
        if self.recorder is not None:
            self.recorder.record(
                "capacity_debt_opened", key=key,
                debt_kind=src.get("kind", "?"),
                source=src.get("source", "?"),
                replacement=node.name, now=now)
        logger.warning(
            "capacity debt: %s (%s) is out of service — replacement "
            "node %s launched immediately (debt retires when it joins "
            "or the source recovers)",
            src.get("source", "?"), src.get("kind", "?"), node.name)
        self._trace_replacement(now, src, node)

    def _retire_debt(self, debt: dict, reason: str, now: float) -> None:
        debt["retired"] = True
        debt["retired_reason"] = reason
        self.capacity_debt_retired += 1
        if reason == "replacement_joined":
            # opened->joined is the cluster's real provisioning
            # latency; its floor (fastest ever) gates future deferrals
            self._join_samples.append(max(0.0, now - debt["opened_at"]))
            del self._join_samples[:-32]
        if self.recorder is not None:
            self.recorder.record(
                "capacity_debt_retired", key=debt["key"],
                source=debt["source"], replacement=debt["replacement"],
                reason=reason, now=now)
        logger.info(
            "capacity debt for %s retired (%s)", debt["source"], reason)
        if reason == "source_cleared":
            # the source healed before the replacement joined: close
            # the replacement trace now — its milestones stopped
            # mattering, and the surplus node drains via the policy
            for st in list(self._open_traces):
                if st.get("only") == {debt["replacement"]} \
                        and not st["placed"]:
                    self._close_trace(st, "source_cleared", now)

    # ------------------------------------------- control-plane tracing
    # the stage each fabric event advances a NEW replica to; spans run
    # from the previous milestone so stage-to-stage latency is visible
    _UP_STAGES = {
        "node_create": "node_create",
        "worker_spawn": "worker_spawn",
        "replica_join": "hello_join",
        "replica_first_placement": "first_placement",
    }

    def _trace_decision(self, now: float, current: int, desired: int,
                        plan: ScalePlan) -> None:
        """Open the policy decision's autoscale trace (always sampled:
        control-plane traces are one-per-decision, never hot-path)."""
        if self.tracer is None:
            return
        direction = "up" if desired > current else "down"
        st = self._scale_trace
        if st is not None and st["direction"] == direction \
                and st["desired"] == desired:
            # the same episode re-planned while its replicas are still
            # materializing (short cooldowns re-decide every round):
            # ONE trace per episode, with the replan count on the root
            st["plans"] += 1
            st["root"].attrs["plans"] = st["plans"]
            return
        if st is not None:
            self._close_trace(st, "superseded", now)
        tracer = self.tracer
        root = tracer.start_trace(
            "autoscale", now=now, always_sample=True,
            current=current, desired=desired, direction=direction)
        sample = self._samples[-1] if self._samples else None
        window_attrs = {} if sample is None else {
            "queue_depth": round(sample.queue_depth, 3),
            "ttft_seconds": round(sample.ttft_seconds, 6),
            "tokens_per_sec": round(sample.tokens_per_sec, 3),
            "slo_pressure": round(sample.slo_pressure, 4),
        }
        slo = getattr(self.router, "slo", None)
        if slo is not None and hasattr(slo, "class_burn_rate"):
            # which tenant CLASS is burning when this decision fired —
            # the postmortem's "scaled up because premium was starving"
            # reads straight off the decision trace
            for cls in getattr(slo, "_classes", {}):
                window_attrs[f"class_burn_{cls}"] = round(
                    slo.class_burn_rate(cls, now, "fast"), 4)
        tracer.start_span(
            root, "load_window", now=now,
            samples=len(self._samples), **window_attrs).finish(now)
        tracer.start_span(
            root, "policy", now=now, current=current, desired=desired,
            source="brain" if self.brain is not None else "local",
        ).finish(now)
        tracer.start_span(
            root, "scale_plan", now=now,
            count=sum(
                g.count for g in plan.node_group_resources.values()),
            remove_nodes=len(plan.remove_nodes),
        ).finish(now)
        record = {
            "root": root, "direction": direction, "desired": desired,
            "decided_at": now, "plans": 1,
            # replicas that existed at decision time: anything ELSE
            # joining afterwards is this decision materializing
            "known": set(self.router.replica_names),
            "stage_t": {}, "stages": {}, "placed": set(),
            "expected_new": max(0, desired - current),
            "victims": set(self._pending_removal),
            "retired": set(),
            # None = claim any unknown name not owned by a
            # replacement trace; replacement traces pin their name
            "only": None,
        }
        self._scale_trace = record
        self._open_traces.append(record)

    def _trace_replacement(self, now: float, src: dict,
                           node: Node) -> None:
        """Open a replacement decision's autoscale trace: root carries
        ``replacement_for``, the marker span records the debt evidence,
        and stitching is pinned to the replacement node's name."""
        if self.tracer is None:
            return
        tracer = self.tracer
        current = self.router.manager.up_count()
        root = tracer.start_trace(
            "autoscale", now=now, always_sample=True,
            current=current, desired=current + 1, direction="up",
            replacement_for=src.get("source", "?"),
            debt_kind=src.get("kind", "?"))
        tracer.start_span(
            root, "capacity_debt", now=now,
            key=src.get("key", "?"), kind=src.get("kind", "?"),
            source=src.get("source", "?"),
            until=float(src.get("until", now))).finish(now)
        tracer.start_span(
            root, "scale_plan", now=now, count=1, remove_nodes=0,
            replacement=node.name).finish(now)
        self._open_traces.append({
            "root": root, "direction": "up", "desired": current + 1,
            "decided_at": now, "plans": 1,
            "known": set(), "stage_t": {}, "stages": {},
            "placed": set(), "expected_new": 1,
            "victims": set(), "retired": set(),
            "only": {node.name},
        })

    def sync_traces(self) -> None:
        """Consume pending fabric events into the open autoscale
        traces — and the router's replica-origin registry — NOW.  The
        router calls this right before placement so a replica that
        joined since the last poll has its origin registered before
        its FIRST attempt stamps links (on_step alone runs after the
        step's placements, one round too late for that first hit).
        Cursor-based and idempotent; pure dict/span bookkeeping, safe
        under the step lock (DL003)."""
        self._stitch_scale_traces()

    def current_episode_link(self) -> Optional[dict]:
        """The live autoscale episode's trace reference, if one is
        open — the fleet coordinator links a borrow's
        ``fleet_migration`` trace to it as the demand evidence (its
        ``load_window``/``policy`` spans are the recorded 'why')."""
        st = self._scale_trace
        if st is None and self._open_traces:
            st = self._open_traces[-1]
        if st is None:
            return None
        root = st["root"]
        return {"trace_id": root.trace_id, "span_id": root.span_id,
                "kind": "autoscale_episode"}

    def _claimed_names(self) -> set:
        """Names pinned by replacement traces — the generic policy
        trace must not stitch THEIR milestones as its own."""
        claimed: set = set()
        for st in self._open_traces:
            if st.get("only"):
                claimed |= st["only"]
        return claimed

    def _stitch_scale_traces(self) -> None:
        """Consume new flight-recorder events into every open autoscale
        trace — the cross-component stitch: provisioner node creation,
        supervisor worker spawn, router join/probation/first placement
        all narrate through the recorder, and this turns their
        timestamps into milestone spans."""
        if self.recorder is None:
            return
        events = self.recorder.events_since(self._event_cursor)
        if events:
            self._event_cursor = max(e["seq"] for e in events)
        if not self._open_traces or self.tracer is None:
            return
        claimed = self._claimed_names()
        for event in events:
            for st in list(self._open_traces):
                if st["direction"] == "up":
                    self._stitch_up(st, event, claimed)
                else:
                    self._stitch_down(st, event)

    def _stitch_up(self, st: dict, event: dict, claimed: set) -> None:
        kind = str(event.get("kind"))
        name = event.get("replica") or event.get("worker") \
            or event.get("node")
        if not name or name in st["known"]:
            return
        only = st.get("only")
        if only is not None:
            if name not in only:
                return
        elif name in claimed:
            # a replacement trace owns this name's story
            return
        # the replica's ORIGIN registry: this trace is the control-
        # plane decision that created the replica — recorded on the
        # router so every later placement's attempt span can link back
        # to it ("why does the replica this request landed on exist").
        # Keyed by base name (a supervisor respawn rejoins as name#rN
        # and is still the same decision's offspring); ASSIGNED, not
        # setdefault — a name re-created by a later decision must link
        # to the trace that created THIS incarnation, not a long-
        # evicted predecessor.  Only CREATION milestones register
        # (probation included): an unrelated replica's death or
        # quarantine event naming an unknown worker must not be
        # credited to whatever trace happens to be open.
        creation_event = (kind in self._UP_STAGES
                          or kind == "replica_probation")
        origins = getattr(self.router, "replica_origins", None)
        if origins is not None and creation_event:
            root = st["root"]
            entry = {"trace_id": root.trace_id,
                     "span_id": root.span_id}
            replacement_for = root.attrs.get("replacement_for")
            if replacement_for is not None:
                entry["kind"] = "replacement"
                entry["replacement_for"] = replacement_for
            else:
                entry["kind"] = "autoscale"
            origins[base_replica_name(str(name))] = entry
        t = float(event.get("t", st["decided_at"]))
        if kind == "replica_probation":
            # crash-loop damping delayed this replica's first traffic:
            # the probation span runs join -> scheduled release
            self.tracer.start_span(
                st["root"], "probation", now=t, replica=name,
            ).finish(max(t, float(event.get("until", t))))
            return
        stage = self._UP_STAGES.get(kind)
        if stage is None or stage in st["stages"].setdefault(name, set()):
            return
        start = st["stage_t"].get(name, st["decided_at"])
        # clamp: stitched events may mix the caller's synthetic clock
        # with real monotonic stamps; a milestone never runs backwards
        end = max(t, start)
        self.tracer.start_span(
            st["root"], stage, now=start, replica=name).finish(end)
        st["stages"][name].add(stage)
        st["stage_t"][name] = end
        if stage == "first_placement":
            st["placed"].add(name)
            if len(st["placed"]) >= st["expected_new"]:
                self._close_trace(st, "ok", end)

    def _stitch_down(self, st: dict, event: dict) -> None:
        kind = str(event.get("kind"))
        name = event.get("replica")
        if name not in st["victims"]:
            return
        t = float(event.get("t", st["decided_at"]))
        # a victim dying MID-DRAIN still terminates its leg of the
        # decision (the node is retired through _finish_deaths) — the
        # trace must close rather than sit active forever
        stage = {"replica_drain": "drain",
                 "replica_retired": "retired",
                 "replica_dead": "retired"}.get(kind)
        if stage is None or stage in st["stages"].setdefault(name, set()):
            return
        start = st["stage_t"].get(name, st["decided_at"])
        end = max(t, start)
        attrs = {"replica": name}
        if kind == "replica_dead":
            attrs["died_mid_drain"] = True
        self.tracer.start_span(
            st["root"], stage, now=start, **attrs).finish(end)
        st["stages"][name].add(stage)
        st["stage_t"][name] = end
        if stage == "retired":
            st["retired"].add(name)
            if st["retired"] >= st["victims"]:
                self._close_trace(st, "ok", end)

    def _close_trace(self, st: dict, status: str,
                     now: Optional[float] = None) -> None:
        if self.tracer is None:
            return
        try:
            self._open_traces.remove(st)
        except ValueError:
            return  # already closed (stitch + retire racing one step)
        if st is self._scale_trace:
            self._scale_trace = None
        end = max(st["decided_at"],
                  st["decided_at"] if now is None else now)
        self.tracer.finish_trace(st["root"], now=end, status=status)


class ReplicaProvisioner:
    """Cluster node events -> router replica membership.

    Watches the scaler's node watcher; an ADDED/RUNNING node of the
    serving type gets an engine from ``engine_factory`` and joins the
    router, a DELETED one leaves (drain-first).  This is the piece a
    k8s/ray deployment replaces with real pod/actor startup — the
    in-memory version makes the whole autoscale loop testable in one
    process.
    """

    def __init__(
        self,
        router,
        watcher,                       # NodeWatcher
        engine_factory: Callable[[Node], object],
        node_type: str = NodeType.SERVING_REPLICA,
        max_join_attempts: int = 5,
    ):
        self.router = router
        self.watcher = watcher
        self.engine_factory = engine_factory
        self.node_type = node_type
        self.max_join_attempts = int(max_join_attempts)
        # fabric narration: the cluster handing over a node is the
        # first stitched milestone of an autoscale trace
        self.recorder = getattr(router, "recorder", None)
        # nodes whose engine_factory failed transiently, awaiting retry
        # (the watcher's events were already destructively consumed, so
        # losing these here would be permanent capacity loss)
        self._join_retry: Dict[str, tuple] = {}  # name -> (node, tries)

    def _try_join(self, node: Node) -> bool:
        """One join attempt; failures queue the node for later polls.
        ``engine_factory`` now spawns real processes (supervisor seam)
        and can legitimately fail transiently (announce timeout under
        load, connect refusal) — one bad spawn must not strand the node
        NOR abort the rest of the event batch."""
        try:
            engine = self.engine_factory(node)
        except Exception as e:
            _, tries = self._join_retry.get(node.name, (None, 0))
            if tries + 1 >= self.max_join_attempts:
                self._join_retry.pop(node.name, None)
                logger.error(
                    "provisioning replica for node %s failed %d times; "
                    "giving up: %s", node.name, tries + 1, e)
            else:
                self._join_retry[node.name] = (node, tries + 1)
                logger.warning(
                    "provisioning replica for node %s failed "
                    "(attempt %d/%d, retried next poll): %s",
                    node.name, tries + 1, self.max_join_attempts, e)
            return False
        self._join_retry.pop(node.name, None)
        self.router.join_replica(node.name, engine, node=node)
        return True

    def poll(self, timeout: float = 0.01) -> int:
        """Apply pending node events; returns how many were applied."""
        applied = 0
        for name, (node, _) in list(self._join_retry.items()):
            if name not in self.router.replica_names \
                    and self._try_join(node):
                applied += 1
        for event in self.watcher.watch(timeout=timeout):
            node = event.node
            if node.type != self.node_type:
                continue
            joined = node.name in self.router.replica_names
            if event.event_type == NodeEventType.DELETED:
                self._join_retry.pop(node.name, None)
                if joined:
                    self.router.begin_drain(node.name)
                    applied += 1
            elif not joined and not node.is_exited():
                if self.recorder is not None:
                    self.recorder.record("node_create", node=node.name)
                if self._try_join(node):
                    applied += 1
        return applied
