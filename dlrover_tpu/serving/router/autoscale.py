"""Autoscale hooks: router load -> Brain plan -> ScalePlan -> replicas.

The training auto-scaler's loop (speed samples -> Brain optimize ->
ScalePlan -> Scaler), rebuilt for serving:

- the router's :class:`~.metrics.RouterMetrics` windows provide the
  signals (queue depth, TTFT, tokens/sec);
- the DECISION comes from :class:`~dlrover_tpu.brain.serving.
  ServingScalePolicy` — run locally by default, or remotely through a
  ``BrainClient.serving_plan`` query when a Brain deployment is
  configured (same policy code on both paths);
- the EXECUTION is a plain :class:`~dlrover_tpu.master.scaler.base.
  ScalePlan` handed to any cluster ``Scaler`` — the in-memory scheduler
  in tests, pod/actor scalers (scheduler/k8s.py, scheduler/ray.py) in
  deployments;
- the :class:`ReplicaProvisioner` closes the loop: cluster node events
  coming back from the scaler's watcher become router join/leave calls.

Scale-down is drain-first: the victim replica stops taking placements,
finishes its in-flight requests inside the router pump, and only the
DRAINED husk's node is removed from the cluster — no request is ever
cut off by a scale decision.

Every executed scale decision also opens a control-plane **autoscale
trace** (served at ``/traces/autoscale``): marker spans for the
load-window snapshot, the policy verdict and the ScalePlan emission at
decision time, then milestone spans stitched from the flight
recorder's fabric-event vocabulary as the decision materializes —
``node_create`` (provisioner) → ``worker_spawn`` (supervisor) →
``hello_join`` (router) → ``probation`` (if damped) →
``first_placement`` (the new replica takes traffic); scale-downs trace
``drain`` → ``retired`` per victim.  Each milestone span runs from the
previous milestone, so the trace reads as "where did the 9 seconds
between 'queue too deep' and 'new replica serving' actually go".
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from dlrover_tpu.brain.serving import ServingScalePolicy, ServingSignal
from dlrover_tpu.common.constants import NodeEventType, NodeType
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.node import Node, NodeGroupResource, NodeResource
from dlrover_tpu.master.scaler.base import ScalePlan, Scaler


class ServingAutoScaler:
    """Periodic replica-count control loop, driven by router steps."""

    def __init__(
        self,
        router,
        scaler: Scaler,
        policy: Optional[ServingScalePolicy] = None,
        brain=None,                    # BrainClient-like (serving_plan)
        job_name: str = "serving",
        node_type: str = NodeType.SERVING_REPLICA,
        node_resource: Optional[NodeResource] = None,
        decide_interval: float = 5.0,
        cooldown: float = 15.0,
        min_samples: int = 3,
    ):
        self.router = router
        self.scaler = scaler
        self.policy = policy or ServingScalePolicy()
        self.brain = brain
        self.job_name = job_name
        self.node_type = node_type
        self.node_resource = node_resource or NodeResource()
        self.decide_interval = float(decide_interval)
        self.cooldown = float(cooldown)
        self.min_samples = int(min_samples)
        self._samples: List[ServingSignal] = []
        self._last_sample = 0.0
        self._last_scale = 0.0
        self._next_node_id = 0
        # replicas this autoscaler asked to drain, by name -> their Node
        self._pending_removal: Dict[str, Optional[Node]] = {}
        self.plans: List[ScalePlan] = []
        # control-plane tracing: one autoscale trace per executed
        # decision, milestones stitched from flight-recorder events
        self.tracer = getattr(router, "tracer", None)
        self.recorder = getattr(router, "recorder", None)
        self._scale_trace: Optional[dict] = None
        self._event_cursor = (
            self.recorder.last_seq if self.recorder is not None else -1)
        router.autoscaler = self

    # -------------------------------------------------------- sampling
    def on_step(self, now: Optional[float] = None) -> None:
        """Router pump hook: sample the windows, maybe act."""
        now = time.monotonic() if now is None else now
        if now - self._last_sample >= self.decide_interval / max(
            1, self.min_samples
        ):
            self._last_sample = now
            m = self.router.metrics
            self._samples.append(ServingSignal(
                queue_depth=m.queue_depth_mean(now),
                ttft_seconds=m.ttft_mean(now),
                tokens_per_sec=m.tokens_per_second(now),
            ))
            del self._samples[: -8 * self.min_samples]
        self._stitch_scale_trace()
        self._finish_deaths()
        self._finish_drains()
        if now - self._last_scale >= self.cooldown:
            self.maybe_scale(now)

    # -------------------------------------------------------- deciding
    def desired_replicas(self, current: int) -> int:
        if len(self._samples) < self.min_samples:
            return current
        samples = self._samples[-self.min_samples:]
        if self.brain is not None:
            try:
                got = self.brain.serving_plan(
                    job_name=self.job_name,
                    current_replicas=current,
                    min_replicas=self.policy.min_replicas,
                    max_replicas=self.policy.max_replicas,
                    queue_high=self.policy.queue_high,
                    queue_low=self.policy.queue_low,
                    ttft_high=self.policy.ttft_high,
                    samples=[s.to_dict() for s in samples],
                )
                if got:
                    return int(got)
            except Exception as e:  # Brain outage must not stop serving
                logger.warning("brain serving_plan failed: %s", e)
        return self.policy.decide(samples, current)

    def maybe_scale(self, now: Optional[float] = None
                    ) -> Optional[ScalePlan]:
        """One control decision; returns the executed plan, if any."""
        now = time.monotonic() if now is None else now
        current = self.router.manager.up_count()
        if current == 0 and self.router.gateway.depth() == 0:
            return None
        desired = self.desired_replicas(max(current, 1))
        self._report_brain(current)
        if desired > current:
            plan = self._scale_up(desired)
        elif desired < current:
            plan = self._scale_down(current - desired)
        else:
            return None
        if plan is not None:
            # trace BEFORE clearing samples: the load-window snapshot
            # span wants the evidence the decision was made from
            self._trace_decision(now, current, desired, plan)
        self._last_scale = now
        self._samples.clear()  # decide from post-change evidence only
        return plan

    # ------------------------------------------------------- executing
    def _scale_up(self, desired: int) -> ScalePlan:
        # ``desired`` counts UP replicas, but the cluster group still
        # contains draining replicas' nodes until their removal plans
        # land — the group count must include them or the scaler sees
        # "already at count" and silently adds nothing (or worse,
        # shrinks an arbitrary node the policy never chose)
        count = desired + len(self._pending_removal)
        plan = ScalePlan(node_group_resources={
            self.node_type: NodeGroupResource(
                count=count, node_resource=self.node_resource)
        })
        logger.info(
            "serving scale-up: -> %d replicas (+%d draining)",
            desired, len(self._pending_removal))
        self.plans.append(plan)
        self.scaler.scale(plan)
        return plan

    def _scale_down(self, n: int) -> Optional[ScalePlan]:
        """Drain-first: pick the least-loaded UP replicas and stop
        placements; node removal happens when each one empties."""
        victims = sorted(
            (
                h for h in self.router.manager.schedulable()
                if h.name not in self._pending_removal
            ),
            key=lambda h: len(h.inflight),
        )[:n]
        if not victims:
            return None
        for handle in victims:
            logger.info(
                "serving scale-down: draining replica %s "
                "(%d in-flight)", handle.name, len(handle.inflight),
            )
            self.router.begin_drain(handle.name)
            self._pending_removal[handle.name] = handle.node
        return ScalePlan()  # removal plan follows once drained

    def _finish_deaths(self) -> None:
        """Retire DEAD replicas' cluster nodes.  Without this the
        crashed replica's node stays 'alive' in the cluster, every
        future scale-up count matches the stale node count and adds
        nothing — a crash would permanently cap the fleet.  A drain
        victim dying mid-drain also lands here: its _pending_removal
        entry must not inflate scale-up counts forever."""
        while self.router.dead:
            rec = self.router.dead.popleft()
            node = self._pending_removal.pop(rec.name, None) or rec.node
            if node is not None:
                plan = ScalePlan(remove_nodes=[node])
                self.plans.append(plan)
                self.scaler.scale(plan)
                logger.info(
                    "serving replica %s died; removed its node %s",
                    rec.name, node.name)

    def _finish_drains(self) -> None:
        """Retire drained replicas: emit the remove_nodes plan."""
        if not self._pending_removal:
            return
        for handle in list(self.router.drained):
            if handle.name not in self._pending_removal:
                continue  # drained by someone else; not ours to retire
            node = self._pending_removal.pop(handle.name)
            self.router.drained.remove(handle)
            if node is not None:
                plan = ScalePlan(remove_nodes=[node])
                self.plans.append(plan)
                self.scaler.scale(plan)
                logger.info(
                    "serving scale-down: removed node %s", node.name)

    def _report_brain(self, current: int) -> None:
        if self.brain is None or not self._samples:
            return
        s = self._samples[-1]
        try:
            self.brain.record_serving(
                job_uuid=self.job_name, job_name=self.job_name,
                replicas=current, queue_depth=s.queue_depth,
                ttft_seconds=s.ttft_seconds,
                tokens_per_sec=s.tokens_per_sec,
            )
        except Exception:  # telemetry only; never blocks the loop
            pass

    # ------------------------------------------- control-plane tracing
    # the stage each fabric event advances a NEW replica to; spans run
    # from the previous milestone so stage-to-stage latency is visible
    _UP_STAGES = {
        "node_create": "node_create",
        "worker_spawn": "worker_spawn",
        "replica_join": "hello_join",
        "replica_first_placement": "first_placement",
    }

    def _trace_decision(self, now: float, current: int, desired: int,
                        plan: ScalePlan) -> None:
        """Open the decision's autoscale trace (always sampled:
        control-plane traces are one-per-decision, never hot-path)."""
        if self.tracer is None:
            return
        direction = "up" if desired > current else "down"
        st = self._scale_trace
        if st is not None and st["direction"] == direction \
                and st["desired"] == desired:
            # the same episode re-planned while its replicas are still
            # materializing (short cooldowns re-decide every round):
            # ONE trace per episode, with the replan count on the root
            st["plans"] += 1
            st["root"].attrs["plans"] = st["plans"]
            return
        self._close_scale_trace("superseded", now)
        tracer = self.tracer
        root = tracer.start_trace(
            "autoscale", now=now, always_sample=True,
            current=current, desired=desired, direction=direction)
        sample = self._samples[-1] if self._samples else None
        window_attrs = {} if sample is None else {
            "queue_depth": round(sample.queue_depth, 3),
            "ttft_seconds": round(sample.ttft_seconds, 6),
            "tokens_per_sec": round(sample.tokens_per_sec, 3),
        }
        tracer.start_span(
            root, "load_window", now=now,
            samples=len(self._samples), **window_attrs).finish(now)
        tracer.start_span(
            root, "policy", now=now, current=current, desired=desired,
            source="brain" if self.brain is not None else "local",
        ).finish(now)
        tracer.start_span(
            root, "scale_plan", now=now,
            count=sum(
                g.count for g in plan.node_group_resources.values()),
            remove_nodes=len(plan.remove_nodes),
        ).finish(now)
        self._scale_trace = {
            "root": root, "direction": direction, "desired": desired,
            "decided_at": now, "plans": 1,
            # replicas that existed at decision time: anything ELSE
            # joining afterwards is this decision materializing
            "known": set(self.router.replica_names),
            "stage_t": {}, "stages": {}, "placed": set(),
            "expected_new": max(0, desired - current),
            "victims": set(self._pending_removal),
            "retired": set(),
        }

    def _stitch_scale_trace(self) -> None:
        """Consume new flight-recorder events into the open autoscale
        trace — the cross-component stitch: provisioner node creation,
        supervisor worker spawn, router join/probation/first placement
        all narrate through the recorder, and this turns their
        timestamps into milestone spans."""
        if self.recorder is None:
            return
        events = self.recorder.events_since(self._event_cursor)
        if events:
            self._event_cursor = max(e["seq"] for e in events)
        st = self._scale_trace
        if st is None or self.tracer is None:
            return
        for event in events:
            if st["direction"] == "up":
                self._stitch_up(st, event)
            else:
                self._stitch_down(st, event)
            if self._scale_trace is None:  # closed mid-batch
                return

    def _stitch_up(self, st: dict, event: dict) -> None:
        kind = str(event.get("kind"))
        name = event.get("replica") or event.get("worker") \
            or event.get("node")
        if not name or name in st["known"]:
            return
        t = float(event.get("t", st["decided_at"]))
        if kind == "replica_probation":
            # crash-loop damping delayed this replica's first traffic:
            # the probation span runs join -> scheduled release
            self.tracer.start_span(
                st["root"], "probation", now=t, replica=name,
            ).finish(max(t, float(event.get("until", t))))
            return
        stage = self._UP_STAGES.get(kind)
        if stage is None or stage in st["stages"].setdefault(name, set()):
            return
        start = st["stage_t"].get(name, st["decided_at"])
        # clamp: stitched events may mix the caller's synthetic clock
        # with real monotonic stamps; a milestone never runs backwards
        end = max(t, start)
        self.tracer.start_span(
            st["root"], stage, now=start, replica=name).finish(end)
        st["stages"][name].add(stage)
        st["stage_t"][name] = end
        if stage == "first_placement":
            st["placed"].add(name)
            if len(st["placed"]) >= st["expected_new"]:
                self._close_scale_trace("ok", end)

    def _stitch_down(self, st: dict, event: dict) -> None:
        kind = str(event.get("kind"))
        name = event.get("replica")
        if name not in st["victims"]:
            return
        t = float(event.get("t", st["decided_at"]))
        # a victim dying MID-DRAIN still terminates its leg of the
        # decision (the node is retired through _finish_deaths) — the
        # trace must close rather than sit active forever
        stage = {"replica_drain": "drain",
                 "replica_retired": "retired",
                 "replica_dead": "retired"}.get(kind)
        if stage is None or stage in st["stages"].setdefault(name, set()):
            return
        start = st["stage_t"].get(name, st["decided_at"])
        end = max(t, start)
        attrs = {"replica": name}
        if kind == "replica_dead":
            attrs["died_mid_drain"] = True
        self.tracer.start_span(
            st["root"], stage, now=start, **attrs).finish(end)
        st["stages"][name].add(stage)
        st["stage_t"][name] = end
        if stage == "retired":
            st["retired"].add(name)
            if st["retired"] >= st["victims"]:
                self._close_scale_trace("ok", end)

    def _close_scale_trace(self, status: str,
                           now: Optional[float] = None) -> None:
        st = self._scale_trace
        if st is None or self.tracer is None:
            return
        self._scale_trace = None
        end = max(st["decided_at"],
                  st["decided_at"] if now is None else now)
        self.tracer.finish_trace(st["root"], now=end, status=status)


class ReplicaProvisioner:
    """Cluster node events -> router replica membership.

    Watches the scaler's node watcher; an ADDED/RUNNING node of the
    serving type gets an engine from ``engine_factory`` and joins the
    router, a DELETED one leaves (drain-first).  This is the piece a
    k8s/ray deployment replaces with real pod/actor startup — the
    in-memory version makes the whole autoscale loop testable in one
    process.
    """

    def __init__(
        self,
        router,
        watcher,                       # NodeWatcher
        engine_factory: Callable[[Node], object],
        node_type: str = NodeType.SERVING_REPLICA,
        max_join_attempts: int = 5,
    ):
        self.router = router
        self.watcher = watcher
        self.engine_factory = engine_factory
        self.node_type = node_type
        self.max_join_attempts = int(max_join_attempts)
        # fabric narration: the cluster handing over a node is the
        # first stitched milestone of an autoscale trace
        self.recorder = getattr(router, "recorder", None)
        # nodes whose engine_factory failed transiently, awaiting retry
        # (the watcher's events were already destructively consumed, so
        # losing these here would be permanent capacity loss)
        self._join_retry: Dict[str, tuple] = {}  # name -> (node, tries)

    def _try_join(self, node: Node) -> bool:
        """One join attempt; failures queue the node for later polls.
        ``engine_factory`` now spawns real processes (supervisor seam)
        and can legitimately fail transiently (announce timeout under
        load, connect refusal) — one bad spawn must not strand the node
        NOR abort the rest of the event batch."""
        try:
            engine = self.engine_factory(node)
        except Exception as e:
            _, tries = self._join_retry.get(node.name, (None, 0))
            if tries + 1 >= self.max_join_attempts:
                self._join_retry.pop(node.name, None)
                logger.error(
                    "provisioning replica for node %s failed %d times; "
                    "giving up: %s", node.name, tries + 1, e)
            else:
                self._join_retry[node.name] = (node, tries + 1)
                logger.warning(
                    "provisioning replica for node %s failed "
                    "(attempt %d/%d, retried next poll): %s",
                    node.name, tries + 1, self.max_join_attempts, e)
            return False
        self._join_retry.pop(node.name, None)
        self.router.join_replica(node.name, engine, node=node)
        return True

    def poll(self, timeout: float = 0.01) -> int:
        """Apply pending node events; returns how many were applied."""
        applied = 0
        for name, (node, _) in list(self._join_retry.items()):
            if name not in self.router.replica_names \
                    and self._try_join(node):
                applied += 1
        for event in self.watcher.watch(timeout=timeout):
            node = event.node
            if node.type != self.node_type:
                continue
            joined = node.name in self.router.replica_names
            if event.event_type == NodeEventType.DELETED:
                self._join_retry.pop(node.name, None)
                if joined:
                    self.router.begin_drain(node.name)
                    applied += 1
            elif not joined and not node.is_exited():
                if self.recorder is not None:
                    self.recorder.record("node_create", node=node.name)
                if self._try_join(node):
                    applied += 1
        return applied
