"""Autoscale hooks: router load -> Brain plan -> ScalePlan -> replicas.

The training auto-scaler's loop (speed samples -> Brain optimize ->
ScalePlan -> Scaler), rebuilt for serving:

- the router's :class:`~.metrics.RouterMetrics` windows provide the
  signals (queue depth, TTFT, tokens/sec);
- the DECISION comes from :class:`~dlrover_tpu.brain.serving.
  ServingScalePolicy` — run locally by default, or remotely through a
  ``BrainClient.serving_plan`` query when a Brain deployment is
  configured (same policy code on both paths);
- the EXECUTION is a plain :class:`~dlrover_tpu.master.scaler.base.
  ScalePlan` handed to any cluster ``Scaler`` — the in-memory scheduler
  in tests, pod/actor scalers (scheduler/k8s.py, scheduler/ray.py) in
  deployments;
- the :class:`ReplicaProvisioner` closes the loop: cluster node events
  coming back from the scaler's watcher become router join/leave calls.

Scale-down is drain-first: the victim replica stops taking placements,
finishes its in-flight requests inside the router pump, and only the
DRAINED husk's node is removed from the cluster — no request is ever
cut off by a scale decision.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from dlrover_tpu.brain.serving import ServingScalePolicy, ServingSignal
from dlrover_tpu.common.constants import NodeEventType, NodeType
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.node import Node, NodeGroupResource, NodeResource
from dlrover_tpu.master.scaler.base import ScalePlan, Scaler


class ServingAutoScaler:
    """Periodic replica-count control loop, driven by router steps."""

    def __init__(
        self,
        router,
        scaler: Scaler,
        policy: Optional[ServingScalePolicy] = None,
        brain=None,                    # BrainClient-like (serving_plan)
        job_name: str = "serving",
        node_type: str = NodeType.SERVING_REPLICA,
        node_resource: Optional[NodeResource] = None,
        decide_interval: float = 5.0,
        cooldown: float = 15.0,
        min_samples: int = 3,
    ):
        self.router = router
        self.scaler = scaler
        self.policy = policy or ServingScalePolicy()
        self.brain = brain
        self.job_name = job_name
        self.node_type = node_type
        self.node_resource = node_resource or NodeResource()
        self.decide_interval = float(decide_interval)
        self.cooldown = float(cooldown)
        self.min_samples = int(min_samples)
        self._samples: List[ServingSignal] = []
        self._last_sample = 0.0
        self._last_scale = 0.0
        self._next_node_id = 0
        # replicas this autoscaler asked to drain, by name -> their Node
        self._pending_removal: Dict[str, Optional[Node]] = {}
        self.plans: List[ScalePlan] = []
        router.autoscaler = self

    # -------------------------------------------------------- sampling
    def on_step(self, now: Optional[float] = None) -> None:
        """Router pump hook: sample the windows, maybe act."""
        now = time.monotonic() if now is None else now
        if now - self._last_sample >= self.decide_interval / max(
            1, self.min_samples
        ):
            self._last_sample = now
            m = self.router.metrics
            self._samples.append(ServingSignal(
                queue_depth=m.queue_depth_mean(now),
                ttft_seconds=m.ttft_mean(now),
                tokens_per_sec=m.tokens_per_second(now),
            ))
            del self._samples[: -8 * self.min_samples]
        self._finish_deaths()
        self._finish_drains()
        if now - self._last_scale >= self.cooldown:
            self.maybe_scale(now)

    # -------------------------------------------------------- deciding
    def desired_replicas(self, current: int) -> int:
        if len(self._samples) < self.min_samples:
            return current
        samples = self._samples[-self.min_samples:]
        if self.brain is not None:
            try:
                got = self.brain.serving_plan(
                    job_name=self.job_name,
                    current_replicas=current,
                    min_replicas=self.policy.min_replicas,
                    max_replicas=self.policy.max_replicas,
                    queue_high=self.policy.queue_high,
                    queue_low=self.policy.queue_low,
                    ttft_high=self.policy.ttft_high,
                    samples=[s.to_dict() for s in samples],
                )
                if got:
                    return int(got)
            except Exception as e:  # Brain outage must not stop serving
                logger.warning("brain serving_plan failed: %s", e)
        return self.policy.decide(samples, current)

    def maybe_scale(self, now: Optional[float] = None
                    ) -> Optional[ScalePlan]:
        """One control decision; returns the executed plan, if any."""
        now = time.monotonic() if now is None else now
        current = self.router.manager.up_count()
        if current == 0 and self.router.gateway.depth() == 0:
            return None
        desired = self.desired_replicas(max(current, 1))
        self._report_brain(current)
        if desired > current:
            plan = self._scale_up(desired)
        elif desired < current:
            plan = self._scale_down(current - desired)
        else:
            return None
        self._last_scale = now
        self._samples.clear()  # decide from post-change evidence only
        return plan

    # ------------------------------------------------------- executing
    def _scale_up(self, desired: int) -> ScalePlan:
        # ``desired`` counts UP replicas, but the cluster group still
        # contains draining replicas' nodes until their removal plans
        # land — the group count must include them or the scaler sees
        # "already at count" and silently adds nothing (or worse,
        # shrinks an arbitrary node the policy never chose)
        count = desired + len(self._pending_removal)
        plan = ScalePlan(node_group_resources={
            self.node_type: NodeGroupResource(
                count=count, node_resource=self.node_resource)
        })
        logger.info(
            "serving scale-up: -> %d replicas (+%d draining)",
            desired, len(self._pending_removal))
        self.plans.append(plan)
        self.scaler.scale(plan)
        return plan

    def _scale_down(self, n: int) -> Optional[ScalePlan]:
        """Drain-first: pick the least-loaded UP replicas and stop
        placements; node removal happens when each one empties."""
        victims = sorted(
            (
                h for h in self.router.manager.schedulable()
                if h.name not in self._pending_removal
            ),
            key=lambda h: len(h.inflight),
        )[:n]
        if not victims:
            return None
        for handle in victims:
            logger.info(
                "serving scale-down: draining replica %s "
                "(%d in-flight)", handle.name, len(handle.inflight),
            )
            self.router.begin_drain(handle.name)
            self._pending_removal[handle.name] = handle.node
        return ScalePlan()  # removal plan follows once drained

    def _finish_deaths(self) -> None:
        """Retire DEAD replicas' cluster nodes.  Without this the
        crashed replica's node stays 'alive' in the cluster, every
        future scale-up count matches the stale node count and adds
        nothing — a crash would permanently cap the fleet.  A drain
        victim dying mid-drain also lands here: its _pending_removal
        entry must not inflate scale-up counts forever."""
        while self.router.dead:
            rec = self.router.dead.popleft()
            node = self._pending_removal.pop(rec.name, None) or rec.node
            if node is not None:
                plan = ScalePlan(remove_nodes=[node])
                self.plans.append(plan)
                self.scaler.scale(plan)
                logger.info(
                    "serving replica %s died; removed its node %s",
                    rec.name, node.name)

    def _finish_drains(self) -> None:
        """Retire drained replicas: emit the remove_nodes plan."""
        if not self._pending_removal:
            return
        for handle in list(self.router.drained):
            if handle.name not in self._pending_removal:
                continue  # drained by someone else; not ours to retire
            node = self._pending_removal.pop(handle.name)
            self.router.drained.remove(handle)
            if node is not None:
                plan = ScalePlan(remove_nodes=[node])
                self.plans.append(plan)
                self.scaler.scale(plan)
                logger.info(
                    "serving scale-down: removed node %s", node.name)

    def _report_brain(self, current: int) -> None:
        if self.brain is None or not self._samples:
            return
        s = self._samples[-1]
        try:
            self.brain.record_serving(
                job_uuid=self.job_name, job_name=self.job_name,
                replicas=current, queue_depth=s.queue_depth,
                ttft_seconds=s.ttft_seconds,
                tokens_per_sec=s.tokens_per_sec,
            )
        except Exception:  # telemetry only; never blocks the loop
            pass


class ReplicaProvisioner:
    """Cluster node events -> router replica membership.

    Watches the scaler's node watcher; an ADDED/RUNNING node of the
    serving type gets an engine from ``engine_factory`` and joins the
    router, a DELETED one leaves (drain-first).  This is the piece a
    k8s/ray deployment replaces with real pod/actor startup — the
    in-memory version makes the whole autoscale loop testable in one
    process.
    """

    def __init__(
        self,
        router,
        watcher,                       # NodeWatcher
        engine_factory: Callable[[Node], object],
        node_type: str = NodeType.SERVING_REPLICA,
        max_join_attempts: int = 5,
    ):
        self.router = router
        self.watcher = watcher
        self.engine_factory = engine_factory
        self.node_type = node_type
        self.max_join_attempts = int(max_join_attempts)
        # nodes whose engine_factory failed transiently, awaiting retry
        # (the watcher's events were already destructively consumed, so
        # losing these here would be permanent capacity loss)
        self._join_retry: Dict[str, tuple] = {}  # name -> (node, tries)

    def _try_join(self, node: Node) -> bool:
        """One join attempt; failures queue the node for later polls.
        ``engine_factory`` now spawns real processes (supervisor seam)
        and can legitimately fail transiently (announce timeout under
        load, connect refusal) — one bad spawn must not strand the node
        NOR abort the rest of the event batch."""
        try:
            engine = self.engine_factory(node)
        except Exception as e:
            _, tries = self._join_retry.get(node.name, (None, 0))
            if tries + 1 >= self.max_join_attempts:
                self._join_retry.pop(node.name, None)
                logger.error(
                    "provisioning replica for node %s failed %d times; "
                    "giving up: %s", node.name, tries + 1, e)
            else:
                self._join_retry[node.name] = (node, tries + 1)
                logger.warning(
                    "provisioning replica for node %s failed "
                    "(attempt %d/%d, retried next poll): %s",
                    node.name, tries + 1, self.max_join_attempts, e)
            return False
        self._join_retry.pop(node.name, None)
        self.router.join_replica(node.name, engine, node=node)
        return True

    def poll(self, timeout: float = 0.01) -> int:
        """Apply pending node events; returns how many were applied."""
        applied = 0
        for name, (node, _) in list(self._join_retry.items()):
            if name not in self.router.replica_names \
                    and self._try_join(node):
                applied += 1
        for event in self.watcher.watch(timeout=timeout):
            node = event.node
            if node.type != self.node_type:
                continue
            joined = node.name in self.router.replica_names
            if event.event_type == NodeEventType.DELETED:
                self._join_retry.pop(node.name, None)
                if joined:
                    self.router.begin_drain(node.name)
                    applied += 1
            elif not joined and not node.is_exited():
                if self._try_join(node):
                    applied += 1
        return applied
