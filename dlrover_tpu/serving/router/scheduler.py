"""Continuous-batching placement: micro-batches per replica under the
KV-block budget, prefix-cache-aware, least-loaded.

Each router round forms one micro-batch per replica: the requests placed
on a replica in the same round reach its engine together, and the
engine's own bucketed group-prefill turns them into one dispatch (the
Orca/vLLM admission model, one level up).  Placement is gated by REAL
capacity — a free decode slot AND enough free KV blocks for the
request's whole lifetime — so the router never over-admits into a
replica's HBM budget; a request no replica can hold right now simply
stays queued.

Placement preference order:

1. **prefix routing table** — a replica currently ADVERTISING the
   request's prefix head as hot (serving/prefixcache.PrefixRoutingTable,
   fed from STATS) gets the request: its paged pool is KNOWN to hold
   the shared blocks right now, so the prefill maps them for free
   (copy-on-write sharing).  Requires the scheduler's ``block_size``
   to match the engines' — heads are depth-one block digests;
2. **prefix affinity** — else a replica that recently served the same
   leading prompt tokens (its cache very LIKELY still holds them);
3. **least loaded** — otherwise the replica with the most free slots,
   ties broken by free KV blocks.

Incremental placement index (``incremental=True``, the event step
engine's default): the historical implementation rescanned **every
replica for every queued request in the window on every step** —
O(replicas x queued) even when nothing changed, which continuous-
batching systems (Orca, vLLM) show becomes THE ceiling once decode
steps drop under a millisecond.  The index kills that product three
ways, none of them changing placement semantics:

- **capacity generation**: replica capacities are read once per round
  (O(replicas)) and compared against the previous round; the
  generation bumps only when some replica's free slots/blocks GREW
  (join, completion, cancel, STATS refresh).  A request that found no
  home is stamped with the generation it was refused at and skipped —
  O(1) — until capacity actually grows, because nothing else can
  change the verdict (capacity only shrinks mid-round);
- **candidate heap**: fitting candidates come off a max-heap keyed
  (slots_free, blocks_free) with lazy invalidation, so the common
  first-candidate-fits case costs O(log replicas) instead of a scan;
  walking past non-fitting entries reproduces exactly the legacy
  "max over fitting candidates" pick (iterating in descending key
  order, the first fit IS that max);
- **round short-circuit**: a round that placed nothing records the
  (queue generation, capacity generation) pair; while both are
  unchanged the window scan itself is skipped (``rounds_skipped``).

``capacity_evals`` counts (request x replica) fit evaluations — the
regression surface: on idle entries it must NOT scale with
replicas x queued (pinned by tests/test_step_engine.py and the
``serving_sched_capacity_evals_total`` gauge).

Tie-break note: the legacy scan breaks (slots, blocks) ties by replica
LIST order (manager insertion), the heap by replica name — both are
deterministic, and placement distribution (not request outcome) is the
only thing that can differ.
"""

from __future__ import annotations

import hashlib
import heapq
from collections import OrderedDict
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from dlrover_tpu.serving.prefixcache import PrefixRoutingTable, head_key
from dlrover_tpu.serving.router.gateway import RequestGateway, ServingRequest


class ContinuousBatchScheduler:
    """Stateless placement plus a small per-replica prefix-affinity LRU
    and (``incremental=True``) the placement index above."""

    def __init__(
        self,
        block_size: int = 16,
        schedule_window: int = 64,
        prefix_tokens: int = 32,
        affinity_cap: int = 512,
        incremental: bool = True,
        suspect_weight: float = 0.25,
    ):
        self.block_size = int(block_size)
        self.schedule_window = int(schedule_window)
        self.prefix_tokens = int(prefix_tokens)
        self.affinity_cap = int(affinity_cap)
        # gray-zone demotion: a phi-suspect (demoted) replica's
        # capacity is multiplied by this weight in every ORDERING
        # comparison — least-loaded ranking, the candidate heap, the
        # affinity pick — so new work prefers healthy replicas.  FIT
        # checks stay on REAL capacity: a demoted replica still takes
        # work nothing else can hold (demotion, never starvation), and
        # since ordering can't change whether a request fits, a
        # suspicion flip needs no index invalidation — the heap is
        # rebuilt from the live ledger every round anyway
        if not 0.0 <= float(suspect_weight) <= 1.0:
            raise ValueError(
                f"suspect_weight {suspect_weight} not in [0, 1]")
        self.suspect_weight = float(suspect_weight)
        # the step-engine seam: ServingRouter(step_engine=...) sets
        # this to match (sweep keeps the historical full rescan)
        self.incremental = bool(incremental)
        # replica name -> LRU of prefix keys it has recently served
        self._affinity: Dict[str, "OrderedDict[bytes, None]"] = {}
        # reverse index: prefix key -> replica names that recently
        # served it (bounded by the sum of the per-replica LRUs) — the
        # affinity probe becomes a dict hit instead of a scan of every
        # candidate's LRU
        self._affinity_index: Dict[bytes, Set[str]] = {}
        # ---- placement index state -----------------------------------
        # last capacity reading per replica, POST-placement (comparing
        # the next round's fresh read against the round-end ledger is
        # what makes "freed capacity" detectable)
        self._last_free: Dict[str, Tuple[float, float]] = {}
        self._cap_gen = 0
        # (queue_gen, cap_gen) of a round that placed nothing — while
        # unchanged, schedule() returns [] without scanning the window
        self._idle_marker: Optional[tuple] = None
        # prefix-head -> replica routing (fed by STATS advertisements
        # via advertise_prefixes; invalidated by forget_replica and by
        # advertisement replacement) — consulted AHEAD of affinity
        self.prefix_table = PrefixRoutingTable()
        # ---- regression counters -------------------------------------
        self.capacity_evals = 0   # (request x replica) fit checks
        self.rounds = 0
        self.rounds_skipped = 0   # short-circuited rounds
        self.route_placements = 0  # placements steered by prefix_table

    # ------------------------------------------------------------ keys
    def prefix_key(self, prompt: np.ndarray) -> Optional[bytes]:
        """Stable digest of the leading prompt tokens; ``None`` for
        prompts shorter than one cache block (nothing reusable)."""
        n = min(self.prefix_tokens, int(prompt.size))
        if n < self.block_size:
            return None
        return hashlib.blake2b(
            np.asarray(prompt[:n], np.int32).tobytes(), digest_size=16
        ).digest()

    def blocks_needed(self, req: ServingRequest) -> int:
        return -(-req.total_len // self.block_size)

    def _need(self, handle, req: ServingRequest) -> float:
        """Per-replica block requirement: the replica's own admission
        formula when it exposes one (bucket padding + speculative slack
        differ per engine), else the block-size default."""
        fn = getattr(handle, "blocks_needed", None)
        if fn is not None:
            n = fn(int(req.prompt.size), int(req.max_new_tokens))
            if n is not None:
                return float(n)
        return float(self.blocks_needed(req))

    def _weight(self, handle) -> float:
        """Ordering weight of one replica: ``suspect_weight`` while
        demoted (gray zone / flap-damping hold), else 1.0."""
        return (self.suspect_weight
                if getattr(handle, "demoted", False) else 1.0)

    # ------------------------------------------------------- schedule
    def schedule(
        self, gateway: RequestGateway, replicas: List,
        now: Optional[float] = None,
    ) -> List[Tuple[object, ServingRequest]]:
        """One placement round: assign queued requests to replicas with
        capacity.  Returns ``(replica_handle, request)`` pairs; the
        requests are already removed from the gateway.  Skips (leaves
        queued) any request no replica can currently hold.  Placed
        requests get a ``placement``-decision stamp on their trace
        (replica, candidate count, affinity hit) at ``now``."""
        self.rounds += 1
        if not replicas:
            return []
        # capacity read, once per replica per round; generation bumps
        # only on GROWTH vs the previous round's post-placement ledger
        free: Dict[str, List[float]] = {}
        grew = False
        for h in replicas:
            s, b = h.slots_free(), h.blocks_free()
            free[h.name] = [s, b]
            last = self._last_free.get(h.name)
            if last is None or s > last[0] or b > last[1]:
                grew = True
        for name in list(self._last_free):
            if name not in free:
                # departed (or probation-hidden) replica: forget it so
                # its return reads as fresh capacity
                del self._last_free[name]
        if grew:
            self._cap_gen += 1
        if not self.incremental:
            placements = self._schedule_scan_all(
                gateway, replicas, free, now)
        else:
            placements = self._schedule_indexed(
                gateway, replicas, free, now)
        # post-placement ledger: next round's growth test must compare
        # against what this round LEFT, or a placement+completion pair
        # inside one step would mask the freed capacity
        for name, f in free.items():
            self._last_free[name] = (f[0], f[1])
        return placements

    def _schedule_scan_all(
        self, gateway, replicas, free, now,
    ) -> List[Tuple[object, ServingRequest]]:
        """The legacy full rescan (step_engine="sweep"): every queued
        request in the window probes every replica, every round."""
        placements: List[Tuple[object, ServingRequest]] = []
        for req in gateway.schedule_scan(self.schedule_window):
            if not gateway.tenant_can_place(req):
                # tenant at max_inflight: stays queued — a per-tenant
                # cap, not a capacity fact, so no blocked-gen marking
                # (the gateway bumps queue_gen when the tenant drains)
                continue
            self.capacity_evals += len(replicas)
            cands = [
                h for h in replicas
                if free[h.name][0] > 0
                and free[h.name][1] >= self._need(h, req)
            ]
            if not cands:
                continue  # stays queued; later (smaller) requests may fit
            key = self.prefix_key(req.prompt)
            affinity_hit = False
            route_hit = False
            routed = self.prefix_table.lookup(
                head_key(req.prompt, self.block_size))
            if routed is not None:
                # the routing table KNOWS this head's blocks are
                # resident there right now — stronger than affinity's
                # "recently served", so it wins when the target fits
                target = [h for h in cands if h.name == routed]
                if target:
                    cands = target
                    affinity_hit = True
                    route_hit = True
            if not route_hit and key is not None:
                affine = [
                    h for h in cands
                    if key in self._affinity.get(h.name, ())
                ]
                if affine:
                    cands = affine
                    affinity_hit = True
            best = max(
                cands,
                key=lambda h: (free[h.name][0] * self._weight(h),
                               free[h.name][1] * self._weight(h)),
            )
            placed = self._commit(gateway, placements, free, best, req,
                                  len(cands), affinity_hit, now)
            if placed and route_hit:
                self.route_placements += 1
        return placements

    def _schedule_indexed(
        self, gateway, replicas, free, now,
    ) -> List[Tuple[object, ServingRequest]]:
        """The incremental path: blocked-generation skip + lazy
        candidate max-heap (see module docstring)."""
        queue_gen = getattr(gateway, "queue_gen", None)
        marker = (queue_gen, self._cap_gen)
        if queue_gen is not None and self._idle_marker == marker:
            self.rounds_skipped += 1
            return []
        by_name = {h.name: h for h in replicas}
        # demotion weights, read once per round: ordering keys are
        # weighted so suspects sink, while fit checks below stay on
        # the REAL ledger values
        wt = {name: self._weight(by_name[name]) for name in free}
        # max-heap by weighted (slots, blocks), name tiebreak; entries
        # are invalidated lazily by comparing against the live ledger
        heap = [
            (-f[0] * wt[name], -f[1] * wt[name], name)
            for name, f in free.items() if f[0] > 0
        ]
        heapq.heapify(heap)
        placements: List[Tuple[object, ServingRequest]] = []
        for req in gateway.schedule_scan(self.schedule_window):
            if req.sched_blocked_gen == self._cap_gen:
                continue  # nothing grew since every replica refused it
            if not gateway.tenant_can_place(req):
                # per-tenant max_inflight, not replica capacity: no
                # blocked-gen marking — the tenant's next completion
                # (not capacity growth) unblocks it, and the gateway's
                # terminal hook bumps queue_gen for exactly that case
                continue
            key = self.prefix_key(req.prompt)
            best = None
            affinity_hit = False
            route_hit = False
            cand_count = 0
            routed = self.prefix_table.lookup(
                head_key(req.prompt, self.block_size))
            if routed is not None:
                # routed replica wins when it fits (resident blocks
                # beat probabilistic affinity); free.get covers a
                # routed name that is dead or hidden this round
                f = free.get(routed)
                if f is not None and f[0] > 0:
                    self.capacity_evals += 1
                    if f[1] >= self._need(by_name[routed], req):
                        best = by_name[routed]
                        affinity_hit = True
                        route_hit = True
                        cand_count = 1
            if best is None and key is not None:
                affine = self._affinity_index.get(key)
                if affine:
                    fitting = []
                    for name in affine:
                        f = free.get(name)
                        if f is None or f[0] <= 0:
                            continue
                        self.capacity_evals += 1
                        if f[1] >= self._need(by_name[name], req):
                            fitting.append((f[0] * wt[name],
                                            f[1] * wt[name], name))
                    if fitting:
                        best = by_name[max(fitting)[2]]
                        affinity_hit = True
                        cand_count = len(fitting)
            if best is None:
                # pop candidates in descending (slots, blocks) order;
                # the first FITTING one is exactly the legacy "max
                # over fitting candidates" pick
                skipped: List[tuple] = []
                while heap:
                    neg_s, neg_b, name = heapq.heappop(heap)
                    f = free.get(name)
                    if f is None or f[0] <= 0 or \
                            (-neg_s, -neg_b) != (f[0] * wt[name],
                                                 f[1] * wt[name]):
                        continue  # stale entry; a fresh one exists
                    self.capacity_evals += 1
                    cand_count += 1
                    if f[1] >= self._need(by_name[name], req):
                        best = by_name[name]
                        skipped.append((neg_s, neg_b, name))
                        break
                    skipped.append((neg_s, neg_b, name))
                for entry in skipped:
                    heapq.heappush(heap, entry)
            if best is None:
                req.sched_blocked_gen = self._cap_gen
                continue
            placed = self._commit(
                gateway, placements, free, best, req,
                cand_count, affinity_hit, now)
            if placed:
                if route_hit:
                    self.route_placements += 1
                f = free[best.name]
                if f[0] > 0:
                    w = wt[best.name]
                    heapq.heappush(
                        heap, (-f[0] * w, -f[1] * w, best.name))
        self._idle_marker = marker if not placements else None
        return placements

    def _commit(self, gateway, placements, free, best, req,
                cand_count: int, affinity_hit: bool, now) -> bool:
        """Shared placement commit: remove from the gateway, charge the
        round-local ledger, remember affinity, stamp the trace."""
        if not gateway.remove(req):
            return False  # expired/cancelled between scan and placement
        f = free[best.name]
        f[0] -= 1
        f[1] -= self._need(best, req)
        key = self.prefix_key(req.prompt)
        if key is not None:
            self._remember(best.name, key)
        if req.trace is not None:
            # the placement DECISION span: queue wait ends here and
            # the per-replica attempt begins, carrying why this
            # replica won (affinity vs load) and how long the
            # request waited (the histogram's per-trace twin)
            extra = {} if now is None else {
                "queued_s": round(
                    max(0.0, now - req.enqueued_at), 6)}
            req.trace.placed(
                getattr(best, "name", "?"), now=now,
                candidates=cand_count, affinity=affinity_hit,
                **extra)
        placements.append((best, req))
        return True

    def _remember(self, replica: str, key: bytes) -> None:
        lru = self._affinity.setdefault(replica, OrderedDict())
        lru[key] = None
        lru.move_to_end(key)
        self._affinity_index.setdefault(key, set()).add(replica)
        while len(lru) > self.affinity_cap:
            old, _ = lru.popitem(last=False)
            self._unindex(old, replica)

    def _unindex(self, key: bytes, replica: str) -> None:
        names = self._affinity_index.get(key)
        if names is not None:
            names.discard(replica)
            if not names:
                del self._affinity_index[key]

    # ----------------------------------------------------- prefix route
    def advertise_prefixes(self, replica: str, heads) -> None:
        """Feed one replica's newest hot-head advertisement into the
        routing table (replacement semantics: heads it stopped
        advertising were evicted engine-side and their entries drop).
        Called from the router's observe phase every step."""
        self.prefix_table.advertise(replica, heads)

    def prefix_route_stats(self) -> Dict[str, float]:
        """Routing-table counters plus actual routed placements — the
        ``serving_prefix_route_*`` metric feed."""
        stats = self.prefix_table.stats()
        stats["prefix_route_placements"] = float(self.route_placements)
        return stats

    def forget_replica(self, replica: str) -> None:
        """Drop affinity AND prefix-routing state for a departed
        replica (its cache is gone with it — routing for warmth to a
        fresh process is pure loss, and a routing-table entry pointing
        at a corpse would steer every warm request into the reap)."""
        lru = self._affinity.pop(replica, None)
        if lru:
            for key in lru:
                self._unindex(key, replica)
        self._last_free.pop(replica, None)
        self.prefix_table.forget_replica(replica)
