"""Continuous-batching placement: micro-batches per replica under the
KV-block budget, prefix-cache-aware, least-loaded.

Each router round forms one micro-batch per replica: the requests placed
on a replica in the same round reach its engine together, and the
engine's own bucketed group-prefill turns them into one dispatch (the
Orca/vLLM admission model, one level up).  Placement is gated by REAL
capacity — a free decode slot AND enough free KV blocks for the
request's whole lifetime — so the router never over-admits into a
replica's HBM budget; a request no replica can hold right now simply
stays queued.

Placement preference order:

1. **prefix affinity** — a replica that recently served the same leading
   prompt tokens gets the request (its paged prefix cache very likely
   still holds those blocks, making the prefill nearly free);
2. **least loaded** — otherwise the replica with the most free slots,
   ties broken by free KV blocks.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from dlrover_tpu.serving.router.gateway import RequestGateway, ServingRequest


class ContinuousBatchScheduler:
    """Stateless placement plus a small per-replica prefix-affinity LRU."""

    def __init__(
        self,
        block_size: int = 16,
        schedule_window: int = 64,
        prefix_tokens: int = 32,
        affinity_cap: int = 512,
    ):
        self.block_size = int(block_size)
        self.schedule_window = int(schedule_window)
        self.prefix_tokens = int(prefix_tokens)
        self.affinity_cap = int(affinity_cap)
        # replica name -> LRU of prefix keys it has recently served
        self._affinity: Dict[str, "OrderedDict[bytes, None]"] = {}

    # ------------------------------------------------------------ keys
    def prefix_key(self, prompt: np.ndarray) -> Optional[bytes]:
        """Stable digest of the leading prompt tokens; ``None`` for
        prompts shorter than one cache block (nothing reusable)."""
        n = min(self.prefix_tokens, int(prompt.size))
        if n < self.block_size:
            return None
        return hashlib.blake2b(
            np.asarray(prompt[:n], np.int32).tobytes(), digest_size=16
        ).digest()

    def blocks_needed(self, req: ServingRequest) -> int:
        return -(-req.total_len // self.block_size)

    def _need(self, handle, req: ServingRequest) -> float:
        """Per-replica block requirement: the replica's own admission
        formula when it exposes one (bucket padding + speculative slack
        differ per engine), else the block-size default."""
        fn = getattr(handle, "blocks_needed", None)
        if fn is not None:
            n = fn(int(req.prompt.size), int(req.max_new_tokens))
            if n is not None:
                return float(n)
        return float(self.blocks_needed(req))

    # ------------------------------------------------------- schedule
    def schedule(
        self, gateway: RequestGateway, replicas: List,
        now: Optional[float] = None,
    ) -> List[Tuple[object, ServingRequest]]:
        """One placement round: assign queued requests to replicas with
        capacity.  Returns ``(replica_handle, request)`` pairs; the
        requests are already removed from the gateway.  Skips (leaves
        queued) any request no replica can currently hold.  Placed
        requests get a ``placement``-decision stamp on their trace
        (replica, candidate count, affinity hit) at ``now``."""
        if not replicas:
            return []
        # local capacity ledger: placements in this round consume it
        free = {
            h.name: [h.slots_free(), h.blocks_free()] for h in replicas
        }
        placements: List[Tuple[object, ServingRequest]] = []
        for req in gateway.schedule_scan(self.schedule_window):
            cands = [
                h for h in replicas
                if free[h.name][0] > 0
                and free[h.name][1] >= self._need(h, req)
            ]
            if not cands:
                continue  # stays queued; later (smaller) requests may fit
            key = self.prefix_key(req.prompt)
            affinity_hit = False
            if key is not None:
                affine = [
                    h for h in cands
                    if key in self._affinity.get(h.name, ())
                ]
                if affine:
                    cands = affine
                    affinity_hit = True
            best = max(
                cands,
                key=lambda h: (free[h.name][0], free[h.name][1]),
            )
            if not gateway.remove(req):
                continue  # expired/cancelled between scan and placement
            free[best.name][0] -= 1
            free[best.name][1] -= self._need(best, req)
            if key is not None:
                self._remember(best.name, key)
            if req.trace is not None:
                # the placement DECISION span: queue wait ends here and
                # the per-replica attempt begins, carrying why this
                # replica won (affinity vs load) and how long the
                # request waited (the histogram's per-trace twin)
                extra = {} if now is None else {
                    "queued_s": round(
                        max(0.0, now - req.enqueued_at), 6)}
                req.trace.placed(
                    getattr(best, "name", "?"), now=now,
                    candidates=len(cands), affinity=affinity_hit,
                    **extra)
            placements.append((best, req))
        return placements

    def _remember(self, replica: str, key: bytes) -> None:
        lru = self._affinity.setdefault(replica, OrderedDict())
        lru[key] = None
        lru.move_to_end(key)
        while len(lru) > self.affinity_cap:
            lru.popitem(last=False)

    def forget_replica(self, replica: str) -> None:
        """Drop affinity state for a departed replica (its cache is gone
        with it — routing for warmth to a fresh process is pure loss)."""
        self._affinity.pop(replica, None)
