"""Open-loop traffic generation + the 10k-QPS gateway rig.

A closed-loop load test (N workers, each waiting for its answer before
sending the next request) measures the SERVER's pace and politely
backs off exactly when the system degrades — it cannot see the cliff.
Real traffic is **open-loop**: users arrive when they arrive, whether
or not the gateway is keeping up.  This module generates that traffic
and drives it at the in-process serving stack:

- :class:`OpenLoopGenerator` — a **seeded, replayable** arrival
  schedule: Poisson / bursty (on-off square wave) / diurnal
  (sinusoidal, a compressed day) arrival processes, heavy-tailed
  (Pareto) or fixed prompt lengths, and a per-priority mix.  Same
  config + seed -> byte-identical schedule, so a perf regression
  re-runs the EXACT offered load that exposed it;
- :func:`run_gateway_rig` — the bench harness (``bench.py --config
  gateway``): replays a schedule against a router wall-clock
  open-loop, measuring what the GATEWAY itself costs — per-request
  admission latency (the ``submit()`` call: validation, brown-out
  check, queue insert, trace creation), admission→placement wait,
  shed behavior per priority band, SLO verdicts from the router's
  burn-rate engine, and the OTLP exporter's proof counters when one
  is wired.  The queue bound and the brown-out ladder are expected
  to bite at rate: shed requests ARE the measurement, not a failure.

- :func:`run_router_rig` — the FULL-pipeline twin (``bench.py
  --config router``): the same open-loop schedule driven through the
  WHOLE serving path — admission, placement, submit, streamed tokens,
  DONE — against a fleet of in-process engines, measuring sustained
  **end-to-end** QPS, e2e latency percentiles from the completed
  requests themselves, and the zero-lost/books accounting identity
  (admitted == done + timed_out + cancelled + rejected + poisoned,
  poisoned == 0, nothing non-terminal after the drain).  This is the
  step loop's own perf trajectory next to the gateway's: the admission
  rig proved the front door sustains ~15k QPS, this one holds the
  step engine behind it to the ``router_qps_ok`` bar.  Seeded
  mid-flight cancels (``cancel_every``) make the nightly soak exercise
  the withdrawal machinery at rate.

Everything here is driver-side; the router under test is the real
one, unmodified — any object with ``submit``/``step``/``has_work``
(a :class:`~dlrover_tpu.serving.router.router.ServingRouter` or the
sharded front) drives identically.
"""

from __future__ import annotations

import dataclasses
import math
import random
import time
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from dlrover_tpu.common.constants import ServingRequestState
from dlrover_tpu.serving.router.gateway import (
    PRIORITY_BATCH,
    PRIORITY_HIGH,
    PRIORITY_NORMAL,
    AdmissionError,
    BrownoutShedError,
    QueueFullError,
    TenantQuotaError,
)
from dlrover_tpu.serving.router.slo import BAND_NAMES


@dataclasses.dataclass
class LoadgenConfig:
    """One replayable offered-load description."""

    seed: int = 0
    rate_qps: float = 12000.0       # mean offered arrival rate
    duration_s: float = 2.0         # schedule horizon (virtual time)
    arrival: str = "poisson"        # poisson | bursty | diurnal
    burst_factor: float = 4.0       # bursty: on-phase rate multiplier
    burst_period_s: float = 0.5     # bursty: one on+off cycle
    diurnal_period_s: float = 4.0   # diurnal: one compressed "day"
    diurnal_amplitude: float = 0.8  # peak/trough swing (0..1)
    prompt_mix: str = "heavy_tail"  # heavy_tail | fixed
    prompt_min: int = 8
    prompt_max: int = 512
    pareto_alpha: float = 1.5       # heavy tail: smaller = heavier
    max_new_tokens: int = 32
    # (priority, weight) admission mix — the default mirrors a fleet
    # where interactive traffic dominates and batch rides along
    priority_mix: Tuple[Tuple[int, float], ...] = (
        (PRIORITY_HIGH, 0.1),
        (PRIORITY_NORMAL, 0.6),
        (PRIORITY_BATCH, 0.3),
    )
    # (tenant, weight) identity mix; empty = untenanted legacy traffic
    # (arrivals carry tenant=None and submit omits the kwarg).  Tenant
    # picks draw from their OWN seeded stream so configuring a mix
    # cannot perturb the arrival times/prompts an existing seed
    # replays byte-identically.
    tenant_mix: Tuple[Tuple[str, float], ...] = ()
    # prompt CONTENT shape (the prefix-cache workloads):
    # - "independent": every prompt is unrelated content (the legacy
    #   shape; np.arange prompts, zero sharable prefix);
    # - "chat": multi-turn conversations over ``chat_sessions``
    #   concurrent sessions — each arrival is the next turn of a
    #   (seeded) random session, and a turn's prompt EXTENDS the
    #   previous turn's prompt + answer, so consecutive turns share a
    #   growing prefix (the COW cache's bread-and-butter reuse);
    # - "sysprompt": the shared-system-prompt flood — every arrival is
    #   one ``system_prompt_len``-token prompt shared by ALL users
    #   plus a unique per-user tail (the N-users-one-template shape
    #   the dedup gate measures).
    # Workload draws ride their OWN seeded streams: existing seeds of
    # the "independent" shape replay byte-identically.
    workload: str = "independent"   # independent | chat | sysprompt
    system_prompt_len: int = 256    # chat/sysprompt shared head
    chat_sessions: int = 8          # concurrent conversations
    chat_turn_tokens: int = 32      # new user tokens per turn


@dataclasses.dataclass
class Arrival:
    at_s: float          # offset from schedule start (virtual time)
    prompt_len: int
    max_new_tokens: int
    priority: int
    tenant: Optional[str] = None
    # prefix-workload identity (prompt CONTENT is a pure function of
    # these + the config, via prompt_tokens): uid distinguishes users
    # in the sysprompt flood; session/turn name the conversation slot
    # and its turn number in the chat workload
    uid: int = 0
    session: int = -1
    turn: int = 0


def _tok_stream(n: int, salt: int) -> np.ndarray:
    """Deterministic pseudo-token content: ``n`` int32 ids in
    [0, 32000) from a salted multiplicative stream.  Same (n, salt) ->
    identical array, and a longer stream with the same salt EXTENDS the
    shorter one — which is exactly the property the chat workload needs
    (turn t's prompt is a strict prefix-extension of turn t-1's)."""
    if n <= 0:
        return np.zeros(0, dtype=np.int32)
    return ((np.arange(n, dtype=np.int64) * 2654435761
             + salt * 40503 + 11) % 32000).astype(np.int32)


#: salt of the fleet-wide shared system prompt (sysprompt workload)
_SYSPROMPT_SALT = 0xC0FFEE
#: per-session stream base salt (chat workload)
_CHAT_SALT = 0x5E55


def prompt_tokens(arrival: Arrival, cfg: LoadgenConfig) -> np.ndarray:
    """The arrival's prompt CONTENT (deterministic; rigs call this
    instead of the np.arange pool for the prefix workloads).

    - chat: one salted stream per session slot, truncated at the
      turn's length — every turn extends the previous turn's prompt;
    - sysprompt: the shared system-prompt head (same salt for every
      user) + a unique per-uid tail;
    - independent: the legacy np.arange prompt."""
    if cfg.workload == "chat":
        return _tok_stream(
            arrival.prompt_len, _CHAT_SALT + arrival.session)
    if cfg.workload == "sysprompt":
        head = _tok_stream(cfg.system_prompt_len, _SYSPROMPT_SALT)
        tail = _tok_stream(
            arrival.prompt_len - cfg.system_prompt_len,
            1 + arrival.uid)
        return np.concatenate([head, tail])
    return np.arange(arrival.prompt_len, dtype=np.int32)


class OpenLoopGenerator:
    """Seeded arrival-schedule generator (see module docstring)."""

    def __init__(self, config: Optional[LoadgenConfig] = None):
        self.config = config or LoadgenConfig()
        if self.config.arrival not in ("poisson", "bursty", "diurnal"):
            raise ValueError(
                f"unknown arrival process {self.config.arrival!r}")
        if self.config.workload not in (
                "independent", "chat", "sysprompt"):
            raise ValueError(
                f"unknown workload {self.config.workload!r}")

    def _rate_at(self, t: float) -> float:
        cfg = self.config
        if cfg.arrival == "bursty":
            # square-wave on/off, NORMALIZED so the mean stays
            # rate_qps whatever the burst factor: the on half runs at
            # burst_factor x the (floored) off half, and both are
            # scaled by 2/(on+off) — a bursty-vs-poisson comparison
            # at equal nominal rate really compares shapes, not rates
            phase = (t % cfg.burst_period_s) / cfg.burst_period_s
            on = float(cfg.burst_factor)
            off = max(0.05, 2.0 - on)
            norm = 2.0 / (on + off)
            return cfg.rate_qps * norm * (on if phase < 0.5 else off)
        if cfg.arrival == "diurnal":
            swing = math.sin(2 * math.pi * t / cfg.diurnal_period_s)
            return cfg.rate_qps * (
                1.0 + cfg.diurnal_amplitude * swing)
        return cfg.rate_qps

    def _prompt_len(self, rng: random.Random) -> int:
        cfg = self.config
        if cfg.prompt_mix == "fixed":
            return cfg.prompt_min
        # Pareto body at prompt_min, tail clipped at prompt_max — the
        # heavy-tail mix where one long prompt rides among many short
        return int(min(cfg.prompt_max,
                       cfg.prompt_min * rng.paretovariate(
                           cfg.pareto_alpha)))

    def arrivals(self) -> Iterator[Arrival]:
        """The schedule, in arrival order.  Deterministic per config."""
        cfg = self.config
        rng = random.Random(cfg.seed)
        bands = [p for p, _ in cfg.priority_mix]
        weights = [w for _, w in cfg.priority_mix]
        # tenant identity draws from a SEPARATE seeded stream: adding
        # (or changing) a tenant mix must not move a single arrival
        # time, prompt length or band of an already-seeded schedule
        trng = random.Random(cfg.seed ^ 0x7E4A47)
        tenants = [t for t, _ in cfg.tenant_mix]
        tweights = [w for _, w in cfg.tenant_mix]
        # prefix-workload draws ride their own stream (same invariant
        # as the tenant stream: the chat/sysprompt shape must not move
        # an arrival time or band the main stream already determined)
        wrng = random.Random(cfg.seed ^ 0xC4A7)
        turn_of = [0] * max(1, cfg.chat_sessions)  # per-session turns
        uid = 0
        t = 0.0
        while True:
            rate = max(1e-6, self._rate_at(t))
            t += rng.expovariate(rate)
            if t >= cfg.duration_s:
                return
            # the main stream's draw happens UNCONDITIONALLY so the
            # legacy "independent" schedule replays byte-identically
            # whatever workload is configured on top of it
            drawn_len = self._prompt_len(rng)
            session, turn = -1, 0
            prompt_len = drawn_len
            if cfg.workload == "chat":
                session = wrng.randrange(max(1, cfg.chat_sessions))
                turn = turn_of[session]
                # turn t's prompt = system prompt + t completed
                # (user turn + answer) rounds + this turn's user text;
                # a conversation that would outgrow prompt_max resets
                # its slot (a fresh conversation, same session stream)
                prompt_len = (cfg.system_prompt_len
                              + turn * (cfg.chat_turn_tokens
                                        + cfg.max_new_tokens)
                              + cfg.chat_turn_tokens)
                if prompt_len > cfg.prompt_max and turn > 0:
                    turn_of[session] = 0
                    turn = 0
                    prompt_len = (cfg.system_prompt_len
                                  + cfg.chat_turn_tokens)
                turn_of[session] = turn + 1
            elif cfg.workload == "sysprompt":
                # shared head + the drawn length as the unique tail
                prompt_len = cfg.system_prompt_len + drawn_len
            yield Arrival(
                at_s=t,
                prompt_len=prompt_len,
                max_new_tokens=cfg.max_new_tokens,
                priority=rng.choices(bands, weights)[0],
                tenant=(trng.choices(tenants, tweights)[0]
                        if tenants else None),
                uid=uid,
                session=session,
                turn=turn,
            )
            uid += 1


def _quantiles(sorted_vals: List[float],
               qs: Tuple[float, ...]) -> List[float]:
    if not sorted_vals:
        return [0.0 for _ in qs]
    out = []
    for q in qs:
        idx = min(len(sorted_vals) - 1,
                  int(q / 100.0 * len(sorted_vals)))
        out.append(sorted_vals[idx])
    return out


def hist_quantile(snapshot: Dict[str, object], q: float) -> float:
    """Approximate quantile from a Histogram.snapshot(): linear
    interpolation inside the winning bucket (the standard Prometheus
    histogram_quantile estimate)."""
    counts = list(snapshot["counts"])
    bounds = list(snapshot["buckets"])
    total = sum(counts)
    if total == 0:
        return 0.0
    target = q / 100.0 * total
    cum = 0
    for i, c in enumerate(counts):
        if cum + c >= target and c > 0:
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i] if i < len(bounds) else bounds[-1] * 2
            frac = (target - cum) / c
            return lo + (hi - lo) * frac
        cum += c
    return bounds[-1]


def run_gateway_rig(
    router,
    config: Optional[LoadgenConfig] = None,
    step_every: int = 256,
    pace: bool = True,
    admission_reservoir: int = 200_000,
    drain_max_steps: int = 200_000,
    otlp_exporter=None,
) -> Dict[str, object]:
    """Replay one open-loop schedule against ``router`` on the wall
    clock and report the gateway's own cost.

    ``pace=True`` holds the driver to the schedule when it runs ahead
    (so bursty/diurnal shapes survive); it can never slow a driver
    that is BEHIND — achieved QPS below the offered rate is the
    honest "this gateway cannot admit that fast" answer, and the
    bench gates on it.  ``step_every`` bounds how much admission-only
    work happens between router pump rounds."""
    cfg = config or LoadgenConfig()
    gen = OpenLoopGenerator(cfg)
    # pre-built prompt pool: the rig measures the GATEWAY, and
    # np.arange per arrival would time numpy allocation instead.
    # Prefix workloads need CONTENT (shared heads), so they build per
    # arrival via prompt_tokens instead — those rigs measure the
    # cache, not the admission microseconds.
    content = cfg.workload != "independent"
    pool = ({} if content else
            {n: np.arange(n, dtype=np.int32)
             for n in sorted({a.prompt_len for a in gen.arrivals()})})

    # per-submit wall seconds, RESERVOIR-sampled (not first-N: on a
    # 60s soak the p99 must see the final seconds' tail, not only the
    # opening 17s); seeded so the sampling replays with the schedule
    lat: List[float] = []
    lat_rng = random.Random(cfg.seed ^ 0x5EED)
    lat_seen = 0
    # keyed on the CONFIGURED mix (a custom band outside the stock
    # three must count, not KeyError mid-run)
    shed = {band: 0 for band, _ in cfg.priority_mix}
    shed_kinds = {"queue_full": 0, "brownout": 0, "quota": 0,
                  "other": 0}
    admitted = 0
    offered = 0
    steps = 0

    t0 = time.perf_counter()
    since_step = 0
    for arrival in gen.arrivals():
        offered += 1
        if pace:
            ahead = arrival.at_s - (time.perf_counter() - t0)
            if ahead > 0.002:
                time.sleep(ahead)
        prompt = (prompt_tokens(arrival, cfg) if content
                  else pool[arrival.prompt_len])
        kw = ({"tenant": arrival.tenant}
              if arrival.tenant is not None else {})
        s0 = time.perf_counter()
        try:
            router.submit(prompt, arrival.max_new_tokens,
                          priority=arrival.priority, **kw)
            admitted += 1
        except BrownoutShedError:
            shed[arrival.priority] += 1
            shed_kinds["brownout"] += 1
        except QueueFullError:
            shed[arrival.priority] += 1
            shed_kinds["queue_full"] += 1
        except TenantQuotaError:
            shed[arrival.priority] += 1
            shed_kinds["quota"] += 1
        except AdmissionError:
            shed[arrival.priority] += 1
            shed_kinds["other"] += 1
        dt = time.perf_counter() - s0
        lat_seen += 1
        if len(lat) < admission_reservoir:
            lat.append(dt)
        else:  # reservoir sampling keeps the quantiles unbiased
            j = lat_rng.randint(0, lat_seen - 1)
            if j < admission_reservoir:
                lat[j] = dt
        since_step += 1
        if since_step >= step_every:
            since_step = 0
            router.step()
            steps += 1
    offer_wall_s = time.perf_counter() - t0

    # drain: the offered phase is over; pump until the admitted work
    # completes or expires so the SLO verdicts cover every request
    while router.has_work and steps < drain_max_steps:
        router.step()
        steps += 1
    drain_wall_s = time.perf_counter() - t0 - offer_wall_s

    lat.sort()
    p50, p99, p999 = _quantiles(lat, (50, 99, 99.9))
    now = time.monotonic()
    m = router.metrics.metrics()
    result: Dict[str, object] = {
        "gateway_offered": offered,
        "gateway_admitted": admitted,
        "gateway_shed": {BAND_NAMES.get(b, str(b)): n
                         for b, n in shed.items()},
        "gateway_shed_kinds": dict(shed_kinds),
        "gateway_offer_wall_s": round(offer_wall_s, 4),
        "gateway_drain_wall_s": round(drain_wall_s, 4),
        "gateway_qps": round(offered / max(1e-9, offer_wall_s), 1),
        "gateway_admission_p50_us": round(p50 * 1e6, 2),
        "gateway_admission_p99_us": round(p99 * 1e6, 2),
        "gateway_admission_p999_us": round(p999 * 1e6, 2),
        "gateway_router_steps": steps,
        "gateway_completed": int(
            m["serving_requests_completed_total"]),
        "gateway_timed_out": int(
            m["serving_requests_timed_out_total"]),
        "gateway_queue_wait_p50_s": round(hist_quantile(
            router.metrics.queue_wait_hist.snapshot(), 50), 6),
        "gateway_queue_wait_p99_s": round(hist_quantile(
            router.metrics.queue_wait_hist.snapshot(), 99), 6),
    }
    slo = getattr(router, "slo", None)
    if slo is not None:
        result["gateway_slo"] = slo.summary(now)
    if otlp_exporter is not None:
        result["gateway_otlp"] = {
            k: v for k, v in otlp_exporter.metrics().items()}
    return result


def run_router_rig(
    router,
    config: Optional[LoadgenConfig] = None,
    step_every: int = 64,
    pace: bool = True,
    cancel_every: int = 0,
    drain_max_steps: int = 500_000,
    drain_timeout_s: float = 120.0,
) -> Dict[str, object]:
    """Replay one open-loop schedule through the WHOLE pipeline on the
    wall clock: admission -> placement -> submit -> streamed tokens ->
    DONE, against whatever fleet is already joined on ``router``.

    Differences from :func:`run_gateway_rig`, deliberately:

    - every admitted request object is KEPT and audited at the end —
      zero-lost means zero requests outside a terminal state, and the
      books identity is computed from the requests themselves, so the
      rig works unchanged against a single router or the sharded
      front (whose counters live in N shards);
    - the headline number is sustained END-TO-END QPS: completed
      requests over the whole wall (offer + drain) — the step loop
      cannot hide behind a fast front door;
    - e2e percentiles come from ``finished_at - submitted_at`` of the
      completed requests (the router's own monotonic stamps);
    - ``cancel_every=N`` withdraws every Nth admitted request a step
      later (seeded by admission order, replayable): the mid-flight
      cancel mix the nightly soak runs.

    ``step_every`` bounds admissions between router rounds; a threaded
    sharded front self-drives and its ``step()`` briefly yields
    instead, which keeps this driver loop correct for both."""
    cfg = config or LoadgenConfig()
    gen = OpenLoopGenerator(cfg)
    content = cfg.workload != "independent"
    pool = ({} if content else
            {n: np.arange(n, dtype=np.int32)
             for n in sorted({a.prompt_len for a in gen.arrivals()})})

    admitted: List[object] = []
    shed = {band: 0 for band, _ in cfg.priority_mix}
    shed_kinds = {"queue_full": 0, "brownout": 0, "quota": 0,
                  "other": 0}
    # per-tenant refusal counts (admission raises before a request
    # object exists, so the ARRIVAL's tenant id is the key here; the
    # admitted-side audit below keys on the RESOLVED req.tenant)
    tenant_rejected: Dict[str, int] = {}
    offered = 0
    steps = 0
    cancelled_by_rig: List[object] = []
    to_cancel: List[object] = []

    t0 = time.perf_counter()
    since_step = 0
    for arrival in gen.arrivals():
        offered += 1
        if pace:
            ahead = arrival.at_s - (time.perf_counter() - t0)
            if ahead > 0.002:
                time.sleep(ahead)
        prompt = (prompt_tokens(arrival, cfg) if content
                  else pool[arrival.prompt_len])
        kw = ({"tenant": arrival.tenant}
              if arrival.tenant is not None else {})
        try:
            req = router.submit(prompt, arrival.max_new_tokens,
                                priority=arrival.priority, **kw)
            admitted.append(req)
            if cancel_every and len(admitted) % cancel_every == 0:
                # withdraw shortly after admission: flushed on the
                # next arrival (typically still queued — a request
                # cannot complete before a router step) or at the next
                # step boundary (by then often RUNNING), so both
                # cancel paths get traffic
                to_cancel.append(req)
            elif to_cancel:
                for marked in to_cancel:
                    if marked.cancel():
                        cancelled_by_rig.append(marked)
                to_cancel.clear()
        except BrownoutShedError:
            shed[arrival.priority] += 1
            shed_kinds["brownout"] += 1
            if arrival.tenant is not None:
                tenant_rejected[arrival.tenant] = \
                    tenant_rejected.get(arrival.tenant, 0) + 1
        except QueueFullError:
            shed[arrival.priority] += 1
            shed_kinds["queue_full"] += 1
            if arrival.tenant is not None:
                tenant_rejected[arrival.tenant] = \
                    tenant_rejected.get(arrival.tenant, 0) + 1
        except TenantQuotaError:
            shed[arrival.priority] += 1
            shed_kinds["quota"] += 1
            if arrival.tenant is not None:
                tenant_rejected[arrival.tenant] = \
                    tenant_rejected.get(arrival.tenant, 0) + 1
        except AdmissionError:
            shed[arrival.priority] += 1
            shed_kinds["other"] += 1
            if arrival.tenant is not None:
                tenant_rejected[arrival.tenant] = \
                    tenant_rejected.get(arrival.tenant, 0) + 1
        since_step += 1
        if since_step >= step_every:
            since_step = 0
            router.step()
            steps += 1
            for req in to_cancel:
                if req.cancel():
                    cancelled_by_rig.append(req)
            to_cancel.clear()
    # a request marked on the schedule's LAST arrival has no later
    # arrival or step boundary to flush it — withdraw it now, before
    # the drain, so "every Nth admitted request" means every Nth
    for req in to_cancel:
        if req.cancel():
            cancelled_by_rig.append(req)
    to_cancel.clear()
    offer_wall_s = time.perf_counter() - t0

    # drain: pump until every admitted request reaches a terminal
    # state (DONE, or the deadline/cancel machinery answers it)
    drain_deadline = time.perf_counter() + drain_timeout_s
    while router.has_work and steps < drain_max_steps \
            and time.perf_counter() < drain_deadline:
        router.step()
        steps += 1
    total_wall_s = time.perf_counter() - t0

    # the audit, from the request objects themselves
    by_state: Dict[str, int] = {}
    e2e: List[float] = []
    terminal_states = (ServingRequestState.DONE,
                       ServingRequestState.TIMED_OUT,
                       ServingRequestState.CANCELLED,
                       ServingRequestState.REJECTED,
                       ServingRequestState.POISONED)
    # per-RESOLVED-tenant books (raw ids are fine in this JSON report
    # — the DL010 bound applies to metric labels, not rig summaries)
    tenant_books: Dict[str, Dict[str, object]] = {}
    for req in admitted:
        by_state[req.state] = by_state.get(req.state, 0) + 1
        done_req = (req.state == ServingRequestState.DONE
                    and req.finished_at is not None)
        if done_req:
            e2e.append(req.finished_at - req.submitted_at)
        name = getattr(req, "tenant", None)
        if name is not None:
            book = tenant_books.setdefault(
                name, {"admitted": 0, "done": 0, "lost": 0,
                       "e2e": []})
            book["admitted"] += 1
            if done_req:
                book["done"] += 1
                book["e2e"].append(req.finished_at - req.submitted_at)
            if req.state not in terminal_states:
                book["lost"] += 1
    done = by_state.get(ServingRequestState.DONE, 0)
    terminal = terminal_states
    lost = sum(n for state, n in by_state.items()
               if state not in terminal)
    poisoned = by_state.get(ServingRequestState.POISONED, 0)
    accounted = sum(by_state.get(s, 0) for s in terminal)
    e2e.sort()
    p50, p99, p999 = _quantiles(e2e, (50, 99, 99.9))
    by_tenant: Dict[str, Dict[str, object]] = {}
    for name in sorted(set(tenant_books) | set(tenant_rejected)):
        book = tenant_books.get(
            name, {"admitted": 0, "done": 0, "lost": 0, "e2e": []})
        tl = sorted(book["e2e"])
        tp50, tp99, _ = _quantiles(tl, (50, 99, 99.9))
        by_tenant[name] = {
            "admitted": book["admitted"],
            "done": book["done"],
            "lost": book["lost"],
            "rejected": tenant_rejected.get(name, 0),
            "e2e_p50_s": round(tp50, 6),
            "e2e_p99_s": round(tp99, 6),
        }
    return {
        "router_offered": offered,
        "router_admitted": len(admitted),
        "router_shed": {BAND_NAMES.get(b, str(b)): n
                        for b, n in shed.items()},
        "router_shed_kinds": dict(shed_kinds),
        "router_by_state": dict(sorted(by_state.items())),
        "router_completed": done,
        "router_cancel_attempts": len(cancelled_by_rig),
        "router_lost": lost,
        "router_poisoned": poisoned,
        # the identity: every admitted request reached exactly one
        # terminal state and nothing fell through the failover /
        # cancel / expiry machinery
        "router_books_ok": bool(
            lost == 0 and accounted == len(admitted)),
        "router_offer_wall_s": round(offer_wall_s, 4),
        "router_total_wall_s": round(total_wall_s, 4),
        "router_steps": steps,
        # sustained END-TO-END throughput: completions over the whole
        # wall — the step loop's own number
        "router_qps": round(done / max(1e-9, total_wall_s), 1),
        "router_offered_qps": round(
            offered / max(1e-9, offer_wall_s), 1),
        "router_e2e_p50_s": round(p50, 6),
        "router_e2e_p99_s": round(p99, 6),
        "router_e2e_p999_s": round(p999, 6),
        # per-tenant slice of the same audit (empty when untenanted);
        # the noisy-neighbor gate reads victims' p99/lost from here
        "router_by_tenant": by_tenant,
    }
