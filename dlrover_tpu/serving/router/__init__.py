"""Elastic serving gateway: continuous-batching router over replicas.

The serving-side counterpart of the trainer's elasticity stack: the
single-replica engine (serving/engine.py) scales out behind a router
that admits, queues, places and — when a replica dies — REQUEUES
requests, and that feeds load signals into the Brain so replica counts
scale like worker counts do for training.

Layers (one module each):

- :mod:`gateway`   — admission control, bounded priority queues,
  per-request deadlines;
- :mod:`scheduler` — continuous-batching placement: micro-batches per
  replica under the KV-block budget, prefix-affine + least-loaded;
- :mod:`replica`   — replica handles + manager: heartbeats, failover
  (drain + requeue, zero lost requests), graceful join/leave;
- :mod:`autoscale` — queue/TTFT/throughput signals -> Brain plan ->
  ScalePlan through a cluster Scaler, plus the provisioner closing the
  loop from cluster node events back to router membership;
- :mod:`brownout`  — per-priority brown-out shedding: watermark +
  hysteresis ladder that sheds BATCH before NORMAL, never HIGH;
- :mod:`slo`       — per-priority objectives + multi-window error-
  budget burn rates; the SLO-pressure autoscale signal;
- :mod:`loadgen`   — seeded replayable open-loop traffic generator +
  the 10k-QPS gateway rig (bench.py --config gateway) and the FULL-
  pipeline router rig (admission -> placement -> streamed tokens ->
  DONE; bench.py --config router);
- :mod:`metrics`   — Prometheus gauges/counters for all of the above;
- :mod:`router`    — the orchestrating pump, behind the step-engine
  seam (``step_engine="event" | "sweep"``);
- :mod:`stepengine` — the sharded router front: N independent step
  loops, requests partitioned by rid hash, shared brown-out view.

Tenancy (who is asking, as opposed to how urgent) lives one package up
in :mod:`dlrover_tpu.serving.tenancy` — policy + accounting with no
router imports; the gateway wires it into admission (token-bucket
quotas, :class:`TenantQuotaError`), within-band weighted fair
queueing, and proportional brown-out shedding.
"""

from dlrover_tpu.serving.router.brownout import (  # noqa: F401
    BrownoutPolicy,
)
from dlrover_tpu.serving.router.gateway import (  # noqa: F401
    PRIORITY_BATCH,
    PRIORITY_HIGH,
    PRIORITY_NORMAL,
    STREAM_RESTART,
    AdmissionError,
    BrownoutShedError,
    QueueFullError,
    RequestGateway,
    ServingRequest,
    TenantQuotaError,
)
from dlrover_tpu.serving.router.metrics import RouterMetrics  # noqa: F401
from dlrover_tpu.serving.router.replica import (  # noqa: F401
    InferenceEngineAdapter,
    ReplicaDeadError,
    ReplicaHandle,
    ReplicaManager,
)
from dlrover_tpu.serving.router.router import ServingRouter  # noqa: F401
from dlrover_tpu.serving.router.scheduler import (  # noqa: F401
    ContinuousBatchScheduler,
)
from dlrover_tpu.serving.router.autoscale import (  # noqa: F401
    ReplicaProvisioner,
    ServingAutoScaler,
)
from dlrover_tpu.serving.router.slo import (  # noqa: F401
    SloEngine,
    SloObjective,
)
from dlrover_tpu.serving.router.stepengine import (  # noqa: F401
    ShardedRouterFront,
)
from dlrover_tpu.serving.tenancy import (  # noqa: F401
    TenantRegistry,
    TenantSpec,
)
