"""Prompt-lookup speculative drafts + rejection-sampling verification.

The draft side of the engine's speculative decode mode: instead of a
separate draft model, continuations are proposed by matching the tail
n-gram of the context against its own history and copying what followed
the previous occurrence — "prompt lookup decoding".  Free to compute,
surprisingly effective on natural text (summaries, code, chat echo
long spans of their context), and exactly zero-cost when it misses:
the verify step degenerates to a normal decode step (1 token/dispatch).

Verification preserves the model's output distribution EXACTLY for any
sampling config: greedy (temperature 0) accepts a draft iff it is the
argmax; sampling uses speculative rejection sampling
(:func:`rejection_commit`) — accept draft ``d_i`` with probability
``p_i(d_i)`` (the draft proposal is a point mass, so the general
``min(1, p/q)`` rule reduces to ``p``) and on the first rejection
resample from the leftover ``p_i`` with ``d_i`` removed, which is the
``norm(max(0, p - q))`` residual.  The committed tokens are therefore
an exact sample from the target distribution — the Leviathan/Chen
speculative-sampling guarantee, with q = delta(draft).

Beyond-reference capability: the reference delegates serving to vLLM
(atorch/atorch/rl/inference_backend/vllm_backend.py:11-24).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def find_draft(
    context: np.ndarray,
    k: int,
    ngram: int = 3,
    min_ngram: int = 1,
) -> Optional[np.ndarray]:
    """Propose up to ``k`` draft tokens continuing ``context``.

    Searches for the most recent earlier occurrence of the context's
    tail ``ngram`` (backing off to shorter n-grams down to
    ``min_ngram``) and returns a copy of the tokens that followed it.
    Returns None when no match exists or the match has no continuation.
    """
    ctx = np.asarray(context).reshape(-1)
    n = ctx.size
    if n < min_ngram + 1 or k <= 0:
        return None
    for glen in range(min(ngram, n - 1), min_ngram - 1, -1):
        tail = ctx[n - glen:]
        # all window starts except the tail's own position, vectorized
        windows = np.lib.stride_tricks.sliding_window_view(
            ctx[: n - 1], glen
        )
        hits = np.nonzero((windows == tail).all(axis=1))[0]
        if hits.size:
            start = int(hits[-1])  # most recent occurrence
            # every window start satisfies start+glen <= n-1, so the
            # continuation always has at least one token
            return ctx[start + glen: start + glen + k].astype(np.int32)
    return None


def rejection_commit(
    logits,                 # [B, K, V] verify logits (pre-filter)
    drafts,                 # [B, K-1] int32 draft tokens
    draft_len,              # [B] int32 valid draft count per slot
    key,                    # PRNG key
    *,
    temperature: float,
    top_k: int,
    top_p: float,
) -> Tuple["object", "object"]:
    """Device-side speculative commit: returns ``(out_tokens [B, K],
    n_commit [B])`` where ``out_tokens[b, :n_commit[b]]`` is an EXACT
    sample of ``n_commit[b]`` tokens from the target sampling
    distribution.

    ``logits[:, i]`` is the distribution of the token AFTER position i;
    drafts propose tokens at positions 1..K-1.  Greedy (temperature 0):
    accept while ``argmax == draft``, emit the argmax at the first
    mismatch (or the bonus position).  Sampling: accept draft ``d_i``
    with probability ``p_i(d_i)``; at the first rejection sample from
    ``p_i`` with ``d_i`` zeroed (the q=delta residual); after a full
    accept sample the bonus from ``p_K``.
    """
    import jax
    import jax.numpy as jnp

    from dlrover_tpu.rl.generation import filter_logits

    b, k, v = logits.shape
    flogits = filter_logits(logits, temperature, top_k, top_p)
    idx = jnp.arange(k - 1)[None, :]
    valid = idx < draft_len[:, None]                       # [B, K-1]
    if temperature == 0.0:
        greedy = jnp.argmax(flogits, axis=-1).astype(jnp.int32)  # [B, K]
        accept = (greedy[:, : k - 1] == drafts) & valid
        lead = jnp.cumprod(accept.astype(jnp.int32), axis=1)
        acc = lead.sum(axis=1)                             # [B]
        final = jnp.take_along_axis(
            greedy, acc[:, None], axis=1
        )[:, 0]
    else:
        probs = jax.nn.softmax(flogits / temperature, axis=-1)
        k_u, k_s = jax.random.split(key)
        u = jax.random.uniform(k_u, (b, k - 1))
        p_draft = jnp.take_along_axis(
            probs[:, : k - 1], drafts[..., None], axis=-1
        )[..., 0]                                          # [B, K-1]
        accept = (u < p_draft) & valid
        lead = jnp.cumprod(accept.astype(jnp.int32), axis=1)
        acc = lead.sum(axis=1)                             # [B]
        p_final = jnp.take_along_axis(
            probs, acc[:, None, None], axis=1
        )[:, 0]                                            # [B, V]
        # at a rejection, remove the rejected draft's mass (the
        # norm(max(0, p - q)) residual for a point-mass q); a full
        # accept (acc == draft_len) keeps p intact for the bonus token
        rejected = acc < draft_len
        d_rej = jnp.take_along_axis(
            drafts, jnp.minimum(acc, k - 2)[:, None], axis=1
        )[:, 0]
        remove = rejected[:, None] & (
            jax.nn.one_hot(d_rej, v, dtype=bool)
        )
        p_final = jnp.where(remove, 0.0, p_final)
        final = jax.random.categorical(
            k_s, jnp.log(jnp.maximum(p_final, 1e-38))
        ).astype(jnp.int32)
    out = jnp.where(
        jnp.arange(k)[None, :] < acc[:, None],
        jnp.pad(drafts, ((0, 0), (0, 1))),
        0,
    )
    out = out.at[jnp.arange(b), acc].set(final)
    return out.astype(jnp.int32), (acc + 1).astype(jnp.int32)
