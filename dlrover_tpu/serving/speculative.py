"""Prompt-lookup speculative drafts (model-free n-gram matching).

The draft side of the engine's speculative decode mode: instead of a
separate draft model, continuations are proposed by matching the tail
n-gram of the context against its own history and copying what followed
the previous occurrence — "prompt lookup decoding".  Free to compute,
surprisingly effective on natural text (summaries, code, chat echo
long spans of their context), and exactly zero-cost when it misses:
the verify step degenerates to a normal decode step (1 token/dispatch).

Greedy verification preserves the model's output distribution exactly
(an accepted draft token IS the greedy token), so the engine restricts
speculation to ``temperature == 0``.

Beyond-reference capability: the reference delegates serving to vLLM
(atorch/atorch/rl/inference_backend/vllm_backend.py:11-24).
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def find_draft(
    context: np.ndarray,
    k: int,
    ngram: int = 3,
    min_ngram: int = 1,
) -> Optional[np.ndarray]:
    """Propose up to ``k`` draft tokens continuing ``context``.

    Searches for the most recent earlier occurrence of the context's
    tail ``ngram`` (backing off to shorter n-grams down to
    ``min_ngram``) and returns a copy of the tokens that followed it.
    Returns None when no match exists or the match has no continuation.
    """
    ctx = np.asarray(context).reshape(-1)
    n = ctx.size
    if n < min_ngram + 1 or k <= 0:
        return None
    for glen in range(min(ngram, n - 1), min_ngram - 1, -1):
        tail = ctx[n - glen:]
        # all window starts except the tail's own position, vectorized
        windows = np.lib.stride_tricks.sliding_window_view(
            ctx[: n - 1], glen
        )
        hits = np.nonzero((windows == tail).all(axis=1))[0]
        if hits.size:
            start = int(hits[-1])  # most recent occurrence
            # every window start satisfies start+glen <= n-1, so the
            # continuation always has at least one token
            return ctx[start + glen: start + glen + k].astype(np.int32)
    return None
