"""Paged KV cache: block-pool memory management for the serving engine.

What vLLM gives the reference's RL rollouts (reference:
atorch/atorch/rl/inference_backend/vllm_backend.py:11-24 — paged
attention, prefix reuse), rebuilt TPU-style:

- **block pool**: per layer, K/V live in ``[num_blocks, block_size,
  KV, D]`` pools; a sequence owns a LIST of blocks instead of a dense
  ``max_len`` stripe, so cache memory scales with actual sequence
  lengths and concurrency is bounded by the pool (HBM budget), not by
  ``slots x max_len`` worst-case reservations.
- **prefix caching**: the leading FULL prompt blocks are content-hashed
  (chained, so a hit guarantees the whole prefix matches); admissions
  reuse hit blocks refcounted, and fully-released prefix blocks linger
  in an LRU until the allocator actually needs them — repeated system
  prompts cost their KV once.
- **static shapes**: the device side sees a fixed ``[slots,
  max_blocks]`` int32 table and fixed pools; only the HOST manager is
  dynamic.  Reads gather ``pool[table]`` back to the dense ``[B, L,
  KV, D]`` view the attention kernels already handle — correctness
  first (the gather is XLA-fused with the attention reads); a fused
  Pallas paged-attention kernel is the optimization seam.

Shared (refcount > 1) prefix blocks are READ-ONLY — the copy-on-write
contract (serving/prefixcache):

- a full prompt block whose chained digest matches a committed block
  is MAPPED (refcount bumped), never copied or recomputed;
- prefill write masking (the ``skip_upto`` argument of the scatter
  helpers) routes every write at a shared position to the trash sink,
  so a sharer can never perturb the block it maps — readers see the
  FIRST writer's KV bit-for-bit;
- a sequence that must write INSIDE its shared region (chunked
  prefill starting chunk-unaligned) first diverges those blocks via
  :meth:`BlockManager.cow_block` — still-shared blocks are copied to
  a fresh block, a privately-held committed block is unregistered in
  place — and only then writes.

Generated tokens, speculative-verify slack and bucket-padding junk
all land at positions >= the prompt's full-block prefix, which the
allocator always backs with fresh blocks — so the only writers the
COW machinery must police are the prefill paths above.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.serving.prefixcache import PrefixBlockIndex, chain_key

# legacy alias: the chained digest moved to serving/prefixcache (the
# router computes routing heads with the SAME function)
_chain_key = chain_key

# dlint DL012 contract: a block id handed out by the allocator is a
# refcount the caller now owes — every acquire site must return it,
# hand it to a sequence's block list, or push it back through the
# release surface on EVERY path (including exception edges)
_DLINT_RESOURCE_SPECS = (
    {
        "resource": "KV block refcount",
        "acquire": ("_take_block", "evict_one"),
        "release": ("free_sequence", "linger", "forget"),
        "why": "a dropped block id leaves _ref pinned nonzero forever "
               "— the pool shrinks by one block per leak until "
               "alloc_sequence starves every admission",
    },
)


class BlockManager:
    """Host-side pool bookkeeping: allocation, refcounts, prefix COW.

    Committed-prefix state (digests, content verification, the ref-0
    LRU, head tracking, the stats ledger) lives in
    :class:`~dlrover_tpu.serving.prefixcache.PrefixBlockIndex`; this
    class owns ids, the free list and refcounts.  ``sharing=False``
    disables prefix mapping entirely (every allocation gets fresh
    blocks, nothing is committed) — the COW-off half of the golden
    equivalence suite."""

    def __init__(self, num_blocks: int, block_size: int,
                 sharing: bool = True):
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.sharing = bool(sharing)
        # block 0 is the TRASH SINK, never allocated: the decode step
        # computes (and writes) junk KV for INACTIVE slots too — their
        # all-zero table rows must route those writes somewhere no live
        # sequence reads (the dense layout absorbs this in the dead
        # slot's own row; paging needs the sentinel)
        self._free: List[int] = list(range(1, num_blocks))[::-1]
        self._ref = np.zeros(num_blocks, np.int32)
        # committed blocks whose KV content has NOT been written yet.
        # Batched prefill writes within the same dispatch that follows
        # allocation, so its registrations are immediately valid; the
        # CHUNKED path registers at alloc time but writes the prompt
        # over many steps — the engine marks those blocks pending and
        # clears them (mark_filled) as its cursor crosses each one, so
        # a second sequence never warm-starts over unwritten content
        self._pending: set = set()
        self.index = PrefixBlockIndex()

    # ------------------------------------------------------------ alloc
    @property
    def available_blocks(self) -> int:
        return len(self._free) + self.index.lru_count()

    def _take_block(self) -> Optional[int]:
        if self._free:
            bid = self._free.pop()
        else:
            # evict the oldest lingering prefix block (LRU); the index
            # stages its head (if it was one) for the next advertisement
            # drain so the router's routing entry invalidates too
            bid = self.index.evict_one()
        if bid is not None:
            self._pending.discard(bid)
        return bid

    def mark_pending(self, bids: List[int]) -> None:
        """Declare committed blocks whose KV write is IN FLIGHT (the
        chunked-prefill registration gap).  ``shared_prefix_ready``
        holds admissions that would map them until :meth:`mark_filled`
        publishes each one.  Uncommitted ids (sharing disabled) are
        ignored — nothing can map them anyway."""
        self._pending.update(
            b for b in bids if self.index.is_committed(b))

    def mark_filled(self, bid: int) -> None:
        """The prefill dispatch covering ``bid``'s positions landed:
        its KV content now exists, so other sequences may warm-start
        over it."""
        self._pending.discard(bid)

    def shared_prefix_ready(self, prompt: np.ndarray) -> bool:
        """Would ``prompt``'s committed-prefix hits all hold WRITTEN
        content?

        Pure probe (no stats, no refcounts): walks the digest chain
        exactly like :meth:`alloc_sequence`'s hit loop and returns
        False iff some matching committed block is still pending —
        i.e. the first writer's chunked prefill has not reached it
        yet.  Callers keep the request queued and retry next step
        rather than mapping (and warm-starting past) content that
        does not exist."""
        if not self.sharing or not self._pending:
            return True
        bs = self.block_size
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        chain = b""
        for i in range(prompt.size // bs):
            tok_bytes = prompt[i * bs:(i + 1) * bs].tobytes()
            chain = chain_key(chain, tok_bytes)
            bid = self.index.lookup(chain, tok_bytes)
            if bid is None:
                break
            if bid in self._pending:
                return False
        return True

    def alloc_sequence(
        self, prompt: np.ndarray, total_len: int
    ) -> Optional[Tuple[List[int], int]]:
        """Blocks for a sequence of ``total_len`` positions whose first
        ``len(prompt)`` tokens are known: returns ``(blocks,
        shared_tokens)`` where the first ``shared_tokens`` positions
        are served by refcount-bumped prefix-cache hits, or None when
        the pool cannot cover the request (caller keeps it queued)."""
        bs = self.block_size
        # int32 normalization: digests are over raw token BYTES, and
        # the router's head_key hashes int32 — a caller handing int64
        # tokens must still land on the same chain
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        n_blocks = -(-max(int(total_len), 1) // bs)
        # enforce total_len >= len(prompt) at the API boundary: a shorter
        # total_len would otherwise let len(shared) exceed n_blocks and
        # the returned list overflow the engine's fixed table row
        full_prompt_blocks = min(prompt.size // bs, n_blocks)

        shared: List[Tuple[bytes, int]] = []
        chain = b""
        if self.sharing:
            for i in range(full_prompt_blocks):
                tok_bytes = prompt[i * bs:(i + 1) * bs].tobytes()
                chain = chain_key(chain, tok_bytes)
                bid = self.index.lookup(chain, tok_bytes)
                if bid is None:
                    break
                shared.append((chain, bid))
        need = n_blocks - len(shared)
        # reviving a shared hit that currently lingers in the LRU also
        # consumes availability (it leaves the evictable set) — without
        # counting those, the guard can pass and _take_block() then come
        # up empty mid-allocation
        revived = sum(1 for _, bid in shared if self._ref[bid] == 0)
        if need > self.available_blocks - revived:
            return None
        blocks: List[int] = []
        for chain_h, bid in shared:
            if self._ref[bid] == 0:
                self.index.revive(bid)  # revive a lingering block
            self._ref[bid] += 1
            self.index.note_hit(bid, bs)
            blocks.append(bid)
        chain = shared[-1][0] if shared else b""
        for i in range(len(shared), n_blocks):
            bid = self._take_block()
            assert bid is not None  # guarded by available_blocks above
            self._ref[bid] = 1
            blocks.append(bid)
            if i < full_prompt_blocks:
                tok_bytes = prompt[i * bs:(i + 1) * bs].tobytes()
                chain = chain_key(chain, tok_bytes)
                if self.sharing:
                    self.index.note_miss()
                    self.index.register(
                        chain, bid, tok_bytes, head=(i == 0))
        return blocks, len(shared) * bs

    def free_sequence(self, blocks: List[int]) -> None:
        for bid in blocks:
            self._ref[bid] -= 1
            assert self._ref[bid] >= 0
            if self._ref[bid] == 0:
                if self.index.is_committed(bid) \
                        and bid not in self._pending:
                    # prefix block: linger in the LRU for reuse
                    self.index.linger(bid)
                else:
                    # uncommitted — or committed but still pending (its
                    # chunked writer was cancelled mid-prefill): the
                    # content is garbage, so drop the registration
                    # instead of letting a future hit map it
                    if self.index.is_committed(bid):
                        self.index.forget(bid)
                    self._pending.discard(bid)
                    self._free.append(bid)

    # -------------------------------------------------------------- cow
    def cow_block(self, bid: int) -> Optional[Tuple[int, bool]]:
        """Divergence point: the caller is about to WRITE into ``bid``,
        which may be shared.  Returns ``(block, copied)``:

        - still shared (ref > 1): a fresh block with ref 1; ``bid``'s
          ref comes down by one and ``copied=True`` tells the caller
          to copy the pool rows ``bid -> block`` before writing;
        - privately held (ref == 1) but committed: the SAME id with
          its registration dropped (``copied=False``) — no other
          sequence can map it mid-rewrite;
        - None: pool exhausted (no block for the copy) — the caller
          rolls its admission back and keeps the request queued."""
        if self._ref[bid] > 1:
            new = self._take_block()
            if new is None:
                return None
            self._ref[bid] -= 1
            self._ref[new] = 1
            self.index.note_cow()
            return new, True
        if self.index.is_committed(bid):
            self.index.forget(bid)
            self._pending.discard(bid)
        return bid, False

    # ------------------------------------------------------------ books
    def shared_block_count(self) -> int:
        """Blocks currently mapped by more than one sequence."""
        return int((self._ref > 1).sum())

    def prefix_stats(self) -> Dict[str, float]:
        """The ``serving_prefix_*`` ledger for this pool."""
        stats = self.index.stats()
        stats["prefix_shared_blocks"] = float(self.shared_block_count())
        return stats

    def hot_heads(self, n: int = 8) -> List[str]:
        return self.index.hot_heads(n)

    def drain_evicted_heads(self) -> List[str]:
        return self.index.drain_evicted_heads()

    def check_books(self) -> bool:
        """Assert the block books balance: every block except the
        trash sink is in EXACTLY one of {free list, referenced,
        ref-0 LRU}, and LRU membership implies committed.  The fuzz
        and chaos suites call this after every interleaving — a leak
        or double-free fails here, not three allocations later.
        Returns True so callers can write ``assert m.check_books()``."""
        free = set(self._free)
        assert len(free) == len(self._free), "free list holds dupes"
        live = {int(b) for b in np.nonzero(self._ref > 0)[0]}
        lru = {bid for bid in range(self.num_blocks)
               if self.index.in_lru(bid)}
        assert 0 not in free | live | lru, "trash sink was allocated"
        assert not (free & live), f"free AND referenced: {free & live}"
        assert not (free & lru), f"free AND lingering: {free & lru}"
        assert not (live & lru), f"referenced AND lingering: {live & lru}"
        every = free | live | lru
        expect = set(range(1, self.num_blocks))
        assert every == expect, (
            f"leaked blocks: {sorted(expect - every)}; "
            f"phantom blocks: {sorted(every - expect)}")
        for bid in lru:
            assert self.index.is_committed(bid), (
                f"uncommitted block {bid} lingering in LRU")
        return True


# ---------------------------------------------------------------- device
def gather_blocks(pool: jax.Array, table: jax.Array) -> jax.Array:
    """``pool [NB, bs, KV, D] x table [B, MB] -> [B, MB*bs, KV, D]`` —
    the dense per-slot view the attention kernels consume."""
    b, mb = table.shape
    g = jnp.take(pool, table, axis=0)          # [B, MB, bs, KV, D]
    return g.reshape(b, mb * pool.shape[1], *pool.shape[2:])


def _block_offsets(table: jax.Array, positions: jax.Array,
                   k: int, bs: int,
                   skip_upto: Optional[jax.Array] = None):
    """``(block_id [B, K], offset [B, K])`` for K consecutive positions
    per slot.  Positions BEYOND the table row route to block 0 (the
    trash sink) instead of gather-clamping to the last column: a
    clamped write would land in the row's LAST listed block at a
    wrapped offset — which for a full-length sequence is a LIVE block
    — whereas parked/inactive slots (chunked prefill holds a slot
    mid-prompt while decode keeps dispatching) legitimately emit
    out-of-range junk positions that must go nowhere.

    ``skip_upto`` [B] is the COW write mask: positions BELOW it are
    served by shared prefix blocks (refcount > 1 — read-only by the
    copy-on-write contract), so their writes route to the trash sink
    too.  The trash detour is cheaper and simpler than predicating the
    scatter itself, and the VALUES being suppressed are recomputed
    bit-identical anyway — the mask exists so a numerically-divergent
    rewrite (different batch geometry) can never perturb a block
    another live sequence is reading."""
    mb = table.shape[1]
    pos = positions[:, None] + jnp.arange(k)[None, :]        # [B, K]
    col = pos // bs
    bidx = jnp.take_along_axis(
        table, jnp.minimum(col, mb - 1), axis=1)             # [B, K]
    bidx = jnp.where(col < mb, bidx, 0)
    if skip_upto is not None:
        bidx = jnp.where(pos < skip_upto[:, None], 0, bidx)
    return bidx, pos % bs


def scatter_tokens(
    pool: jax.Array,        # [NB, bs, KV, D]
    table: jax.Array,       # [B, MB]
    kv: jax.Array,          # [B, K, KV, D] new entries
    positions: jax.Array,   # [B] position of kv[:, 0]
    skip_upto: Optional[jax.Array] = None,  # [B] COW write mask
) -> jax.Array:
    """Write K consecutive tokens per slot into their blocks."""
    bs = pool.shape[1]
    b, k = kv.shape[:2]
    bidx, off = _block_offsets(table, positions, k, bs, skip_upto)
    return pool.at[bidx.reshape(-1), off.reshape(-1)].set(
        kv.reshape(b * k, *kv.shape[2:])
    )


# -------------------------------------------------- quantized KV pools
def kv_budget_multiplier(ref_dtype, head_dim: int,
                         kv_dtype: str = "int8") -> float:
    """THE single source of KV-budget math: how many ``kv_dtype``
    blocks fit in the HBM of one ``ref_dtype`` block.  A quantized
    (token, head) vector costs its code bytes (``D`` for int8, ``D/2``
    for packed int4) plus one ``KV_SCALE_DTYPE`` scale —
    ``D * itemsize(ref) / (code_bytes + itemsize(scale))``.

    bf16 references: int8 -> 1.94x @ D=64 / 1.97x @ D=128; int4 ->
    3.76x @ D=64 / 3.88x @ D=128 (the >= 3.5x acceptance bar).

    Everything downstream derives from THIS function — the engine
    multiplies its HBM-denominated ``cache_blocks`` budget by it
    (``InferenceEngine.kv_budget_x``), ``alloc_sequence`` admits
    against the multiplied pool, and the router's placement ledger
    (``InferenceEngineAdapter.blocks_free`` locally, the worker's
    HELLO/STATS ``blocks_free`` remotely) reads the same pool — so the
    engine's admission and the router's placement can never disagree
    on what a quantized pool holds (regression-tested in
    tests/test_paged_kernel.py)."""
    from dlrover_tpu.models.quantize import KV_SCALE_DTYPE

    if kv_dtype in (None, "bf16"):
        return 1.0
    code_bytes = {"int8": float(head_dim),
                  "int4": head_dim / 2.0}.get(kv_dtype)
    if code_bytes is None:
        raise ValueError(f"kv_budget_multiplier: unknown kv_dtype "
                         f"{kv_dtype!r}")
    ref = int(head_dim) * jnp.dtype(ref_dtype).itemsize
    return ref / (code_bytes + jnp.dtype(KV_SCALE_DTYPE).itemsize)


def scatter_tokens_q(
    pool: jax.Array,        # [NB, bs, KV, D] int8 codes
    scale_pool: jax.Array,  # [NB, bs, KV] per-vector scales
    table: jax.Array,       # [B, MB]
    kv: jax.Array,          # [B, K, KV, D] new fp entries
    positions: jax.Array,   # [B]
    skip_upto: Optional[jax.Array] = None,  # [B] COW write mask
):
    """Quantize-and-write K consecutive tokens per slot: codes into the
    int8 pool, per-(token, head) scales into the block-shaped scale
    pool (same index math, so a write is always self-consistent)."""
    from dlrover_tpu.models.quantize import quantize_kv_int8

    bs = pool.shape[1]
    b, k = kv.shape[:2]
    q, scale = quantize_kv_int8(kv)
    bidx, off = _block_offsets(table, positions, k, bs, skip_upto)
    flat_b, flat_o = bidx.reshape(-1), off.reshape(-1)
    return (
        pool.at[flat_b, flat_o].set(q.reshape(b * k, *q.shape[2:])),
        scale_pool.at[flat_b, flat_o].set(
            scale.reshape(b * k, *scale.shape[2:])),
    )


def gather_blocks_q(
    pool: jax.Array,        # [NB, bs, KV, D] int8 codes
    scale_pool: jax.Array,  # [NB, bs, KV]
    table: jax.Array,       # [B, MB]
    dtype,
) -> jax.Array:
    """Dense ``[B, MB*bs, KV, D]`` dequantized view of int8 pools — the
    dequant fuses into the consuming attention reads, so the pool
    streams from HBM at int8 width (the whole point: KV budget is what
    caps the continuous batch)."""
    from dlrover_tpu.models.quantize import dequantize_kv_int8

    b, mb = table.shape
    g = jnp.take(pool, table, axis=0)          # [B, MB, bs, KV, D]
    s = jnp.take(scale_pool, table, axis=0)    # [B, MB, bs, KV]
    return dequantize_kv_int8(
        g.reshape(b, mb * pool.shape[1], *pool.shape[2:]),
        s.reshape(b, mb * pool.shape[1], *s.shape[3:]),
        dtype,
    )


def scatter_tokens_q4(
    pool: jax.Array,        # [NB, bs, KV, D//2] packed int4 codes
    scale_pool: jax.Array,  # [NB, bs, KV] per-vector scales
    table: jax.Array,       # [B, MB]
    kv: jax.Array,          # [B, K, KV, D] new fp entries
    positions: jax.Array,   # [B]
    skip_upto: Optional[jax.Array] = None,  # [B] COW write mask
):
    """int4 twin of :func:`scatter_tokens_q`: quantize-pack-and-write K
    consecutive tokens per slot (codes at half a byte per element,
    per-(token, head) scales in the block-shaped scale pool — same
    index math, so a write is always self-consistent)."""
    from dlrover_tpu.models.quantize import quantize_kv_int4

    bs = pool.shape[1]
    b, k = kv.shape[:2]
    q, scale = quantize_kv_int4(kv)
    bidx, off = _block_offsets(table, positions, k, bs, skip_upto)
    flat_b, flat_o = bidx.reshape(-1), off.reshape(-1)
    return (
        pool.at[flat_b, flat_o].set(q.reshape(b * k, *q.shape[2:])),
        scale_pool.at[flat_b, flat_o].set(
            scale.reshape(b * k, *scale.shape[2:])),
    )


def gather_blocks_q4(
    pool: jax.Array,        # [NB, bs, KV, D//2] packed int4 codes
    scale_pool: jax.Array,  # [NB, bs, KV]
    table: jax.Array,       # [B, MB]
    dtype,
) -> jax.Array:
    """Dense ``[B, MB*bs, KV, D]`` dequantized view of packed int4
    pools — unpack + dequant fuse into the consuming attention reads,
    so the pool streams from HBM at 0.5 bytes/element (the fused
    Pallas kernel goes further and never materializes this view at
    all; this is the XLA fallback path)."""
    from dlrover_tpu.models.quantize import dequantize_kv_int4

    b, mb = table.shape
    g = jnp.take(pool, table, axis=0)          # [B, MB, bs, KV, D//2]
    s = jnp.take(scale_pool, table, axis=0)    # [B, MB, bs, KV]
    return dequantize_kv_int4(
        g.reshape(b, mb * pool.shape[1], *pool.shape[2:]),
        s.reshape(b, mb * pool.shape[1], *s.shape[3:]),
        dtype,
    )
