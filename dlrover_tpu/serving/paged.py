"""Paged KV cache: block-pool memory management for the serving engine.

What vLLM gives the reference's RL rollouts (reference:
atorch/atorch/rl/inference_backend/vllm_backend.py:11-24 — paged
attention, prefix reuse), rebuilt TPU-style:

- **block pool**: per layer, K/V live in ``[num_blocks, block_size,
  KV, D]`` pools; a sequence owns a LIST of blocks instead of a dense
  ``max_len`` stripe, so cache memory scales with actual sequence
  lengths and concurrency is bounded by the pool (HBM budget), not by
  ``slots x max_len`` worst-case reservations.
- **prefix caching**: the leading FULL prompt blocks are content-hashed
  (chained, so a hit guarantees the whole prefix matches); admissions
  reuse hit blocks refcounted, and fully-released prefix blocks linger
  in an LRU until the allocator actually needs them — repeated system
  prompts cost their KV once.
- **static shapes**: the device side sees a fixed ``[slots,
  max_blocks]`` int32 table and fixed pools; only the HOST manager is
  dynamic.  Reads gather ``pool[table]`` back to the dense ``[B, L,
  KV, D]`` view the attention kernels already handle — correctness
  first (the gather is XLA-fused with the attention reads); a fused
  Pallas paged-attention kernel is the optimization seam.

Writes into SHARED (refcount > 1) prefix blocks are allowed and
harmless by construction: a shared block is always a full prompt block
whose content is a deterministic function of the same tokens, so any
writer rewrites identical values.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _chain_key(prev: bytes, tok_bytes: bytes) -> bytes:
    """Chained prefix-block key: a stable 128-bit blake2b digest.  Python's
    ``hash()`` is only 64-bit and salted per process — a collision would
    silently alias two different prefixes to one block and corrupt a live
    sequence's attention, and salting breaks cross-restart stability."""
    return hashlib.blake2b(prev + tok_bytes, digest_size=16).digest()


class BlockManager:
    """Host-side pool bookkeeping: allocation, refcounts, prefix LRU."""

    def __init__(self, num_blocks: int, block_size: int):
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # block 0 is the TRASH SINK, never allocated: the decode step
        # computes (and writes) junk KV for INACTIVE slots too — their
        # all-zero table rows must route those writes somewhere no live
        # sequence reads (the dense layout absorbs this in the dead
        # slot's own row; paging needs the sentinel)
        self._free: List[int] = list(range(1, num_blocks))[::-1]
        self._ref = np.zeros(num_blocks, np.int32)
        # chain-digest -> block id for full prompt blocks currently in
        # the pool (referenced or lingering)
        self._prefix: Dict[bytes, int] = {}
        self._block_hash: Dict[int, bytes] = {}
        # block id -> the raw token bytes it holds: a hit is only trusted
        # after the content check (belt-and-braces on top of the 128-bit
        # key — a false hit must never alias blocks)
        self._block_tokens: Dict[int, bytes] = {}
        # fully-released prefix blocks, oldest first (evictable)
        self._lru: "OrderedDict[int, None]" = OrderedDict()

    # ------------------------------------------------------------ alloc
    @property
    def available_blocks(self) -> int:
        return len(self._free) + len(self._lru)

    def _take_block(self) -> Optional[int]:
        if self._free:
            return self._free.pop()
        if self._lru:  # evict the oldest lingering prefix block
            bid, _ = self._lru.popitem(last=False)
            self._block_tokens.pop(bid, None)
            h = self._block_hash.pop(bid, None)
            # the chain hash may have been RE-registered to a newer
            # block after this one was orphaned — only drop the mapping
            # if it still points at the block being evicted
            if h is not None and self._prefix.get(h) == bid:
                self._prefix.pop(h, None)
            return bid
        return None

    def alloc_sequence(
        self, prompt: np.ndarray, total_len: int
    ) -> Optional[Tuple[List[int], int]]:
        """Blocks for a sequence of ``total_len`` positions whose first
        ``len(prompt)`` tokens are known: returns ``(blocks,
        shared_tokens)`` where the first ``shared_tokens`` positions
        are served by refcount-bumped prefix-cache hits, or None when
        the pool cannot cover the request (caller keeps it queued)."""
        bs = self.block_size
        prompt = np.asarray(prompt).reshape(-1)
        n_blocks = -(-max(int(total_len), 1) // bs)
        # enforce total_len >= len(prompt) at the API boundary: a shorter
        # total_len would otherwise let len(shared) exceed n_blocks and
        # the returned list overflow the engine's fixed table row
        full_prompt_blocks = min(prompt.size // bs, n_blocks)

        shared: List[Tuple[bytes, int]] = []
        chain = b""
        for i in range(full_prompt_blocks):
            tok_bytes = prompt[i * bs:(i + 1) * bs].tobytes()
            chain = _chain_key(chain, tok_bytes)
            bid = self._prefix.get(chain)
            if bid is None or self._block_tokens.get(bid) != tok_bytes:
                break
            shared.append((chain, bid))
        need = n_blocks - len(shared)
        # reviving a shared hit that currently lingers in the LRU also
        # consumes availability (it leaves the evictable set) — without
        # counting those, the guard can pass and _take_block() then come
        # up empty mid-allocation
        revived = sum(1 for _, bid in shared if self._ref[bid] == 0)
        if need > self.available_blocks - revived:
            return None
        blocks: List[int] = []
        for chain_h, bid in shared:
            if self._ref[bid] == 0:
                self._lru.pop(bid, None)  # revive a lingering block
            self._ref[bid] += 1
            blocks.append(bid)
        chain = shared[-1][0] if shared else b""
        for i in range(len(shared), n_blocks):
            bid = self._take_block()
            assert bid is not None  # guarded by available_blocks above
            self._ref[bid] = 1
            blocks.append(bid)
            if i < full_prompt_blocks:
                tok_bytes = prompt[i * bs:(i + 1) * bs].tobytes()
                chain = _chain_key(chain, tok_bytes)
                self._prefix[chain] = bid
                self._block_hash[bid] = chain
                self._block_tokens[bid] = tok_bytes
        return blocks, len(shared) * bs

    def free_sequence(self, blocks: List[int]) -> None:
        for bid in blocks:
            self._ref[bid] -= 1
            assert self._ref[bid] >= 0
            if self._ref[bid] == 0:
                if bid in self._block_hash:
                    # prefix block: linger in the LRU for reuse
                    self._lru[bid] = None
                    self._lru.move_to_end(bid)
                else:
                    self._free.append(bid)


# ---------------------------------------------------------------- device
def gather_blocks(pool: jax.Array, table: jax.Array) -> jax.Array:
    """``pool [NB, bs, KV, D] x table [B, MB] -> [B, MB*bs, KV, D]`` —
    the dense per-slot view the attention kernels consume."""
    b, mb = table.shape
    g = jnp.take(pool, table, axis=0)          # [B, MB, bs, KV, D]
    return g.reshape(b, mb * pool.shape[1], *pool.shape[2:])


def _block_offsets(table: jax.Array, positions: jax.Array,
                   k: int, bs: int):
    """``(block_id [B, K], offset [B, K])`` for K consecutive positions
    per slot.  Positions BEYOND the table row route to block 0 (the
    trash sink) instead of gather-clamping to the last column: a
    clamped write would land in the row's LAST listed block at a
    wrapped offset — which for a full-length sequence is a LIVE block
    — whereas parked/inactive slots (chunked prefill holds a slot
    mid-prompt while decode keeps dispatching) legitimately emit
    out-of-range junk positions that must go nowhere."""
    mb = table.shape[1]
    pos = positions[:, None] + jnp.arange(k)[None, :]        # [B, K]
    col = pos // bs
    bidx = jnp.take_along_axis(
        table, jnp.minimum(col, mb - 1), axis=1)             # [B, K]
    bidx = jnp.where(col < mb, bidx, 0)
    return bidx, pos % bs


def scatter_tokens(
    pool: jax.Array,        # [NB, bs, KV, D]
    table: jax.Array,       # [B, MB]
    kv: jax.Array,          # [B, K, KV, D] new entries
    positions: jax.Array,   # [B] position of kv[:, 0]
) -> jax.Array:
    """Write K consecutive tokens per slot into their blocks."""
    bs = pool.shape[1]
    b, k = kv.shape[:2]
    bidx, off = _block_offsets(table, positions, k, bs)
    return pool.at[bidx.reshape(-1), off.reshape(-1)].set(
        kv.reshape(b * k, *kv.shape[2:])
    )


# -------------------------------------------------- quantized KV pools
def kv_budget_multiplier(ref_dtype, head_dim: int,
                         kv_dtype: str = "int8") -> float:
    """THE single source of KV-budget math: how many ``kv_dtype``
    blocks fit in the HBM of one ``ref_dtype`` block.  A quantized
    (token, head) vector costs its code bytes (``D`` for int8, ``D/2``
    for packed int4) plus one ``KV_SCALE_DTYPE`` scale —
    ``D * itemsize(ref) / (code_bytes + itemsize(scale))``.

    bf16 references: int8 -> 1.94x @ D=64 / 1.97x @ D=128; int4 ->
    3.76x @ D=64 / 3.88x @ D=128 (the >= 3.5x acceptance bar).

    Everything downstream derives from THIS function — the engine
    multiplies its HBM-denominated ``cache_blocks`` budget by it
    (``InferenceEngine.kv_budget_x``), ``alloc_sequence`` admits
    against the multiplied pool, and the router's placement ledger
    (``InferenceEngineAdapter.blocks_free`` locally, the worker's
    HELLO/STATS ``blocks_free`` remotely) reads the same pool — so the
    engine's admission and the router's placement can never disagree
    on what a quantized pool holds (regression-tested in
    tests/test_paged_kernel.py)."""
    from dlrover_tpu.models.quantize import KV_SCALE_DTYPE

    if kv_dtype in (None, "bf16"):
        return 1.0
    code_bytes = {"int8": float(head_dim),
                  "int4": head_dim / 2.0}.get(kv_dtype)
    if code_bytes is None:
        raise ValueError(f"kv_budget_multiplier: unknown kv_dtype "
                         f"{kv_dtype!r}")
    ref = int(head_dim) * jnp.dtype(ref_dtype).itemsize
    return ref / (code_bytes + jnp.dtype(KV_SCALE_DTYPE).itemsize)


def scatter_tokens_q(
    pool: jax.Array,        # [NB, bs, KV, D] int8 codes
    scale_pool: jax.Array,  # [NB, bs, KV] per-vector scales
    table: jax.Array,       # [B, MB]
    kv: jax.Array,          # [B, K, KV, D] new fp entries
    positions: jax.Array,   # [B]
):
    """Quantize-and-write K consecutive tokens per slot: codes into the
    int8 pool, per-(token, head) scales into the block-shaped scale
    pool (same index math, so a write is always self-consistent)."""
    from dlrover_tpu.models.quantize import quantize_kv_int8

    bs = pool.shape[1]
    b, k = kv.shape[:2]
    q, scale = quantize_kv_int8(kv)
    bidx, off = _block_offsets(table, positions, k, bs)
    flat_b, flat_o = bidx.reshape(-1), off.reshape(-1)
    return (
        pool.at[flat_b, flat_o].set(q.reshape(b * k, *q.shape[2:])),
        scale_pool.at[flat_b, flat_o].set(
            scale.reshape(b * k, *scale.shape[2:])),
    )


def gather_blocks_q(
    pool: jax.Array,        # [NB, bs, KV, D] int8 codes
    scale_pool: jax.Array,  # [NB, bs, KV]
    table: jax.Array,       # [B, MB]
    dtype,
) -> jax.Array:
    """Dense ``[B, MB*bs, KV, D]`` dequantized view of int8 pools — the
    dequant fuses into the consuming attention reads, so the pool
    streams from HBM at int8 width (the whole point: KV budget is what
    caps the continuous batch)."""
    from dlrover_tpu.models.quantize import dequantize_kv_int8

    b, mb = table.shape
    g = jnp.take(pool, table, axis=0)          # [B, MB, bs, KV, D]
    s = jnp.take(scale_pool, table, axis=0)    # [B, MB, bs, KV]
    return dequantize_kv_int8(
        g.reshape(b, mb * pool.shape[1], *pool.shape[2:]),
        s.reshape(b, mb * pool.shape[1], *s.shape[3:]),
        dtype,
    )


def scatter_tokens_q4(
    pool: jax.Array,        # [NB, bs, KV, D//2] packed int4 codes
    scale_pool: jax.Array,  # [NB, bs, KV] per-vector scales
    table: jax.Array,       # [B, MB]
    kv: jax.Array,          # [B, K, KV, D] new fp entries
    positions: jax.Array,   # [B]
):
    """int4 twin of :func:`scatter_tokens_q`: quantize-pack-and-write K
    consecutive tokens per slot (codes at half a byte per element,
    per-(token, head) scales in the block-shaped scale pool — same
    index math, so a write is always self-consistent)."""
    from dlrover_tpu.models.quantize import quantize_kv_int4

    bs = pool.shape[1]
    b, k = kv.shape[:2]
    q, scale = quantize_kv_int4(kv)
    bidx, off = _block_offsets(table, positions, k, bs)
    flat_b, flat_o = bidx.reshape(-1), off.reshape(-1)
    return (
        pool.at[flat_b, flat_o].set(q.reshape(b * k, *q.shape[2:])),
        scale_pool.at[flat_b, flat_o].set(
            scale.reshape(b * k, *scale.shape[2:])),
    )


def gather_blocks_q4(
    pool: jax.Array,        # [NB, bs, KV, D//2] packed int4 codes
    scale_pool: jax.Array,  # [NB, bs, KV]
    table: jax.Array,       # [B, MB]
    dtype,
) -> jax.Array:
    """Dense ``[B, MB*bs, KV, D]`` dequantized view of packed int4
    pools — unpack + dequant fuse into the consuming attention reads,
    so the pool streams from HBM at 0.5 bytes/element (the fused
    Pallas kernel goes further and never materializes this view at
    all; this is the XLA fallback path)."""
    from dlrover_tpu.models.quantize import dequantize_kv_int4

    b, mb = table.shape
    g = jnp.take(pool, table, axis=0)          # [B, MB, bs, KV, D//2]
    s = jnp.take(scale_pool, table, axis=0)    # [B, MB, bs, KV]
    return dequantize_kv_int4(
        g.reshape(b, mb * pool.shape[1], *pool.shape[2:]),
        s.reshape(b, mb * pool.shape[1], *s.shape[3:]),
        dtype,
    )
