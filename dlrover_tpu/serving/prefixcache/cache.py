"""Content-addressed prefix-block index: the engine half of the cache.

``BlockManager`` (serving/paged.py) owns the block POOL — ids, the
free list, refcounts.  This module owns everything about which blocks
are COMMITTED PREFIX blocks: the chained content digests, the
belt-and-braces token-byte verification, the ref-0 LRU that lets
released prefix KV linger until the allocator actually needs the
space, hot-HEAD tracking (which first-block digests are being hit —
the advertisement the router's :class:`~dlrover_tpu.serving.
prefixcache.table.PrefixRoutingTable` is fed from), and the stats
ledger (hits/misses/evictions/COW copies) the ``serving_prefix_*``
metrics export.

Keys are CHAINED: block i's digest covers blocks 0..i, so a hit
guarantees the whole prefix matches, not just one block.  A HEAD is
the depth-1 digest (the first ``block_size`` tokens) — the router
routes on heads because a head hit is a necessary condition for any
deeper chain hit.

Everything here is host-side in-memory bookkeeping driven by the
engine's single-threaded step loop — no locks, no I/O (dlint
DL003/DL007 stay trivially clean).
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np


def chain_key(prev: bytes, tok_bytes: bytes) -> bytes:
    """Chained prefix-block key: a stable 128-bit blake2b digest.
    Python's ``hash()`` is only 64-bit and salted per process — a
    collision would silently alias two different prefixes to one block
    and corrupt a live sequence's attention, and salting breaks
    cross-restart stability (heads must match ACROSS replicas so the
    router can route on them)."""
    return hashlib.blake2b(prev + tok_bytes, digest_size=16).digest()


def head_key(prompt, block_size: int) -> Optional[str]:
    """The HEAD digest of a prompt: the depth-1 chain key over its
    first ``block_size`` tokens, hex-encoded (the wire/advertisement
    form).  None when the prompt does not cover one full block — such
    a prompt can never hit the prefix cache, so it has no head.

    Tokens are normalized to int32 before hashing: the engine's
    ``alloc_sequence`` hashes int32 token bytes, and the scheduler
    computing a head from a client-provided array of any integer
    dtype MUST land on the same digest or routing never matches."""
    prompt = np.asarray(prompt, np.int32).reshape(-1)
    if prompt.size < block_size:
        return None
    return chain_key(b"", prompt[:block_size].tobytes()).hex()


class PrefixBlockIndex:
    """Index of committed prefix blocks for one block pool.

    The owning ``BlockManager`` calls in with bare block ids; this
    class never allocates or refcounts — it only remembers which ids
    currently hold which verified prefix content, which of those are
    evictable (ref 0), and what happened (the stats ledger)."""

    #: evicted-head digests kept for the next STATS advertisement
    #: drain — bounded so a cache-thrashing replica cannot grow an
    #: unbounded list between drains
    MAX_EVICTED_HEADS = 256

    def __init__(self) -> None:
        # chain digest -> block id for full prompt blocks currently in
        # the pool (referenced or lingering)
        self._prefix: Dict[bytes, int] = {}
        self._block_hash: Dict[int, bytes] = {}
        # block id -> the raw token bytes it holds: a hit is only
        # trusted after the content check (belt-and-braces on top of
        # the 128-bit key — a false hit must never alias blocks)
        self._block_tokens: Dict[int, bytes] = {}
        # fully-released prefix blocks, oldest first (evictable)
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        # HEAD tracking: block id -> hex head digest for depth-1
        # blocks, and hit counts per head (the advertisement ranking)
        self._head_of: Dict[int, str] = {}
        self._head_hits: Dict[str, int] = {}
        self._evicted_heads: List[str] = []
        # ---- stats ledger (exported as serving_prefix_* metrics)
        self.hits = 0            # full prompt blocks served by a hit
        self.misses = 0          # full prompt blocks that had to be built
        self.evictions = 0       # committed blocks evicted from the LRU
        self.cow_copies = 0      # divergence copies (BlockManager.cow_block)
        self.revivals = 0        # ref-0 lingering blocks revived by a hit
        self.shared_tokens = 0   # cumulative prompt tokens served shared
        self.lingers = 0         # blocks parked evictable at ref 0
        self.forgotten = 0       # registrations dropped NOT via eviction
        #                          (COW privatization, cancelled writers)
        self.evicted_head_drops = 0  # head invalidations lost to the
        #                          staging cap (router keeps a stale
        #                          route until its TTL — visible, not
        #                          silent)

    # ------------------------------------------------------------ lookup
    def lookup(self, chain: bytes, tok_bytes: bytes) -> Optional[int]:
        """Content-verified hit: the committed block holding exactly
        ``tok_bytes`` under digest ``chain``, or None."""
        bid = self._prefix.get(chain)
        if bid is None or self._block_tokens.get(bid) != tok_bytes:
            return None
        return bid

    def note_hit(self, bid: int, tokens: int) -> None:
        self.hits += 1
        self.shared_tokens += tokens
        head = self._head_of.get(bid)
        if head is not None:
            self._head_hits[head] = self._head_hits.get(head, 0) + 1

    def note_miss(self) -> None:
        self.misses += 1

    def note_cow(self) -> None:
        self.cow_copies += 1

    # ---------------------------------------------------------- register
    def register(self, chain: bytes, bid: int, tok_bytes: bytes,
                 head: bool) -> None:
        """Commit ``bid`` as the block holding ``tok_bytes`` under
        ``chain``.  ``head`` marks a depth-1 block (advertisable)."""
        self._prefix[chain] = bid
        self._block_hash[bid] = chain
        self._block_tokens[bid] = tok_bytes
        if head:
            hx = chain.hex()
            self._head_of[bid] = hx
            self._head_hits.setdefault(hx, 0)

    def is_committed(self, bid: int) -> bool:
        return bid in self._block_hash

    def committed_count(self) -> int:
        return len(self._block_hash)

    def forget(self, bid: int, evicted: bool = False) -> None:
        """Drop every registration of ``bid`` (COW privatization, or
        eviction cleanup).  ``evicted`` stages the block's head (when
        it was one) for the next advertisement drain so the router
        invalidates its routing entry."""
        self._block_tokens.pop(bid, None)
        h = self._block_hash.pop(bid, None)
        if h is not None and not evicted:
            # eviction has its own counter (evict_one); this one counts
            # the other registration-dropping paths
            self.forgotten += 1
        # the chain hash may have been RE-registered to a newer block
        # after this one was orphaned — only drop the mapping if it
        # still points at the block being forgotten
        if h is not None and self._prefix.get(h) == bid:
            self._prefix.pop(h, None)
        self._lru.pop(bid, None)
        head = self._head_of.pop(bid, None)
        if head is not None:
            self._head_hits.pop(head, None)
            if evicted:
                if len(self._evicted_heads) < self.MAX_EVICTED_HEADS:
                    self._evicted_heads.append(head)
                else:
                    self.evicted_head_drops += 1

    # --------------------------------------------------------------- lru
    def linger(self, bid: int) -> None:
        """A committed block's refcount reached 0: evictable, newest
        last."""
        if bid not in self._lru:
            # a re-linger only refreshes recency; the counter tracks
            # distinct park events so lingers - (revivals + evictions)
            # stays reconcilable with the lru_blocks gauge
            self.lingers += 1
        self._lru[bid] = None
        self._lru.move_to_end(bid)

    def revive(self, bid: int) -> None:
        """A lingering block was hit again: back to referenced."""
        # membership test, not pop-default: the stored VALUE is None,
        # so pop(bid, None) could not tell a hit from a miss
        if bid not in self._lru:
            return
        del self._lru[bid]
        self.revivals += 1

    def lru_count(self) -> int:
        return len(self._lru)

    def in_lru(self, bid: int) -> bool:
        return bid in self._lru

    def evict_one(self) -> Optional[int]:
        """Evict the OLDEST lingering block (the allocator needs the
        space); returns its id or None when nothing lingers."""
        if not self._lru:
            return None
        bid, _ = self._lru.popitem(last=False)
        self.evictions += 1
        self.forget(bid, evicted=True)
        return bid

    # ------------------------------------------------------------- heads
    def hot_heads(self, n: int = 8) -> List[str]:
        """The ``n`` most-hit head digests still committed in the pool
        — what this replica advertises over STATS."""
        live = [(hits, hx) for hx, hits in self._head_hits.items()]
        live.sort(reverse=True)
        return [hx for _, hx in live[:n]]

    def drain_evicted_heads(self) -> List[str]:
        """Heads evicted since the last drain (advertised so the
        router drops their routing entries); clears the list."""
        out, self._evicted_heads = self._evicted_heads, []
        return out

    # ------------------------------------------------------------- stats
    def stats(self) -> Dict[str, float]:
        return {
            "prefix_hits": float(self.hits),
            "prefix_misses": float(self.misses),
            "prefix_evictions": float(self.evictions),
            "prefix_cow": float(self.cow_copies),
            "prefix_revivals": float(self.revivals),
            "prefix_shared_tokens": float(self.shared_tokens),
            "prefix_lingers": float(self.lingers),
            "prefix_forgotten": float(self.forgotten),
            "prefix_evicted_head_drops": float(self.evicted_head_drops),
            "prefix_cached_blocks": float(len(self._block_hash)),
            "prefix_lru_blocks": float(len(self._lru)),
        }
