"""Prefix routing table: the router half of the global prefix cache.

A bounded head-digest -> replica map the scheduler consults BEFORE its
generic prefix-affinity heuristic: affinity remembers where a prefix
was last PLACED, this table knows where its KV blocks are actually
RESIDENT right now — replicas advertise their hottest committed heads
over STATS every step, and each advertisement REPLACES the replica's
previous set (generation semantics: a head missing from the newest
advertisement was evicted engine-side, so its entry drops immediately
instead of aging out).

Invalidation paths:

- replica death / drain / retirement: ``forget_replica`` (called from
  ``ContinuousBatchScheduler.forget_replica``, which both the reap and
  the retire paths already hit) drops every entry pointing at it;
- advertised eviction: replacement semantics above;
- capacity: a global LRU over heads bounds the table at ``cap``
  entries whatever the fleet advertises.

Plain dict/OrderedDict bookkeeping mutated only under the router's
step lock — no locks of its own, no I/O.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, Iterable, List, Optional, Set


class PrefixRoutingTable:
    """Bounded, generation-aware head -> replica routing map."""

    def __init__(self, cap: int = 1024):
        self.cap = int(cap)
        # head digest (hex) -> replica name, LRU-ordered (oldest first)
        self._heads: "OrderedDict[str, str]" = OrderedDict()
        # replica -> the head set of its LATEST advertisement
        self._by_replica: Dict[str, Set[str]] = {}
        # advertisement generation per replica (introspection: a stale
        # entry is impossible by construction, but tests pin that the
        # generation actually advanced)
        self._gen: Dict[str, int] = {}
        self.hits = 0
        self.misses = 0
        self.invalidations = 0

    # ---------------------------------------------------------- feeding
    def advertise(self, replica: str, heads: Iterable[str]) -> None:
        """One replica's newest hot-head set.  REPLACES its previous
        advertisement: heads it no longer lists were evicted on the
        engine and their routing entries drop now."""
        new = []
        seen: Set[str] = set()
        for h in heads:
            if h not in seen:
                seen.add(h)
                new.append(h)
        old = self._by_replica.get(replica, set())
        for h in old - seen:
            if self._heads.get(h) == replica:
                del self._heads[h]
                self.invalidations += 1
        for h in new:
            # last advertiser wins: with COW sharing the SAME head can
            # be hot on several replicas; any of them is a warm target
            self._heads[h] = replica
            self._heads.move_to_end(h)
        self._by_replica[replica] = seen
        self._gen[replica] = self._gen.get(replica, 0) + 1
        while len(self._heads) > self.cap:
            h, owner = self._heads.popitem(last=False)
            owned = self._by_replica.get(owner)
            if owned is not None:
                owned.discard(h)

    def forget_replica(self, replica: str) -> None:
        """Replica left the fleet (death, drain, retirement): every
        entry pointing at it is now a route to nowhere — drop them."""
        for h in self._by_replica.pop(replica, set()):
            if self._heads.get(h) == replica:
                del self._heads[h]
                self.invalidations += 1
        self._gen.pop(replica, None)

    # ---------------------------------------------------------- queries
    def lookup(self, head: Optional[str]) -> Optional[str]:
        """Where is this head's KV resident?  None on miss (or for a
        headless prompt).  A hit refreshes the entry's LRU position."""
        if head is None:
            return None
        replica = self._heads.get(head)
        if replica is None:
            self.misses += 1
            return None
        self.hits += 1
        self._heads.move_to_end(head)
        return replica

    def generation(self, replica: str) -> int:
        return self._gen.get(replica, 0)

    def heads_of(self, replica: str) -> List[str]:
        return sorted(self._by_replica.get(replica, set()))

    def __len__(self) -> int:
        return len(self._heads)

    def stats(self) -> Dict[str, float]:
        return {
            "prefix_route_entries": float(len(self._heads)),
            "prefix_route_hits": float(self.hits),
            "prefix_route_misses": float(self.misses),
            "prefix_route_invalidations": float(self.invalidations),
        }
