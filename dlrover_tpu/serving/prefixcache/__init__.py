"""Global prefix cache: shared KV prefix blocks + prefix-aware routing.

Two halves of one story — N requests carrying the same prompt prefix
(a system prompt, a chat session's history) should pay for its KV
once, fleet-wide:

- :mod:`cache` — the ENGINE half: :func:`chain_key` /
  :func:`head_key` (the chained blake2b content addressing shared
  with ``serving/paged.py``) and :class:`PrefixBlockIndex`, the
  host-side index of committed prefix blocks (content-verified
  lookup, ref-0 LRU linger, hot-head tracking, the COW/hit/eviction
  stats ledger).  ``paged.BlockManager`` owns block ids and
  refcounts and delegates every committed-prefix decision here.
- :mod:`table` — the ROUTER half: :class:`PrefixRoutingTable`, a
  bounded prefix-head -> replica map fed by STATS advertisements
  (each replica's hottest committed heads), consulted by the
  scheduler AHEAD of the generic affinity heuristic and invalidated
  on replica death/drain and on advertised eviction.

No router or engine imports here (both sides import THIS package),
so the dependency arrow stays one-way.
"""

from dlrover_tpu.serving.prefixcache.cache import (  # noqa: F401
    PrefixBlockIndex,
    chain_key,
    head_key,
)
from dlrover_tpu.serving.prefixcache.table import (  # noqa: F401
    PrefixRoutingTable,
)
