"""Continuous-batching inference engine (prefill/decode split).

The TPU counterpart of the reference's vLLM inference backend for RL
rollouts (atorch/atorch/rl/inference_backend/vllm_backend.py:11-24) and
its generation config (rl/model_utils/vllm_utils.py): a slotted decode
batch that sequences enter and leave independently —

- ``max_slots`` concurrent sequences decode as ONE jitted step batch;
- a finished slot (EOS / budget) is refilled from the request queue by
  a bucketed prefill WITHOUT stopping the other slots (continuous
  batching, the Orca/vLLM scheduling model);
- decode runs in chunks of ``chunk`` tokens per host sync (multi-step
  scheduling) — sampling stays on-device inside a ``lax.scan``;
- ``int8=True`` serves pre-quantized int8 weights through XLA's native
  int8 MXU dot (weights stream from HBM at half the bf16 bytes — decode
  is bandwidth-bound, so this is the serving speedup; measured against
  the hand-tiled Pallas alternative in
  benchmarks/probes/int8_decode_probe*, the native dot wins at every
  serving shape).

Static shapes everywhere: prompts right-pad to power-of-two buckets,
the decode batch is fixed at ``max_slots``, EOS only masks. One compile
per (bucket) + one for the decode chunk.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.models.llama import LlamaConfig
from dlrover_tpu.rl.generation import select_token
from dlrover_tpu.serving.model import decode_step, prefill
from dlrover_tpu.serving.params import serving_params_from_llama

# dlint DL012 contract: a lifetime allocation is owned by the admitting
# path until it is bound to a slot (whose release funnel is
# _release_slot -> free_sequence) or rolled back — an allocation that
# escapes _admit/_admit_chunked any other way strands its refcounts
_DLINT_RESOURCE_SPECS = (
    {
        "resource": "sequence lifetime allocation",
        "acquire": ("_alloc_lifetime", "alloc_sequence"),
        "release": ("free_sequence", "_bind_blocks"),
        "owners": ("allocs",),
        "why": "an admission that drops its allocation on a bail-out "
               "path pins every block in it until restart — the "
               "chunked COW rollback exists exactly for this",
    },
)


@dataclasses.dataclass
class Request:
    rid: int
    prompt: np.ndarray            # [P] int32
    max_new_tokens: int
    output: List[int] = dataclasses.field(default_factory=list)
    done: bool = False


@dataclasses.dataclass
class EngineStats:
    generated_tokens: int = 0
    decode_seconds: float = 0.0
    prefill_seconds: float = 0.0
    prefill_calls: int = 0        # dispatches; < admissions when batched
    prefill_admissions: int = 0   # requests admitted into prefill —
    #                               the batched-prefill win is
    #                               admissions/dispatches, measurable
    #                               only with BOTH counters exposed
    prefill_chunks: int = 0       # chunked-prefill dispatches
    #                               (a subset of prefill_calls)
    prefill_chunk_slots: int = 0  # slot-chunks advanced — with
    #                               same-step batching > chunks when
    #                               several long prompts prefill
    #                               together (the TTFT-deserialization
    #                               win is slots/chunks)
    prefill_chunk_seconds: float = 0.0  # wall seconds in chunk
    #                               dispatches (the stall-bound budget)
    finished_requests: int = 0
    spec_proposed: int = 0        # draft tokens sent to verification
    spec_accepted: int = 0        # draft tokens accepted
    spec_calls: int = 0           # verify dispatches (model forwards)
    decode_forwards: int = 0      # ALL decode-path model forwards

    @property
    def decode_tokens_per_sec(self) -> float:
        return self.generated_tokens / self.decode_seconds \
            if self.decode_seconds else 0.0

    @property
    def tokens_per_forward(self) -> float:
        """Committed tokens per decode-path model forward — the
        speculative-decoding win metric (1.0 = plain decode; >1 means
        drafts amortized forwards)."""
        return self.generated_tokens / self.decode_forwards \
            if self.decode_forwards else 0.0

    @property
    def spec_accept_ratio(self) -> float:
        """Accepted draft tokens over proposed — the live health signal
        of the speculation governor (``serving_spec_accept_ratio`` on
        /metrics; ``tokens_per_forward`` is the derived win)."""
        return self.spec_accepted / self.spec_proposed \
            if self.spec_proposed else 0.0


def _bucket(n: int, buckets: Tuple[int, ...]) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"prompt length {n} exceeds largest bucket "
                     f"{buckets[-1]}")


class InferenceEngine:
    """Continuous-batching generation over a Llama-family model."""

    def __init__(
        self,
        cfg: LlamaConfig,
        variables: Any,
        *,
        max_slots: int = 8,
        int8: bool = False,
        chunk: int = 8,
        temperature: float = 1.0,
        top_k: int = 0,
        top_p: float = 1.0,
        eos_token: Optional[int] = None,
        max_len: Optional[int] = None,
        prefill_buckets: Optional[Tuple[int, ...]] = None,
        speculative_k: Any = 0,
        spec_accept_floor: float = 0.15,
        paged: bool = False,
        cache_blocks: Optional[int] = None,
        block_size: int = 16,
        kv_dtype: Optional[str] = None,
        prefill_chunk: int = 0,
        attention_impl: str = "auto",
        mesh: Optional[Any] = None,
        seed: int = 0,
        prefix_sharing: bool = True,
    ):
        """``speculative_k > 1`` enables prompt-lookup speculative
        decoding: each dispatch verifies up to ``speculative_k - 1``
        draft tokens found by n-gram lookup in the slot's own context,
        committing up to ``speculative_k`` tokens for ~one decode
        step's cost.  Works with ANY sampling config: greedy verifies
        by argmax match, temperature/top-k/top-p by exact rejection
        sampling (serving/speculative.rejection_commit).

        ``speculative_k="auto"``: start in plain chunk decode, watch
        the (free) draft hit rate, and switch speculation on when
        drafts are available often enough to pay — then self-regulate:
        measured acceptance below ``spec_accept_floor`` backs off to
        chunk decode and re-probes later.

        ``prefill_chunk > 0`` enables CHUNKED prefill: a prompt whose
        bucket exceeds the chunk is admitted into a slot immediately
        but prefilled ``prefill_chunk`` tokens per engine step (a
        ``real_len`` cursor survives across dispatches), interleaved
        with the decode dispatches of the other slots — so the batch's
        worst inter-token gap is bounded by ONE chunk's cost instead
        of a whole max-length prefill (the Sarathi-style stall bound).
        Cancel/failover mid-prefill reclaims the slot and its KV
        blocks like any live slot.

        ``kv_dtype="int8"`` (requires ``paged=True``) stores the K/V
        block pools as int8 codes with per-(token, head) scales in
        block-shaped scale pools (models/quantize machinery).  An
        HBM-denominated ``cache_blocks`` budget is multiplied by
        ``kv_budget_x`` (~2x for bf16 models), which is what doubles
        the continuous batch the placement ledger can admit at fixed
        HBM.  ``kv_dtype="int4"`` packs two codes per byte (split-half
        nibbles, even head_dim required) for ``kv_budget_x`` ~3.7x —
        coarser rounding, bounded by the drift tests and the bench's
        ``kv4_ok`` greedy-agreement gate.

        ``attention_impl`` selects the paged decode attention read:
        ``"xla"`` = fused gather (materializes the dequantized dense
        view), ``"pallas"`` = the fused paged kernel (streams blocks
        in place at code width, dequant folded inside), ``"auto"``
        (default) = a one-shot measured comparison on this engine's
        real pool geometry at build, picking the faster — so auto can
        never select a slower impl.  Non-TPU backends resolve auto to
        ``"xla"`` (the interpret-mode kernel is a correctness tool);
        an explicit ``"pallas"`` is honored anywhere (interpret mode
        off-TPU).  The resolved choice is ``self.attention_impl``,
        the measurement (when taken) ``self.attention_impl_us``.

        ``prefix_sharing=False`` (paged pools only; ignored dense)
        disables copy-on-write prefix-block sharing: every admission
        gets fresh blocks and nothing is committed to the prefix
        index — the control arm of the COW golden-equivalence suite
        and the escape hatch if sharing ever misbehaves in prod."""
        self.cfg = cfg
        self.int8 = int8
        self.chunk = int(chunk)
        self.spec_auto = speculative_k == "auto"
        self.speculative_k = 8 if self.spec_auto else int(speculative_k)
        if self.speculative_k == 1 or self.speculative_k < 0:
            raise ValueError(
                f"speculative_k={self.speculative_k} is invalid: use 0 "
                "to disable, >= 2 to speculate, or 'auto'"
            )
        self.spec_accept_floor = float(spec_accept_floor)
        # speculation state machine: "on" = verify rounds; "watching" =
        # chunk decode + free draft-hit-rate probe (auto mode's start);
        # "backoff" = chunk decode for _spec_cooldown rounds after
        # measured low acceptance, then back to on/watching
        self._spec_state = "watching" if self.spec_auto else "on"
        self._spec_cooldown = 0
        self._spec_window: deque = deque(maxlen=32)
        self._draft_hits: deque = deque(maxlen=32)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.eos_token = eos_token
        self.max_len = int(max_len or cfg.max_seq_len)
        assert self.max_len <= cfg.max_seq_len
        if prefill_buckets is None:
            b, buckets = 32, []
            while b < self.max_len:
                buckets.append(b)
                b *= 2
            buckets.append(self.max_len)
            prefill_buckets = tuple(buckets)
        self.buckets = tuple(sorted(prefill_buckets))
        self.max_slots = int(max_slots)
        # ``mesh``: tensor-parallel serving — params/cache placed with
        # Megatron-style col/row shardings (params.shard_serving_state),
        # jit propagates them and GSPMD inserts the collectives.  Needs
        # the unfused projection layout (fused [q|k|v] columns would
        # shard head-incorrectly).
        self.mesh = mesh
        self.params = serving_params_from_llama(
            variables, cfg, int8=int8, fuse=mesh is None)
        # speculative slack: a verify near the end of a sequence writes
        # up to K-1 entries past its last real position; without the
        # extra rows dynamic_update_slice would CLAMP the start and
        # silently overwrite earlier (live) cache entries
        cache_len = self.max_len + max(0, self.speculative_k)
        self.prefill_chunk = int(prefill_chunk or 0)
        if self.prefill_chunk < 0:
            raise ValueError("prefill_chunk must be >= 0 (0 disables)")
        self._park_pos = 0
        if self.prefill_chunk:
            # PARK-ROW slack: while a slot prefills in chunks, the
            # decode/verify dispatches of the OTHER slots still compute
            # (and write) junk K/V for it at its frozen position.  Real
            # writes never pass cache_len-1, so parking the slot at
            # position `cache_len` and growing the cache by max(1, K)
            # rows keeps every junk write — including the dense verify
            # path's K-row block write (dynamic_update_slice clamps its
            # start to cache_len-K, which the slack makes == park) —
            # inside rows no live query's `key <= pos` mask can see.
            # Paged twin: park positions map to columns past the
            # allocation, which paged._block_offsets routes to the
            # trash sink.
            self._park_pos = cache_len
            cache_len += max(1, self.speculative_k)
        self.paged = bool(paged)
        if kv_dtype in (None, "bf16"):
            self.kv_dtype = None
        elif kv_dtype in ("int8", "int4"):
            if not self.paged:
                raise ValueError(
                    f"kv_dtype={kv_dtype!r} is a paged-pool feature "
                    "(per-block-scale quantized K/V pools); pass "
                    "paged=True")
            if kv_dtype == "int4" and cfg.head_dim_ % 2:
                raise ValueError(
                    "kv_dtype='int4' packs two codes per byte and "
                    f"needs an even head_dim (got {cfg.head_dim_})")
            self.kv_dtype = kv_dtype
        else:
            raise ValueError(
                f"kv_dtype={kv_dtype!r} not supported: use None/'bf16' "
                "(native), 'int8' or 'int4'")
        self.kv_budget_x = 1.0
        if self.paged:
            # block-pool cache (serving/paged.py): per-sequence memory
            # scales with ACTUAL lengths, concurrency is bounded by the
            # pool (HBM budget) instead of slots x max_len reservations,
            # and common prompt prefixes share blocks
            from dlrover_tpu.serving.paged import (
                BlockManager,
                kv_budget_multiplier,
            )

            self.block_size = int(block_size)
            self._max_blocks = -(-cache_len // self.block_size)
            # THE budget function — the same source the regression
            # test pins the router ledger to (serving/paged.py)
            self.kv_budget_x = kv_budget_multiplier(
                cfg.dtype, cfg.head_dim_, self.kv_dtype)
            # +1: block 0 is the trash sink (never allocated), so the
            # default must still let every slot hold a full-length
            # sequence.  An EXPLICIT cache_blocks is an HBM budget
            # denominated in native-dtype blocks — int8 pools multiply
            # it by kv_budget_x, which is the whole point of the knob
            # (same bytes, ~2x the blocks, ~2x the continuous batch).
            if cache_blocks:
                n_blocks = int(int(cache_blocks) * self.kv_budget_x)
            else:
                n_blocks = self.max_slots * self._max_blocks + 1
            self._blockmgr = BlockManager(
                n_blocks, self.block_size,
                sharing=bool(prefix_sharing))
            self._slot_blocks: List[Optional[List[int]]] = (
                [None] * self.max_slots
            )
            self._table_dirty = False
            self._table_np = np.zeros(
                (self.max_slots, self._max_blocks), np.int32
            )
            # packed int4 pools halve the code dim (two codes/byte,
            # split-half nibble layout — models/quantize.pack_int4)
            code_dim = (cfg.head_dim_ // 2 if self.kv_dtype == "int4"
                        else cfg.head_dim_)
            kvd = (n_blocks, self.block_size,
                   cfg.num_kv_heads, code_dim)
            if self.kv_dtype in ("int8", "int4"):
                from dlrover_tpu.models.quantize import KV_SCALE_DTYPE

                self._cache = {
                    "k_pool": [jnp.zeros(kvd, jnp.int8)
                               for _ in range(cfg.num_layers)],
                    "v_pool": [jnp.zeros(kvd, jnp.int8)
                               for _ in range(cfg.num_layers)],
                    "k_scale": [jnp.zeros(kvd[:3], KV_SCALE_DTYPE)
                                for _ in range(cfg.num_layers)],
                    "v_scale": [jnp.zeros(kvd[:3], KV_SCALE_DTYPE)
                                for _ in range(cfg.num_layers)],
                    "table": jnp.asarray(self._table_np),
                }
            else:
                self._cache = {
                    "k_pool": [jnp.zeros(kvd, cfg.dtype)
                               for _ in range(cfg.num_layers)],
                    "v_pool": [jnp.zeros(kvd, cfg.dtype)
                               for _ in range(cfg.num_layers)],
                    "table": jnp.asarray(self._table_np),
                }
        else:
            kvd = (self.max_slots, cache_len,
                   cfg.num_kv_heads, cfg.head_dim_)
            # per-layer buffers (a pytree of lists): donated in place by
            # the decode chunk, no stacked-cache copies
            self._cache = {
                "k": [jnp.zeros(kvd, cfg.dtype)
                      for _ in range(cfg.num_layers)],
                "v": [jnp.zeros(kvd, cfg.dtype)
                      for _ in range(cfg.num_layers)],
            }
        if mesh is not None:
            from dlrover_tpu.serving.params import shard_serving_state

            self.params, self._cache = shard_serving_state(
                self.params, self._cache, mesh, cfg)
        self._rng = jax.random.PRNGKey(seed)
        self._cache_len = cache_len
        # host-side slot state
        self._slot_req: List[Optional[Request]] = [None] * self.max_slots
        # chunked-prefill cursors: _prefilling marks slots holding a
        # request whose prompt is still being written chunk-by-chunk
        # (excluded from decode); _prefill_pos is the real_len cursor —
        # how many prompt tokens are already in the cache — surviving
        # across dispatches.  All prefilling slots advance one chunk
        # per step in ONE batched dispatch (_advance_prefill), so the
        # stall bound holds AND concurrent long prompts don't
        # serialize each other's TTFT
        self._prefilling = np.zeros(self.max_slots, bool)
        self._prefill_pos = np.zeros(self.max_slots, np.int32)
        # per-slot incrementally-filled context (prompt + committed
        # tokens) for the speculative draft lookup — rebuilding it from
        # the output list every round would be O(n^2) per request.
        # +1 column: a full-length prompt with max_new_tokens=0 still
        # receives its one prefill token at index max_len
        self._ctx_buf = np.zeros(
            (self.max_slots, self.max_len + 1), np.int32)
        self._ctx_len = np.zeros(self.max_slots, np.int32)
        self._positions = np.zeros(self.max_slots, np.int32)
        self._tokens = np.zeros(self.max_slots, np.int32)
        self._remaining = np.zeros(self.max_slots, np.int32)
        self._queue: deque[Request] = deque()
        self._finished: List[Request] = []
        self._next_rid = 0
        self.stats = EngineStats()
        # paged decode attention: gather (xla) vs fused kernel
        # (pallas), resolved ONCE at build — "auto" measures both on
        # this engine's real pool geometry and picks the faster
        # (resolve_attention_impl is the pure, tested decision)
        self.attention_impl_requested = str(attention_impl)
        self._kernel_interpret = jax.default_backend() in ("cpu", "gpu")
        self.attention_impl, self.attention_impl_us = \
            self._resolve_attention()
        self._build_programs()

    # ----------------------------------------------- attention impl
    def _resolve_attention(self):
        from dlrover_tpu.ops.pallas.paged_attention import (
            resolve_attention_impl,
        )

        req = self.attention_impl_requested
        if req not in ("auto", "xla", "pallas"):
            raise ValueError(
                f"attention_impl={req!r} not supported: use 'auto', "
                "'xla' or 'pallas'")
        if not self.paged:
            if req == "pallas":
                raise ValueError(
                    "attention_impl='pallas' reads paged block pools "
                    "in place; pass paged=True")
            return "xla", None
        if req in ("xla", "pallas"):
            return req, None
        if self._kernel_interpret:
            # no TPU: the interpret-mode kernel is a parity harness,
            # not a perf candidate — auto must not "measure" it
            return "xla", None
        timings = self._measure_attention()
        # stored in MICROseconds to match the attribute name (the
        # measurement itself is perf_counter seconds)
        return resolve_attention_impl("auto", timings), {
            k: v * 1e6 for k, v in timings.items()}

    def _measure_attention(self):
        """One-shot timing of both paged attention impls on THIS
        engine's pools at worst-case context (every table column
        live): the evidence behind the auto-pick, kept on the engine
        (``attention_impl_us``) so the bench can print it."""
        from dlrover_tpu.ops.pallas.paged_attention import (
            measure_paged_attention,
        )

        cfg = self.cfg
        key = jax.random.PRNGKey(0)
        q = jax.random.normal(
            key, (self.max_slots, cfg.num_heads, cfg.head_dim_),
            jnp.float32).astype(cfg.dtype)
        nb = self._blockmgr.num_blocks
        mb = self._max_blocks
        table = jnp.asarray(
            (np.arange(self.max_slots * mb) % max(1, nb - 1) + 1)
            .reshape(self.max_slots, mb).astype(np.int32))
        lengths = jnp.full(
            (self.max_slots,), min(self._cache_len, mb * self.block_size),
            jnp.int32)
        kw = {}
        if self.kv_dtype in ("int8", "int4"):
            kw = dict(k_scale=self._cache["k_scale"][0],
                      v_scale=self._cache["v_scale"][0])
        return measure_paged_attention(
            q, self._cache["k_pool"][0], self._cache["v_pool"][0],
            table, lengths, interpret=self._kernel_interpret, **kw)

    # ------------------------------------------------------------ jit
    def _build_programs(self) -> None:
        cfg = self.cfg
        temperature, top_k, top_p = self.temperature, self.top_k, self.top_p
        n_steps = self.chunk

        impl = self.attention_impl
        kernel_interpret = self._kernel_interpret

        @functools.partial(jax.jit, donate_argnums=(1,))
        def chunk_fn(params, cache, tokens, positions, active, rng):
            def step(carry, _):
                toks, pos, cache, key = carry
                logits, cache = decode_step(
                    params, cfg, cache, toks, pos,
                    attention_impl=impl,
                    kernel_interpret=kernel_interpret)
                key, sub = jax.random.split(key)
                nxt = select_token(logits, sub, temperature, top_k, top_p)
                toks = jnp.where(active, nxt.astype(toks.dtype), toks)
                pos = jnp.where(active, pos + 1, pos)
                return (toks, pos, cache, key), nxt

            (tokens, positions, cache, rng), out = jax.lax.scan(
                step, (tokens, positions, cache, rng), None,
                length=n_steps,
            )
            return out.T, tokens, positions, cache, rng

        paged = self.paged
        kv_quant = self.kv_dtype in ("int8", "int4")
        kv_packed4 = self.kv_dtype == "int4"

        @functools.partial(jax.jit, donate_argnums=(1,))
        def insert_fn(params, cache, tokens, real_len, slots, skip, rng):
            """Prefill a GROUP of same-bucket prompts ([G, Lp]) and
            scatter their K/V into cache slots ``slots`` [G] in one
            dispatch (jit caches one program per (G, bucket) pair).
            ``skip`` [G] is the per-row shared-prefix length: those
            leading positions live in SHARED (read-only) prefix blocks
            already holding the first writer's K/V, so their writes
            route to the trash sink (paged COW contract) — the prefill
            COMPUTE still covers them (logits need the full prompt),
            only the cache write is masked.  Traced, so one program
            serves every skip value; the dense layout has no sharing
            and ignores it."""
            lp = tokens.shape[1]
            logits, ks, vs = prefill(params, cfg, tokens, real_len)
            if paged and kv_quant:
                from dlrover_tpu.serving.paged import (
                    scatter_tokens_q,
                    scatter_tokens_q4,
                )

                scatter_q = (scatter_tokens_q4 if kv_packed4
                             else scatter_tokens_q)
                rows = jnp.take(cache["table"], slots, axis=0)  # [G, MB]
                zero = jnp.zeros(slots.shape, jnp.int32)
                kp, ksc, vp, vsc = [], [], [], []
                for p, sp, k in zip(cache["k_pool"], cache["k_scale"],
                                    ks):
                    np_, ns_ = scatter_q(p, sp, rows, k, zero, skip)
                    kp.append(np_)
                    ksc.append(ns_)
                for p, sp, v in zip(cache["v_pool"], cache["v_scale"],
                                    vs):
                    np_, ns_ = scatter_q(p, sp, rows, v, zero, skip)
                    vp.append(np_)
                    vsc.append(ns_)
                new_cache = dict(cache, k_pool=kp, k_scale=ksc,
                                 v_pool=vp, v_scale=vsc)
            elif paged:
                from dlrover_tpu.serving.paged import scatter_tokens

                rows = jnp.take(cache["table"], slots, axis=0)  # [G, MB]
                zero = jnp.zeros(slots.shape, jnp.int32)
                new_cache = dict(
                    cache,
                    k_pool=[
                        scatter_tokens(p, rows, k.astype(p.dtype),
                                       zero, skip)
                        for p, k in zip(cache["k_pool"], ks)
                    ],
                    v_pool=[
                        scatter_tokens(p, rows, v.astype(p.dtype),
                                       zero, skip)
                        for p, v in zip(cache["v_pool"], vs)
                    ],
                )
            else:
                new_cache = {
                    "k": [
                        ck.at[slots, :lp].set(k.astype(ck.dtype))
                        for ck, k in zip(cache["k"], ks)
                    ],
                    "v": [
                        cv.at[slots, :lp].set(v.astype(cv.dtype))
                        for cv, v in zip(cache["v"], vs)
                    ],
                }
            rng, sub = jax.random.split(rng)
            first = select_token(logits, sub, temperature, top_k, top_p)
            return new_cache, first, rng

        self._chunk_fn = chunk_fn
        self._insert_fn = insert_fn

        self._prefill_chunk_fn = None
        if self.prefill_chunk:
            from dlrover_tpu.serving.model import verify_step

            @functools.partial(jax.jit, donate_argnums=(1,))
            def prefill_chunk_fn(params, cache, tokens, start, slots,
                                 last_idx, rng):
                """ONE bounded prompt chunk for slot subset ``slots``:
                a draft-free verify run attending to what previous
                chunks cached (one compile — the chunk shape is fixed
                at [G, prefill_chunk]).  ``last_idx`` picks the single
                position whose logits feed sampling; the host uses the
                sampled token only for the FINAL chunk."""
                logits, cache = verify_step(
                    params, cfg, cache, tokens, start,
                    slots=slots, logits_index=last_idx)
                rng, sub = jax.random.split(rng)
                first = select_token(
                    logits[:, 0, :], sub, temperature, top_k, top_p)
                return cache, first, rng

            self._prefill_chunk_fn = prefill_chunk_fn

        self._spec_fn = None
        if self.speculative_k > 1:
            from dlrover_tpu.serving.model import verify_step
            from dlrover_tpu.serving.speculative import rejection_commit

            @functools.partial(jax.jit, donate_argnums=(1,))
            def spec_fn(params, cache, tokens, positions, draft_len,
                        rng):
                logits, cache = verify_step(
                    params, cfg, cache, tokens, positions)
                rng, sub = jax.random.split(rng)
                out, n_commit = rejection_commit(
                    logits, tokens[:, 1:], draft_len, sub,
                    temperature=temperature, top_k=top_k, top_p=top_p,
                )
                return out, n_commit, cache, rng

            self._spec_fn = spec_fn

    # ------------------------------------------------------- requests
    def add_request(self, prompt_ids, max_new_tokens: int) -> int:
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        assert prompt.size >= 1
        total = prompt.size + int(max_new_tokens)
        if total > self.max_len:
            raise ValueError(
                f"prompt {prompt.size} + max_new {max_new_tokens} "
                f"exceeds engine max_len {self.max_len}")
        if self.paged:
            # fail fast on a request the pool can NEVER hold (waiting
            # in the queue would spin run() forever)
            worst = max(
                total + max(0, self.speculative_k),
                _bucket(prompt.size, self.buckets),
            )
            need = -(-worst // self.block_size)
            if need > self._blockmgr.num_blocks - 1:
                raise ValueError(
                    f"request needs {need} cache blocks but the pool "
                    f"holds {self._blockmgr.num_blocks - 1} usable "
                    "(cache_blocks too small for this request)")
        rid = self._next_rid
        self._next_rid += 1
        self._queue.append(Request(rid, prompt, int(max_new_tokens)))
        return rid

    def _admit(self) -> None:
        """Admit waiting requests into free slots.  Consecutive queue
        entries whose prompts land in the SAME length bucket prefill as
        one batched dispatch — at G admissions per dispatch this cuts
        the prefill launch count up to G-fold (the vLLM-style batched
        prefill; on this rig dispatch latency dominates prefill, so the
        cut is a direct wall-clock win)."""
        while self._queue:
            free = [
                s for s in range(self.max_slots)
                if self._slot_req[s] is None
            ]
            if not free:
                return
            bucket = _bucket(self._queue[0].prompt.size, self.buckets)
            if self._prefill_chunk_fn is not None \
                    and bucket > self.prefill_chunk:
                if not self._admit_chunked(free[0]):
                    return  # pool exhausted: keep queued, keep order
                continue
            group: List[Request] = []
            allocs: List[Any] = []
            while (
                self._queue
                and len(group) < len(free)
                and _bucket(self._queue[0].prompt.size, self.buckets)
                == bucket
            ):
                if self.paged:
                    # a committed-prefix hit whose CHUNKED writer is
                    # still mid-prefill must wait — the content is not
                    # written yet.  (This group's own registrations are
                    # never pending: the insert dispatch below writes
                    # them before anything reads.)
                    if not self._blockmgr.shared_prefix_ready(
                            self._queue[0].prompt):
                        break
                    # capacity gate: blocks for the whole lifetime
                    # (bucket-padded prefill writes + gen + spec slack);
                    # pool exhaustion keeps the request QUEUED — that is
                    # the HBM-budget-bound admission paging exists for
                    alloc = self._alloc_lifetime(self._queue[0], bucket)
                    if alloc is None:
                        break
                    allocs.append(alloc)
                group.append(self._queue.popleft())
            if not group:
                return
            slots = free[: len(group)]
            if self.paged:
                for g, s in enumerate(slots):
                    self._bind_blocks(s, allocs[g][0])
                self._push_table()
            padded = np.zeros((len(group), bucket), np.int32)
            lens = np.empty(len(group), np.int32)
            for g, req in enumerate(group):
                padded[g, : req.prompt.size] = req.prompt
                lens[g] = req.prompt.size
            # per-row shared-prefix length: positions below it are
            # mapped shared blocks whose K/V the first writer already
            # holds — insert_fn masks their cache writes
            skips = (np.asarray([a[1] for a in allocs], np.int32)
                     if self.paged
                     else np.zeros(len(group), np.int32))
            t0 = time.perf_counter()
            self._cache, firsts, self._rng = self._insert_fn(
                self.params, self._cache, jnp.asarray(padded),
                jnp.asarray(lens), jnp.asarray(slots, jnp.int32),
                jnp.asarray(skips), self._rng,
            )
            firsts = np.asarray(firsts)
            self.stats.prefill_seconds += time.perf_counter() - t0
            self.stats.prefill_calls += 1
            self.stats.prefill_admissions += len(group)
            for g, (s, req) in enumerate(zip(slots, group)):
                first = int(firsts[g])
                self._slot_req[s] = req
                req.output.append(first)
                p = req.prompt.size
                self._ctx_buf[s, :p] = req.prompt
                self._ctx_buf[s, p] = first
                self._ctx_len[s] = p + 1
                self._tokens[s] = first
                self._positions[s] = p
                self._remaining[s] = req.max_new_tokens - 1
                self._finish_if_done(s, first)

    def _alloc_lifetime(self, req: Request, bucket: int):
        """ONE capacity formula for every admission path (batched AND
        chunked) — and it must stay in lockstep with the router's
        ``blocks_needed``: blocks for the request's whole lifetime,
        i.e. max(bucket-padded prefill writes, prompt + generation +
        speculative slack).  None = pool exhausted (caller keeps the
        request queued)."""
        total = max(
            req.prompt.size + req.max_new_tokens
            + max(0, self.speculative_k),
            bucket,
        )
        return self._blockmgr.alloc_sequence(req.prompt, total)

    def _bind_blocks(self, s: int, blocks: List[int]) -> None:
        """Point slot ``s``'s table row at its allocated blocks
        (zero-filled tail = the trash sink); the caller owns the
        host->device table push."""
        self._slot_blocks[s] = blocks
        self._table_np[s, : len(blocks)] = blocks
        self._table_np[s, len(blocks):] = 0

    def _admit_chunked(self, s: int) -> bool:
        """Admit the queue head into slot ``s`` for CHUNKED prefill:
        blocks for the whole lifetime are allocated now (same capacity
        formula as the router's ``blocks_needed``), but the prompt is
        written ``prefill_chunk`` tokens per step by
        :meth:`_advance_prefill`.  The slot is parked out of decode
        (``_prefilling``; position = the never-read park row) until
        the cursor reaches the prompt end.  False = pool exhausted,
        request stays queued."""
        req = self._queue[0]
        start = 0
        if self.paged:
            # never map (and warm-start past) committed blocks whose
            # writer has not finished writing them: wait in the queue
            # until the prefix is FILLED, then admit with a real hit
            if not self._blockmgr.shared_prefix_ready(req.prompt):
                return False
            alloc = self._alloc_lifetime(
                req, _bucket(req.prompt.size, self.buckets))
            if alloc is None:
                return False
            blocks, shared = alloc
            # blocks past the shared region were REGISTERED at alloc
            # but their content arrives one chunk per step: hold other
            # admissions off them until the cursor publishes each
            # (mark_filled in _advance_prefill)
            self._blockmgr.mark_pending(
                blocks[shared // self.block_size:
                       req.prompt.size // self.block_size])
            if shared:
                c = self.prefill_chunk
                # warm start: shared blocks already hold the prefix's
                # K/V, so the cursor begins at the last chunk boundary
                # inside the shared region instead of 0 — the TTFT win.
                # The clamp keeps the FINAL chunk live even when the
                # whole prompt is shared: sampling the first token
                # needs one real dispatch.
                start = min((shared // c) * c,
                            ((req.prompt.size - 1) // c) * c)
                # the chunk program WRITES positions [start, ...), so
                # every shared block it overlaps must diverge first
                # (COW) — unlike batched prefill there is no write
                # mask here (verify_step's scatter covers the whole
                # chunk), so the contract is enforced by ownership
                src: List[int] = []
                dst: List[int] = []
                for j in range(start // self.block_size,
                               shared // self.block_size):
                    r = self._blockmgr.cow_block(blocks[j])
                    if r is None:
                        # pool exhausted mid-divergence: roll the whole
                        # admission back (cow_block already moved our
                        # reference into blocks[j] for completed
                        # copies, so one free_sequence balances it)
                        self._blockmgr.free_sequence(blocks)
                        return False
                    new_bid, copied = r
                    if copied:
                        src.append(blocks[j])
                        dst.append(new_bid)
                        blocks[j] = new_bid
                if src:
                    self._copy_blocks(src, dst)
            self._bind_blocks(s, blocks)
            self._table_dirty = True
        self._queue.popleft()
        self._slot_req[s] = req
        self._prefilling[s] = True
        self._prefill_pos[s] = start
        self._tokens[s] = 0
        self._positions[s] = self._park_pos
        self._remaining[s] = req.max_new_tokens
        self.stats.prefill_admissions += 1
        return True

    def _copy_blocks(self, src: List[int], dst: List[int]) -> None:
        """COW divergence copies: pool rows ``src[i] -> dst[i]`` across
        every layer's K/V pools (and scale pools when quantized), so
        the diverging sequence starts from the shared content it is
        about to overwrite the tail of."""
        si = jnp.asarray(src, jnp.int32)
        di = jnp.asarray(dst, jnp.int32)
        cache = dict(self._cache)
        for key in ("k_pool", "v_pool", "k_scale", "v_scale"):
            pools = cache.get(key)
            if pools is not None:
                cache[key] = [p.at[di].set(p[si]) for p in pools]
        self._cache = cache

    def _advance_prefill(self) -> None:
        """One bounded prompt chunk for EVERY prefilling slot, batched
        into a single ``verify_step`` dispatch (the ``slots=`` subset
        machinery): rows are independent, so N concurrent long prompts
        advance together instead of round-robining one per step —
        which serialized their TTFTs N-fold while still paying one
        dispatch of latency each step.  The per-step budget that
        bounds every decoding slot's inter-token gap stays ONE chunk
        dispatch (jit caches one program per live group size, bounded
        by max_slots).  When a cursor reaches its prompt end, sample
        that row's first token and hand the slot to decode."""
        slots = [s for s in range(self.max_slots) if self._prefilling[s]]
        if not slots:
            return
        c = self.prefill_chunk
        g = len(slots)
        chunk = np.zeros((g, c), np.int32)
        starts = np.zeros(g, np.int32)
        last_idx = np.zeros(g, np.int32)
        ends = np.zeros(g, np.int32)
        for i, s in enumerate(slots):
            req = self._slot_req[s]
            assert req is not None
            start = int(self._prefill_pos[s])
            end = min(start + c, req.prompt.size)
            chunk[i, : end - start] = req.prompt[start:end]
            starts[i] = start
            ends[i] = end
            # index (within the chunk) of the prompt's final token:
            # only meaningful on a row's final chunk; clamped junk
            # otherwise (that row's sampled token is discarded)
            last_idx[i] = max(0, min(end, req.prompt.size) - 1 - start)
        if self.paged and self._table_dirty:
            self._push_table()
        t0 = time.perf_counter()
        self._cache, firsts, self._rng = self._prefill_chunk_fn(
            self.params, self._cache, jnp.asarray(chunk),
            jnp.asarray(starts),
            jnp.asarray(slots, jnp.int32),
            jnp.asarray(last_idx),
            self._rng,
        )
        dt = time.perf_counter() - t0
        self.stats.prefill_seconds += dt
        self.stats.prefill_chunk_seconds += dt
        self.stats.prefill_calls += 1
        self.stats.prefill_chunks += 1
        self.stats.prefill_chunk_slots += g
        firsts = np.asarray(firsts)
        for i, s in enumerate(slots):
            req = self._slot_req[s]
            end = int(ends[i])
            self._prefill_pos[s] = end
            if self.paged:
                # the chunk just written completes every prompt block
                # it crosses the end of — publish them so waiting
                # admissions (shared_prefix_ready) can warm-start
                bs = self.block_size
                blocks = self._slot_blocks[s]
                for j in range(int(starts[i]) // bs,
                               min(end // bs, req.prompt.size // bs)):
                    self._blockmgr.mark_filled(blocks[j])
            if end < req.prompt.size:
                continue
            first = int(firsts[i])
            self._prefilling[s] = False
            req.output.append(first)
            p = req.prompt.size
            self._ctx_buf[s, :p] = req.prompt
            self._ctx_buf[s, p] = first
            self._ctx_len[s] = p + 1
            self._tokens[s] = first
            self._positions[s] = p
            self._remaining[s] = req.max_new_tokens - 1
            self._finish_if_done(s, first)

    def _finish_if_done(self, s: int, last_token: int) -> bool:
        req = self._slot_req[s]
        assert req is not None
        if (self.eos_token is not None and last_token == self.eos_token) \
                or self._remaining[s] <= 0:
            req.done = True
            self._finished.append(req)
            self.stats.finished_requests += 1
            self._release_slot(s)
            return True
        return False

    def _release_slot(self, s: int) -> None:
        """Return slot ``s`` to the free set — completion AND
        cancellation both land here, so a half-prefilled slot reclaims
        exactly like a decoding one."""
        self._slot_req[s] = None
        self._prefilling[s] = False
        self._prefill_pos[s] = 0
        if self.paged and self._slot_blocks[s] is not None:
            # blocks return to the pool (shared prefix blocks just
            # decref; fully-released ones linger in the prefix LRU).
            # The table row must reset to the trash block NOW: the
            # dead slot keeps writing junk KV every step, and its
            # freed blocks may be reallocated to a live sequence.
            self._blockmgr.free_sequence(self._slot_blocks[s])
            self._slot_blocks[s] = None
            self._table_np[s, :] = 0
            # batched: several slots often finish in one round, and
            # a table transfer per finish would pay the host->device
            # hop each time — dispatch sites push once when dirty
            self._table_dirty = True

    def cancel(self, rid: int) -> bool:
        """Withdraw a request wherever it lives — the engine queue, a
        live decode slot, or a slot still MID-CHUNKED-PREFILL (the
        cursor state is discarded and the lifetime block allocation
        freed) — reclaiming slot + paged KV immediately.  Always True:
        local delivery cannot fail, and an already-finished rid is a
        successfully-delivered no-op (the router-side cancel
        contract)."""
        for i, req in enumerate(self._queue):
            if req.rid == rid:
                del self._queue[i]
                return True
        for s, req in enumerate(self._slot_req):
            if req is not None and req.rid == rid:
                self._release_slot(s)
                return True
        return True

    def _push_table(self) -> None:
        table = jnp.asarray(self._table_np)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            table = jax.device_put(
                table, NamedSharding(self.mesh, PartitionSpec()))
        self._cache = dict(self._cache, table=table)
        self._table_dirty = False

    @property
    def kv_quant_blocks(self) -> int:
        """Blocks in a quantized (int8 OR int4) KV pool (0 when the
        pool is native-dtype) — the ``serving_kv_quant_blocks``
        gauge."""
        if self.paged and self.kv_dtype in ("int8", "int4"):
            return self._blockmgr.num_blocks
        return 0

    @property
    def kv4_blocks(self) -> int:
        """Blocks in a packed-int4 KV pool specifically — the
        ``serving_kv_int4_blocks`` gauge (int4's ~3.7x budget is a
        different capacity planning regime than int8's ~2x, so the
        dashboard needs them apart)."""
        if self.paged and self.kv_dtype == "int4":
            return self._blockmgr.num_blocks
        return 0

    def prefix_stats(self) -> Dict[str, float]:
        """The ``serving_prefix_*`` ledger of the paged prefix cache
        (hits, misses, evictions, COW copies, shared blocks/tokens) —
        {} for dense layouts, which have no sharing to account."""
        if not self.paged:
            return {}
        return self._blockmgr.prefix_stats()

    def prefix_heads(self, n: int = 8) -> List[str]:
        """This replica's hottest committed prefix-head digests (hex)
        — what the worker advertises over STATS so the router's
        prefix-routing table can steer warm traffic here."""
        if not self.paged:
            return []
        return self._blockmgr.hot_heads(n)

    # ----------------------------------------------------------- step
    @property
    def has_work(self) -> bool:
        return bool(self._queue) or any(
            r is not None for r in self._slot_req)

    def step(self) -> List[Request]:
        """Admit waiting requests, advance at most ONE bounded prefill
        chunk, run one decode chunk (or speculative verify), return
        requests finished during this step.  The ordering IS the stall
        bound: a max-length prompt costs every other slot one chunk
        dispatch per decode round, never a whole prefill."""
        before = len(self._finished)
        self._admit()
        if self.prefill_chunk:
            self._advance_prefill()
        active = np.array([
            r is not None and not self._prefilling[s]
            for s, r in enumerate(self._slot_req)
        ])
        if active.any() and self._spec_fn is not None \
                and self._spec_state == "on":
            self._spec_step()
            return self._finished[before:]
        if active.any():
            if self.paged and self._table_dirty:
                self._push_table()
            t0 = time.perf_counter()
            out, tokens, positions, self._cache, self._rng = \
                self._chunk_fn(
                    self.params, self._cache,
                    jnp.asarray(self._tokens), jnp.asarray(self._positions),
                    jnp.asarray(active), self._rng,
                )
            out = np.asarray(out)                       # [B, chunk]
            # copies: jax->numpy views are read-only, but _admit mutates
            self._tokens = np.array(tokens)
            self._positions = np.array(positions)
            self.stats.decode_seconds += time.perf_counter() - t0
            self.stats.decode_forwards += self.chunk
            for s in range(self.max_slots):
                req = self._slot_req[s]
                if req is None or self._prefilling[s]:
                    continue
                take = min(self.chunk, int(self._remaining[s]))
                toks = out[s, :take].tolist()
                if self.eos_token is not None and self.eos_token in toks:
                    toks = toks[: toks.index(self.eos_token) + 1]
                req.output.extend(toks)
                if self._spec_fn is not None and toks:
                    # keep the draft-lookup context fresh so a later
                    # switch back to speculation sees these tokens
                    n = int(self._ctx_len[s])
                    end = min(n + len(toks), self._ctx_buf.shape[1])
                    self._ctx_buf[s, n:end] = toks[: end - n]
                    self._ctx_len[s] = end
                self._remaining[s] -= len(toks)
                self.stats.generated_tokens += len(toks)
                self._finish_if_done(s, toks[-1] if toks else -1)
            if self._spec_fn is not None:
                self._after_chunk_round()
        return self._finished[before:]

    def _after_chunk_round(self) -> None:
        """Speculation governor, chunk-decode side: count down a
        backoff, or (auto mode) probe the FREE draft hit rate and
        switch speculation on when drafts are available often enough."""
        from dlrover_tpu.serving.speculative import find_draft

        if self._spec_state == "backoff":
            self._spec_cooldown -= 1
            if self._spec_cooldown <= 0:
                self._spec_state = "watching" if self.spec_auto else "on"
                self._spec_window.clear()
            return
        if self._spec_state != "watching":
            return
        for s, req in enumerate(self._slot_req):
            if req is None or self._prefilling[s]:
                continue
            n = int(self._ctx_len[s])
            context = self._ctx_buf[s, max(0, n - 2048):n]
            self._draft_hits.append(
                find_draft(context, self.speculative_k - 1) is not None
            )
        if len(self._draft_hits) >= 8 and (
            sum(self._draft_hits) / len(self._draft_hits) >= 0.4
        ):
            self._spec_state = "on"
            self._draft_hits.clear()

    def _spec_step(self) -> None:
        """One speculative round: draft K-1 tokens per slot by prompt
        lookup, verify all slots in ONE dispatch, commit the exact-
        distribution sample (greedy prefix match, or rejection sampling
        under temperature/top-k/top-p — speculative.rejection_commit)."""
        from dlrover_tpu.serving.speculative import find_draft

        k = self.speculative_k
        window = 2048  # bounded lookup tail: keeps the n-gram scan O(1)
        tokens = np.zeros((self.max_slots, k), np.int32)
        tokens[:, 0] = self._tokens
        draft_lens = np.zeros(self.max_slots, np.int32)
        for s, req in enumerate(self._slot_req):
            if req is None or self._prefilling[s]:
                continue
            n = int(self._ctx_len[s])
            context = self._ctx_buf[s, max(0, n - window):n]
            draft = find_draft(context, k - 1)
            if draft is not None:
                tokens[s, 1:1 + draft.size] = draft
                draft_lens[s] = draft.size
        if self.paged and self._table_dirty:
            self._push_table()
        t0 = time.perf_counter()
        out, n_commit, self._cache, self._rng = self._spec_fn(
            self.params, self._cache, jnp.asarray(tokens),
            jnp.asarray(self._positions), jnp.asarray(draft_lens),
            self._rng,
        )
        out = np.asarray(out)
        n_commit = np.asarray(n_commit)
        self.stats.decode_seconds += time.perf_counter() - t0
        self.stats.spec_calls += 1
        self.stats.decode_forwards += 1
        round_proposed = 0
        round_accepted = 0
        for s in range(self.max_slots):
            req = self._slot_req[s]
            if req is None or self._prefilling[s]:
                continue
            accepted = int(n_commit[s]) - 1
            round_proposed += int(draft_lens[s])
            round_accepted += accepted
            self.stats.spec_proposed += int(draft_lens[s])
            self.stats.spec_accepted += accepted
            toks = out[s, : accepted + 1].tolist()
            take = min(len(toks), int(self._remaining[s]))
            toks = toks[:take]
            if self.eos_token is not None and self.eos_token in toks:
                toks = toks[: toks.index(self.eos_token) + 1]
            if not toks:
                continue
            req.output.extend(toks)
            n = int(self._ctx_len[s])
            self._ctx_buf[s, n:n + len(toks)] = toks
            self._ctx_len[s] = n + len(toks)
            self._remaining[s] -= len(toks)
            self.stats.generated_tokens += len(toks)
            self._tokens[s] = toks[-1]
            self._positions[s] += len(toks)
            self._finish_if_done(s, toks[-1])
        # governor: measured low acceptance -> back off to chunk decode
        # (a missing draft costs one wasted verify's worth of drafts
        # every round; backing off makes the miss genuinely free)
        self._spec_window.append((round_proposed, round_accepted))
        proposed = sum(p for p, _ in self._spec_window)
        if proposed >= 64:
            rate = sum(a for _, a in self._spec_window) / proposed
            if rate < self.spec_accept_floor:
                self._spec_state = "backoff"
                self._spec_cooldown = 8
                self._spec_window.clear()

    def run(self) -> Dict[int, np.ndarray]:
        """Drain the queue; returns {request_id: generated tokens}."""
        while self.has_work:
            if self.eos_token is None and self._spec_fn is None \
                    and not self.prefill_chunk:
                # fixed-budget drain needs a KNOWN number of dispatches;
                # speculative acceptance makes progress data-dependent
                # (and chunked prefill interleaves chunk dispatches),
                # so both modes always go through step()
                self._drain_fixed()
            else:
                self.step()
        return {r.rid: np.asarray(r.output, np.int32)
                for r in self._finished}

    def _drain_fixed(self) -> None:
        """No-EOS fast path: until the EARLIEST slot completion the
        number of decode chunks is known, so dispatch them all
        back-to-back and sync the host ONCE — per-chunk host round
        trips would otherwise dominate decode latency (multi-step
        scheduling taken to its fixed-budget limit)."""
        self._admit()
        active = np.array([r is not None for r in self._slot_req])
        if not active.any():
            return
        min_remaining = min(
            int(self._remaining[s]) for s in range(self.max_slots)
            if self._slot_req[s] is not None)
        n_chunks = max(1, -(-min_remaining // self.chunk))
        t0 = time.perf_counter()
        outs = []
        tokens = jnp.asarray(self._tokens)
        positions = jnp.asarray(self._positions)
        active_j = jnp.asarray(active)
        for _ in range(n_chunks):
            out, tokens, positions, self._cache, self._rng = \
                self._chunk_fn(
                    self.params, self._cache, tokens, positions,
                    active_j, self._rng,
                )
            outs.append(out)
        out = np.concatenate([np.asarray(o) for o in outs], axis=1)
        self._tokens = np.array(tokens)
        self._positions = np.array(positions)
        self.stats.decode_seconds += time.perf_counter() - t0
        for s in range(self.max_slots):
            req = self._slot_req[s]
            if req is None:
                continue
            take = min(out.shape[1], int(self._remaining[s]))
            toks = out[s, :take].tolist()
            req.output.extend(toks)
            self._remaining[s] -= len(toks)
            self.stats.generated_tokens += len(toks)
            self._finish_if_done(s, toks[-1] if toks else -1)

    # ----------------------------------------- batch-generate (RL API)
    def generate(
        self,
        prompt_ids,
        max_new_tokens: int,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """``sample_sequences``-compatible batch API for RL rollouts:
        returns (tokens [B, P+new], response_mask [B, P+new]).  A
        sequence that stopped early at EOS pads the remainder with the
        EOS token but the mask covers ONLY the actually-sampled tokens
        (through the EOS) — training signals must not weight filler the
        policy never produced."""
        prompts = np.asarray(prompt_ids, np.int32)
        batch, p_len = prompts.shape
        rids = [self.add_request(prompts[i], max_new_tokens)
                for i in range(batch)]
        outputs = self.run()
        total = p_len + max_new_tokens
        tokens = np.zeros((batch, total), np.int32)
        mask = np.zeros((batch, total), np.int32)
        for i, rid in enumerate(rids):
            out = outputs[rid]
            n_real = min(out.size, max_new_tokens)
            fill = np.full(
                max_new_tokens,
                out[-1] if out.size else 0, np.int32)
            fill[:n_real] = out[:max_new_tokens]
            tokens[i, :p_len] = prompts[i]
            tokens[i, p_len:] = fill
            mask[i, p_len:p_len + n_real] = 1
        # engine state stays warm for the next batch
        self._finished.clear()
        return tokens, mask
