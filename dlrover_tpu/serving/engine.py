"""Continuous-batching inference engine (prefill/decode split).

The TPU counterpart of the reference's vLLM inference backend for RL
rollouts (atorch/atorch/rl/inference_backend/vllm_backend.py:11-24) and
its generation config (rl/model_utils/vllm_utils.py): a slotted decode
batch that sequences enter and leave independently —

- ``max_slots`` concurrent sequences decode as ONE jitted step batch;
- a finished slot (EOS / budget) is refilled from the request queue by
  a bucketed prefill WITHOUT stopping the other slots (continuous
  batching, the Orca/vLLM scheduling model);
- decode runs in chunks of ``chunk`` tokens per host sync (multi-step
  scheduling) — sampling stays on-device inside a ``lax.scan``;
- ``int8=True`` serves pre-quantized int8 weights through the Pallas
  MXU kernel (weights stream from HBM at half the bf16 bytes — decode
  is bandwidth-bound, so this is the serving speedup, fixing the
  0.6x end-to-end w8a8 result of the dynamic-quantization path).

Static shapes everywhere: prompts right-pad to power-of-two buckets,
the decode batch is fixed at ``max_slots``, EOS only masks. One compile
per (bucket) + one for the decode chunk.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.models.llama import LlamaConfig
from dlrover_tpu.rl.generation import select_token
from dlrover_tpu.serving.model import decode_step, prefill
from dlrover_tpu.serving.params import serving_params_from_llama


@dataclasses.dataclass
class Request:
    rid: int
    prompt: np.ndarray            # [P] int32
    max_new_tokens: int
    output: List[int] = dataclasses.field(default_factory=list)
    done: bool = False


@dataclasses.dataclass
class EngineStats:
    generated_tokens: int = 0
    decode_seconds: float = 0.0
    prefill_seconds: float = 0.0
    prefill_calls: int = 0        # dispatches; < admissions when batched
    finished_requests: int = 0
    spec_proposed: int = 0        # draft tokens sent to verification
    spec_accepted: int = 0        # draft tokens accepted (greedy match)
    spec_calls: int = 0           # verify dispatches (model forwards)

    @property
    def decode_tokens_per_sec(self) -> float:
        return self.generated_tokens / self.decode_seconds \
            if self.decode_seconds else 0.0


def _bucket(n: int, buckets: Tuple[int, ...]) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"prompt length {n} exceeds largest bucket "
                     f"{buckets[-1]}")


class InferenceEngine:
    """Continuous-batching generation over a Llama-family model."""

    def __init__(
        self,
        cfg: LlamaConfig,
        variables: Any,
        *,
        max_slots: int = 8,
        int8: bool = False,
        chunk: int = 8,
        temperature: float = 1.0,
        top_k: int = 0,
        top_p: float = 1.0,
        eos_token: Optional[int] = None,
        max_len: Optional[int] = None,
        prefill_buckets: Optional[Tuple[int, ...]] = None,
        speculative_k: int = 0,
        seed: int = 0,
    ):
        """``speculative_k > 1`` enables prompt-lookup speculative
        decoding (greedy only): each dispatch verifies up to
        ``speculative_k - 1`` draft tokens found by n-gram lookup in the
        slot's own context, committing up to ``speculative_k`` tokens
        for ~one decode step's cost (serving/speculative.py)."""
        self.cfg = cfg
        self.int8 = int8
        self.chunk = int(chunk)
        self.speculative_k = int(speculative_k)
        if self.speculative_k == 1 or self.speculative_k < 0:
            raise ValueError(
                f"speculative_k={self.speculative_k} is invalid: use 0 "
                "to disable or >= 2 to speculate (1 would be a no-op)"
            )
        if self.speculative_k > 1 and temperature != 0.0:
            raise ValueError(
                "speculative decoding requires greedy sampling "
                "(temperature=0): greedy verification is what keeps the "
                "output distribution exact"
            )
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.eos_token = eos_token
        self.max_len = int(max_len or cfg.max_seq_len)
        assert self.max_len <= cfg.max_seq_len
        if prefill_buckets is None:
            b, buckets = 32, []
            while b < self.max_len:
                buckets.append(b)
                b *= 2
            buckets.append(self.max_len)
            prefill_buckets = tuple(buckets)
        self.buckets = tuple(sorted(prefill_buckets))
        self.max_slots = int(max_slots)
        self.params = serving_params_from_llama(variables, cfg, int8=int8)
        # speculative slack: a verify near the end of a sequence writes
        # up to K-1 entries past its last real position; without the
        # extra rows dynamic_update_slice would CLAMP the start and
        # silently overwrite earlier (live) cache entries
        cache_len = self.max_len + max(0, self.speculative_k)
        kvd = (self.max_slots, cache_len,
               cfg.num_kv_heads, cfg.head_dim_)
        # per-layer buffers (a pytree of lists): donated in place by the
        # decode chunk, no stacked-cache copies
        self._cache = {
            "k": [jnp.zeros(kvd, cfg.dtype)
                  for _ in range(cfg.num_layers)],
            "v": [jnp.zeros(kvd, cfg.dtype)
                  for _ in range(cfg.num_layers)],
        }
        self._rng = jax.random.PRNGKey(seed)
        # host-side slot state
        self._slot_req: List[Optional[Request]] = [None] * self.max_slots
        # per-slot incrementally-filled context (prompt + committed
        # tokens) for the speculative draft lookup — rebuilding it from
        # the output list every round would be O(n^2) per request.
        # +1 column: a full-length prompt with max_new_tokens=0 still
        # receives its one prefill token at index max_len
        self._ctx_buf = np.zeros(
            (self.max_slots, self.max_len + 1), np.int32)
        self._ctx_len = np.zeros(self.max_slots, np.int32)
        self._positions = np.zeros(self.max_slots, np.int32)
        self._tokens = np.zeros(self.max_slots, np.int32)
        self._remaining = np.zeros(self.max_slots, np.int32)
        self._queue: deque[Request] = deque()
        self._finished: List[Request] = []
        self._next_rid = 0
        self.stats = EngineStats()
        self._build_programs()

    # ------------------------------------------------------------ jit
    def _build_programs(self) -> None:
        cfg = self.cfg
        temperature, top_k, top_p = self.temperature, self.top_k, self.top_p
        n_steps = self.chunk

        @functools.partial(jax.jit, donate_argnums=(1,))
        def chunk_fn(params, cache, tokens, positions, active, rng):
            def step(carry, _):
                toks, pos, cache, key = carry
                logits, cache = decode_step(params, cfg, cache, toks, pos)
                key, sub = jax.random.split(key)
                nxt = select_token(logits, sub, temperature, top_k, top_p)
                toks = jnp.where(active, nxt.astype(toks.dtype), toks)
                pos = jnp.where(active, pos + 1, pos)
                return (toks, pos, cache, key), nxt

            (tokens, positions, cache, rng), out = jax.lax.scan(
                step, (tokens, positions, cache, rng), None,
                length=n_steps,
            )
            return out.T, tokens, positions, cache, rng

        @functools.partial(jax.jit, donate_argnums=(1,))
        def insert_fn(params, cache, tokens, real_len, slots, rng):
            """Prefill a GROUP of same-bucket prompts ([G, Lp]) and
            scatter their K/V into cache slots ``slots`` [G] in one
            dispatch (jit caches one program per (G, bucket) pair)."""
            lp = tokens.shape[1]
            logits, ks, vs = prefill(params, cfg, tokens, real_len)
            new_k = [
                ck.at[slots, :lp].set(k.astype(ck.dtype))
                for ck, k in zip(cache["k"], ks)
            ]
            new_v = [
                cv.at[slots, :lp].set(v.astype(cv.dtype))
                for cv, v in zip(cache["v"], vs)
            ]
            rng, sub = jax.random.split(rng)
            first = select_token(logits, sub, temperature, top_k, top_p)
            return {"k": new_k, "v": new_v}, first, rng

        self._chunk_fn = chunk_fn
        self._insert_fn = insert_fn

        self._spec_fn = None
        if self.speculative_k > 1:
            from dlrover_tpu.serving.model import verify_step

            @functools.partial(jax.jit, donate_argnums=(1,))
            def spec_fn(params, cache, tokens, positions):
                logits, cache = verify_step(
                    params, cfg, cache, tokens, positions)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return nxt, cache

            self._spec_fn = spec_fn

    # ------------------------------------------------------- requests
    def add_request(self, prompt_ids, max_new_tokens: int) -> int:
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        assert prompt.size >= 1
        total = prompt.size + int(max_new_tokens)
        if total > self.max_len:
            raise ValueError(
                f"prompt {prompt.size} + max_new {max_new_tokens} "
                f"exceeds engine max_len {self.max_len}")
        rid = self._next_rid
        self._next_rid += 1
        self._queue.append(Request(rid, prompt, int(max_new_tokens)))
        return rid

    def _admit(self) -> None:
        """Admit waiting requests into free slots.  Consecutive queue
        entries whose prompts land in the SAME length bucket prefill as
        one batched dispatch — at G admissions per dispatch this cuts
        the prefill launch count up to G-fold (the vLLM-style batched
        prefill; on this rig dispatch latency dominates prefill, so the
        cut is a direct wall-clock win)."""
        while self._queue:
            free = [
                s for s in range(self.max_slots)
                if self._slot_req[s] is None
            ]
            if not free:
                return
            bucket = _bucket(self._queue[0].prompt.size, self.buckets)
            group: List[Request] = []
            while (
                self._queue
                and len(group) < len(free)
                and _bucket(self._queue[0].prompt.size, self.buckets)
                == bucket
            ):
                group.append(self._queue.popleft())
            slots = free[: len(group)]
            padded = np.zeros((len(group), bucket), np.int32)
            lens = np.empty(len(group), np.int32)
            for g, req in enumerate(group):
                padded[g, : req.prompt.size] = req.prompt
                lens[g] = req.prompt.size
            t0 = time.perf_counter()
            self._cache, firsts, self._rng = self._insert_fn(
                self.params, self._cache, jnp.asarray(padded),
                jnp.asarray(lens), jnp.asarray(slots, jnp.int32),
                self._rng,
            )
            firsts = np.asarray(firsts)
            self.stats.prefill_seconds += time.perf_counter() - t0
            self.stats.prefill_calls += 1
            for g, (s, req) in enumerate(zip(slots, group)):
                first = int(firsts[g])
                self._slot_req[s] = req
                req.output.append(first)
                p = req.prompt.size
                self._ctx_buf[s, :p] = req.prompt
                self._ctx_buf[s, p] = first
                self._ctx_len[s] = p + 1
                self._tokens[s] = first
                self._positions[s] = p
                self._remaining[s] = req.max_new_tokens - 1
                self._finish_if_done(s, first)

    def _finish_if_done(self, s: int, last_token: int) -> bool:
        req = self._slot_req[s]
        assert req is not None
        if (self.eos_token is not None and last_token == self.eos_token) \
                or self._remaining[s] <= 0:
            req.done = True
            self._finished.append(req)
            self.stats.finished_requests += 1
            self._slot_req[s] = None
            return True
        return False

    # ----------------------------------------------------------- step
    @property
    def has_work(self) -> bool:
        return bool(self._queue) or any(
            r is not None for r in self._slot_req)

    def step(self) -> List[Request]:
        """Admit waiting requests, run one decode chunk (or speculative
        verify), return requests finished during this step."""
        before = len(self._finished)
        self._admit()
        active = np.array([r is not None for r in self._slot_req])
        if active.any() and self._spec_fn is not None:
            self._spec_step()
            return self._finished[before:]
        if active.any():
            t0 = time.perf_counter()
            out, tokens, positions, self._cache, self._rng = \
                self._chunk_fn(
                    self.params, self._cache,
                    jnp.asarray(self._tokens), jnp.asarray(self._positions),
                    jnp.asarray(active), self._rng,
                )
            out = np.asarray(out)                       # [B, chunk]
            # copies: jax->numpy views are read-only, but _admit mutates
            self._tokens = np.array(tokens)
            self._positions = np.array(positions)
            self.stats.decode_seconds += time.perf_counter() - t0
            for s in range(self.max_slots):
                req = self._slot_req[s]
                if req is None:
                    continue
                take = min(self.chunk, int(self._remaining[s]))
                toks = out[s, :take].tolist()
                if self.eos_token is not None and self.eos_token in toks:
                    toks = toks[: toks.index(self.eos_token) + 1]
                req.output.extend(toks)
                self._remaining[s] -= len(toks)
                self.stats.generated_tokens += len(toks)
                self._finish_if_done(s, toks[-1] if toks else -1)
        return self._finished[before:]

    def _spec_step(self) -> None:
        """One speculative round: draft K-1 tokens per slot by prompt
        lookup, verify all slots in ONE dispatch, commit the longest
        greedy-matching prefix + 1 bonus token per slot."""
        from dlrover_tpu.serving.speculative import find_draft

        k = self.speculative_k
        window = 2048  # bounded lookup tail: keeps the n-gram scan O(1)
        tokens = np.zeros((self.max_slots, k), np.int32)
        tokens[:, 0] = self._tokens
        draft_lens = np.zeros(self.max_slots, np.int32)
        for s, req in enumerate(self._slot_req):
            if req is None:
                continue
            n = int(self._ctx_len[s])
            context = self._ctx_buf[s, max(0, n - window):n]
            draft = find_draft(context, k - 1)
            if draft is not None:
                tokens[s, 1:1 + draft.size] = draft
                draft_lens[s] = draft.size
        t0 = time.perf_counter()
        nxt, self._cache = self._spec_fn(
            self.params, self._cache, jnp.asarray(tokens),
            jnp.asarray(self._positions),
        )
        nxt = np.asarray(nxt)
        self.stats.decode_seconds += time.perf_counter() - t0
        self.stats.spec_calls += 1
        for s in range(self.max_slots):
            req = self._slot_req[s]
            if req is None:
                continue
            accepted = 0
            while (accepted < draft_lens[s]
                   and nxt[s, accepted] == tokens[s, accepted + 1]):
                accepted += 1
            self.stats.spec_proposed += int(draft_lens[s])
            self.stats.spec_accepted += accepted
            toks = nxt[s, : accepted + 1].tolist()
            take = min(len(toks), int(self._remaining[s]))
            toks = toks[:take]
            if self.eos_token is not None and self.eos_token in toks:
                toks = toks[: toks.index(self.eos_token) + 1]
            if not toks:
                continue
            req.output.extend(toks)
            n = int(self._ctx_len[s])
            self._ctx_buf[s, n:n + len(toks)] = toks
            self._ctx_len[s] = n + len(toks)
            self._remaining[s] -= len(toks)
            self.stats.generated_tokens += len(toks)
            self._tokens[s] = toks[-1]
            self._positions[s] += len(toks)
            self._finish_if_done(s, toks[-1])

    def run(self) -> Dict[int, np.ndarray]:
        """Drain the queue; returns {request_id: generated tokens}."""
        while self.has_work:
            if self.eos_token is None and self._spec_fn is None:
                # fixed-budget drain needs a KNOWN number of dispatches;
                # speculative acceptance makes progress data-dependent,
                # so spec mode always goes through step()
                self._drain_fixed()
            else:
                self.step()
        return {r.rid: np.asarray(r.output, np.int32)
                for r in self._finished}

    def _drain_fixed(self) -> None:
        """No-EOS fast path: until the EARLIEST slot completion the
        number of decode chunks is known, so dispatch them all
        back-to-back and sync the host ONCE — per-chunk host round
        trips would otherwise dominate decode latency (multi-step
        scheduling taken to its fixed-budget limit)."""
        self._admit()
        active = np.array([r is not None for r in self._slot_req])
        if not active.any():
            return
        min_remaining = min(
            int(self._remaining[s]) for s in range(self.max_slots)
            if self._slot_req[s] is not None)
        n_chunks = max(1, -(-min_remaining // self.chunk))
        t0 = time.perf_counter()
        outs = []
        tokens = jnp.asarray(self._tokens)
        positions = jnp.asarray(self._positions)
        active_j = jnp.asarray(active)
        for _ in range(n_chunks):
            out, tokens, positions, self._cache, self._rng = \
                self._chunk_fn(
                    self.params, self._cache, tokens, positions,
                    active_j, self._rng,
                )
            outs.append(out)
        out = np.concatenate([np.asarray(o) for o in outs], axis=1)
        self._tokens = np.array(tokens)
        self._positions = np.array(positions)
        self.stats.decode_seconds += time.perf_counter() - t0
        for s in range(self.max_slots):
            req = self._slot_req[s]
            if req is None:
                continue
            take = min(out.shape[1], int(self._remaining[s]))
            toks = out[s, :take].tolist()
            req.output.extend(toks)
            self._remaining[s] -= len(toks)
            self.stats.generated_tokens += len(toks)
            self._finish_if_done(s, toks[-1] if toks else -1)

    # ----------------------------------------- batch-generate (RL API)
    def generate(
        self,
        prompt_ids,
        max_new_tokens: int,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """``sample_sequences``-compatible batch API for RL rollouts:
        returns (tokens [B, P+new], response_mask [B, P+new]).  A
        sequence that stopped early at EOS pads the remainder with the
        EOS token but the mask covers ONLY the actually-sampled tokens
        (through the EOS) — training signals must not weight filler the
        policy never produced."""
        prompts = np.asarray(prompt_ids, np.int32)
        batch, p_len = prompts.shape
        rids = [self.add_request(prompts[i], max_new_tokens)
                for i in range(batch)]
        outputs = self.run()
        total = p_len + max_new_tokens
        tokens = np.zeros((batch, total), np.int32)
        mask = np.zeros((batch, total), np.int32)
        for i, rid in enumerate(rids):
            out = outputs[rid]
            n_real = min(out.size, max_new_tokens)
            fill = np.full(
                max_new_tokens,
                out[-1] if out.size else 0, np.int32)
            fill[:n_real] = out[:max_new_tokens]
            tokens[i, :p_len] = prompts[i]
            tokens[i, p_len:] = fill
            mask[i, p_len:p_len + n_real] = 1
        # engine state stays warm for the next batch
        self._finished.clear()
        return tokens, mask
