"""Router-side proxy engine for a remote worker process.

:class:`RemoteReplicaHandle` satisfies the duck-typed engine contract
documented on :class:`~dlrover_tpu.serving.router.replica.ReplicaHandle`
(``add_request`` / ``step`` / ``has_work`` / ``slots_free`` /
``blocks_free`` / ``blocks_needed``) plus the streaming extra
``drain_token_events``, so the router joins it exactly like an
in-process engine — and every elasticity behavior (heartbeat reaping,
drain+requeue failover, graceful leave) applies UNCHANGED:

- a background reader thread consumes TOKEN / DONE / STATS frames;
  STATS double as the liveness signal and capacity refresh;
- a SIGKILLed worker tears the TCP stream; the reader marks the proxy
  dead and the next ``pump`` raises, which is precisely the engine-
  failure path ``ReplicaManager.reap_dead`` already handles;
- a HUNG worker (socket alive, no frames) trips the frame-staleness
  check in :meth:`step`, mapping to the same failover;
- TOKEN frames carry their RECEIVE timestamp into
  ``drain_token_events`` — TTFT is measured from true first-token
  arrival, not from the first post-placement pump.
"""

from __future__ import annotations

import threading
import time
from types import SimpleNamespace
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from dlrover_tpu.common.constants import ServingFabric
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.serving.remote.phi import PhiAccrualDetector
from dlrover_tpu.serving.remote.protocol import (
    FrameConnection,
    FrameKind,
    FrameProtocolError,
    connect,
)

# Exhaustiveness contract (dlint DL004): every FrameKind must be either
# referenced in this module or declared here with its reason.  HEARTBEAT
# is router->worker ping-on-demand; this proxy never pings — the
# worker's own STATS cadence is the liveness signal, and a silent worker
# trips frame_timeout in step() instead.
_UNHANDLED_FRAME_KINDS = (FrameKind.HEARTBEAT,)


class RemoteReplicaHandle:
    """Engine-protocol proxy over one worker's frame connection."""

    # decode-step attribution contract: in-process engines time their
    # own step() into last_step_seconds, but this proxy's step() is a
    # frame DRAIN (microseconds) — timing it would report network
    # bookkeeping as decode time.  Pinned to None so ReplicaHandle.pump
    # always takes the worker-reported path (the worker.decode span's
    # engine_seconds/steps riding the DONE frame) for remote replicas.
    last_step_seconds = None

    def __init__(
        self,
        addr: str,
        name: str = "",
        connect_timeout: float = 5.0,
        submit_timeout: float = 5.0,
        frame_timeout: float = ServingFabric.FRAME_TIMEOUT,
        fault_schedule=None,
        phi_suspect: float = ServingFabric.PHI_SUSPECT,
        phi_dead: float = ServingFabric.PHI_DEAD,
        phi_kill_floor: Optional[float] = None,
        phi_window: int = 128,
        phi_min_samples: int = 8,
    ):
        self.addr = addr
        self.name = name or addr
        self.submit_timeout = float(submit_timeout)
        self.frame_timeout = float(frame_timeout)
        # phi-accrual detection (serving/remote/phi.py): a suspicion
        # GRADIENT over frame interarrivals next to the frame_timeout
        # cliff.  phi >= phi_suspect demotes this replica in placement
        # (suspect property); phi >= phi_dead AND silence past
        # phi_kill_floor fails it over EARLY — with the floor unset
        # (the default) phi never kills, so frame_timeout remains the
        # sole and unchanged death sentence; it stays the hard ceiling
        # either way.
        self.phi_suspect = float(phi_suspect)
        self.phi_dead = float(phi_dead)
        self.phi_kill_floor = (
            None if phi_kill_floor is None else float(phi_kill_floor))
        self._phi = PhiAccrualDetector(
            window=phi_window, min_samples=phi_min_samples)
        if fault_schedule is not None:
            # chaos seam (serving/remote/faults.py): perturb this
            # proxy's router->worker frames (SUBMIT/CANCEL/GOODBYE)
            from dlrover_tpu.serving.remote.faults import maybe_faulty

            self._conn = maybe_faulty(
                connect(addr, connect_timeout), fault_schedule)
        else:
            self._conn = FrameConnection(connect(addr, connect_timeout))
        # RLock: _dispatch(GOODBYE) -> _mark_dead re-enters under the
        # reader's own hold
        self._lock = threading.RLock()
        self._dead: Optional[str] = None
        self._closing = False  # deliberate close() in progress
        self._inflight: Set[int] = set()  # rids placed, not yet DONE
        self._finished: List[SimpleNamespace] = []
        # (rid, tokens, receive-time) — drained by ReplicaHandle.pump
        self._token_events: List[Tuple[int, List[int], float]] = []
        self._submit_replies: Dict[int, dict] = {}
        self._submit_cv = threading.Condition(self._lock)
        self._next_rid = 0
        # CANCEL frames that failed to send (router aggregates these
        # into serving_cancel_send_failures_total); logged once per
        # replica at debug — see cancel()
        self.cancel_send_failures = 0
        self._cancel_fail_logged = False
        # batched-drain introspection: frames per lock crossing — under
        # a token storm batches >> 1, which is the reader-coalescing
        # win (tests assert frames_received / frame_batches grows)
        self.frames_received = 0
        self.frame_batches = 0
        try:
            hello = self._conn.recv(timeout=connect_timeout)
        except Exception:
            # a wedged worker (accepted, never HELLOed) must not leak
            # the socket — the supervisor's respawn retries would pile
            # up one fd per attempt
            self._conn.close()
            raise
        if hello is None or hello.get("kind") != FrameKind.HELLO:
            self._conn.close()
            raise ConnectionError(
                f"worker {addr} did not open with HELLO: {hello!r}")
        self._slots_free = int(hello.get("slots_free", 0))
        self._blocks_free = float(hello.get("blocks_free", 0.0))
        self.block_size = int(hello.get("block_size", 0))
        self.engine_kind = str(hello.get("engine", "?"))
        # STATS staleness watermark: the worker's generated_tokens
        # counter is monotonic within a connection, so a STATS carrying
        # a LOWER value than one already applied arrived out of order
        # (recv-side reorder, a retransmit artifact) — applying it
        # would regress the capacity ledger and over-place
        self._stats_tokens = -1
        self._stats_seq_seen = 0
        # the worker's OWN view of its in-flight count (STATS
        # "inflight"): surfaced when the worker goes silent so the
        # failover log distinguishes "died idle" from "died holding
        # N requests" without trusting this side's ledger, which a
        # lost DONE frame can leave overcounted
        self._worker_inflight = 0
        self.stale_stats_dropped = 0
        self._engine_metrics: Optional[Dict[str, float]] = None
        self._prefix_heads: List[str] = []
        self._profile: Optional[dict] = None
        self._last_frame = time.monotonic()
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"replica-reader-{self.name}")
        self._reader.start()

    # ----------------------------------------------------- reader side
    def _read_loop(self) -> None:
        while self.dead is None and not self._conn.closed:
            try:
                # batched drain: one select wakeup scoops EVERY frame
                # already buffered behind the first — under a token
                # storm (N slots streaming per engine step) the
                # dispatch below then crosses the proxy lock once per
                # BATCH instead of once per TOKEN frame, which is
                # exactly the contention the router's step lock used
                # to eat (recv_many keeps per-frame fault injection:
                # it reads frames through recv)
                frames = self._conn.recv_many(timeout=0.5)
            except TimeoutError:
                # no frame in 0.5s is NOT death by itself — staleness
                # is judged against frame_timeout in step(); keep going
                continue
            except Exception as e:
                self._mark_dead(f"stream torn: {e}")
                return
            if frames is None:
                self._mark_dead("worker closed the connection")
                return
            try:
                self._dispatch_batch(frames)
            except Exception as e:
                # a malformed frame (missing rid, bad field type) must
                # kill the proxy LOUDLY, not leave a zombie reader that
                # silently drops every subsequent frame
                self._mark_dead(f"malformed frame in batch: {e}")
                return

    def _dispatch(self, frame: dict) -> None:
        """Single-frame dispatch (tests drive this directly; the read
        loop goes through :meth:`_dispatch_batch`)."""
        self._dispatch_batch([frame])

    def _dispatch_batch(self, frames: List[dict]) -> None:
        now = time.monotonic()
        self.frames_received += len(frames)
        self.frame_batches += 1
        with self._lock:
            # feed the phi detector the interarrival gap BEFORE the
            # stamp moves: one gap per batch (frames drained together
            # arrived together — intra-batch gaps are ~0 and carry no
            # timing signal, observe() ignores them anyway)
            self._phi.observe(now - self._last_frame)
            self._last_frame = now
            for frame in frames:
                self._dispatch_locked(frame, now)
                if self._dead is not None:
                    # a GOODBYE mid-batch closed the proxy; anything
                    # behind it on the wire is from a peer that said
                    # farewell first
                    return

    def _dispatch_locked(self, frame: dict, now: float) -> None:
        kind = frame.get("kind")
        if kind == FrameKind.TOKEN:
            rid = int(frame["rid"])
            if rid in self._inflight:
                self._token_events.append(
                    (rid, list(frame["tokens"]), now))
        elif kind == FrameKind.DONE:
            rid = int(frame["rid"])
            if rid in self._inflight:
                self._inflight.discard(rid)
                # span shifting only when the worker actually shipped
                # spans (sampled-in traces): a sampled-out request's
                # DONE pays zero tracing work on this thread
                spans = (self._shift_spans(frame, now)
                         if frame.get("spans") else [])
                self._finished.append(SimpleNamespace(
                    rid=rid, output=list(frame["tokens"]),
                    trace_spans=spans,
                    # hedge attempt id echoed from SUBMIT (None from
                    # unhedged submits and older workers) — lets the
                    # router audit WHICH dispatch attempt won the race
                    attempt=frame.get("attempt")))
        elif kind == FrameKind.STATS:
            seq = frame.get("seq")
            seq = int(seq) if isinstance(seq, (int, float)) else None
            gen = frame.get("generated_tokens")
            gen = int(gen) if isinstance(gen, (int, float)) else None
            if seq is not None:
                # per-send ordinal (current workers): a strict
                # total order, so duplicates AND equal-token
                # reorders (two snapshots with no decode step
                # between them, e.g. around a SUBMIT) are droppable
                stale = seq <= self._stats_seq_seen
            else:
                # token watermark fallback (seq-less sender): a
                # snapshot older than one already applied must not
                # regress the ledger — freed capacity would be
                # forgotten or phantom capacity resurrected; equal
                # still refreshes (cancels free slots without
                # generating)
                stale = gen is not None and gen < self._stats_tokens
            if stale:
                self.stale_stats_dropped += 1
            else:
                if seq is not None:
                    self._stats_seq_seen = seq
                if gen is not None:
                    self._stats_tokens = gen
                self._slots_free = int(frame.get("slots_free", 0))
                self._blocks_free = float(
                    frame.get("blocks_free", 0.0))
                self._worker_inflight = int(
                    frame.get("inflight", 0))
                em = frame.get("engine_metrics")
                if isinstance(em, dict):
                    # raw-speed introspection (spec accept ratio,
                    # int8 KV pool, chunked-prefill seconds) from
                    # engines that report it; absent on FakeEngine
                    # workers and older senders
                    self._engine_metrics = {
                        str(k): float(v) for k, v in em.items()
                        if isinstance(v, (int, float))
                    }
                prof = frame.get("profile")
                if isinstance(prof, dict):
                    # continuous-profiler tables from a --profile
                    # worker: cumulative, so latest-wins replacement
                    # is the whole merge; absent on unprofiled workers
                    self._profile = prof
                heads = frame.get("prefix_heads")
                if isinstance(heads, list):
                    # hottest committed prefix heads (hex digests):
                    # replacement semantics — the latest advertised
                    # set IS the replica's current hot set, so the
                    # router's routing table drops what vanished
                    self._prefix_heads = [
                        str(h) for h in heads if isinstance(h, str)
                    ]
        elif kind in (FrameKind.SUBMITTED, FrameKind.ERROR):
            self._submit_replies[int(frame["rid"])] = frame
            self._submit_cv.notify_all()
        elif kind == FrameKind.GOODBYE:
            self._mark_dead("worker said goodbye", graceful=True)

    @staticmethod
    def _shift_spans(frame: dict, now: float) -> list:
        """Worker-side spans ride the DONE frame in the WORKER's
        monotonic clock, which means nothing in this process.  The
        frame also carries ``sent_at`` (worker clock at send); the
        receive time ``now`` is the same instant in OUR clock, so
        ``now - sent_at`` translates every span (error = one-way
        network latency, microseconds on the links this fabric runs).
        Returns spans ready for ``Tracer.graft``; anything malformed
        degrades to no spans, never to a dead replica."""
        spans = frame.get("spans")
        sent_at = frame.get("sent_at")
        if not spans or not isinstance(sent_at, (int, float)):
            return []
        shift = now - float(sent_at)
        out = []
        for raw in spans:
            try:
                out.append(dict(
                    raw,
                    start=float(raw["start"]) + shift,
                    end=float(raw["end"]) + shift,
                ))
            except (KeyError, TypeError, ValueError):
                continue
        return out

    def _mark_dead(self, reason: str, graceful: bool = False) -> None:
        with self._lock:
            first = self._dead is None
            if first:
                self._dead = reason
            self._submit_cv.notify_all()
        # only the call that actually killed the proxy warns — the
        # reader re-detecting a close()d socket, or the peer's EOF
        # answering OUR deliberate goodbye, is not news
        if not graceful and first and not self._closing:
            logger.warning(
                "remote replica %s dead: %s", self.name, reason)
        self._conn.close()

    # -------------------------------------------------- engine protocol
    def add_request(self, prompt, max_new_tokens: int,
                    trace: Optional[str] = None,
                    attempt: Optional[int] = None) -> int:
        """Synchronous SUBMIT round trip.  An engine-side rejection
        (ERROR frame) raises ``ValueError`` — the router's poison-
        request path; a torn/silent worker raises ``ConnectionError`` —
        the router's failover path.  ``trace`` (a W3C-style traceparent
        from the request's span trace) rides the SUBMIT header so the
        worker's own spans come back on DONE and graft into the
        request's tree.

        Tradeoff, documented: the ack wait runs under the router's step
        lock, so a wedged worker can stall placement for up to
        ``submit_timeout`` (once — the timeout fails the replica over).
        The synchronous ack is what gives remote engines rejection
        parity with local ones (ValueError at submit time); an async
        submit pipeline is a future rung if placement RTTs ever show up
        in the step budget (localhost RTT is ~µs today)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        total = int(prompt.size) + int(max_new_tokens)
        with self._lock:
            if self._dead is not None:
                raise ConnectionError(self._dead)
            rid = self._next_rid
            self._next_rid += 1
            # register BEFORE sending: a fast worker's first TOKEN (or
            # even DONE) frame can beat this thread back to the lock
            # after the SUBMITTED ack — an unregistered rid would drop
            # those frames and strand the request in-flight forever
            self._inflight.add(rid)
        try:
            try:
                extra = {} if trace is None else {"trace": trace}
                if attempt is not None:
                    # hedge attempt ordinal (0 = primary dispatch,
                    # 1+ = hedges); the worker echoes it on DONE so
                    # the winner of a hedge race is auditable
                    extra["attempt"] = int(attempt)
                self._conn.send(
                    FrameKind.SUBMIT, rid=rid,
                    prompt=prompt.tolist(),
                    max_new_tokens=int(max_new_tokens),
                    **extra,
                )
            except FrameProtocolError as e:
                # a request too large to FRAME is the request's defect,
                # not the replica's: surface it on the rejection path
                # (ValueError -> router REJECTED) or a healthy replica
                # would be failed over for every oversized submit
                raise ValueError(f"request unframeable: {e}") from e
            deadline = time.monotonic() + self.submit_timeout
            with self._lock:
                while rid not in self._submit_replies:
                    if self._dead is not None:
                        raise ConnectionError(self._dead)
                    remaining = deadline - time.monotonic()
                    timed_out = remaining <= 0 or \
                        not self._submit_cv.wait(remaining)
                    # re-check before raising: the ack can land exactly
                    # on the timeout boundary (wait returns False AFTER
                    # the reader stored the reply), and a spurious raise
                    # here would fail over a healthy replica
                    if timed_out and rid not in self._submit_replies:
                        raise ConnectionError(
                            f"worker {self.name}: no SUBMIT ack in "
                            f"{self.submit_timeout}s")
                reply = self._submit_replies.pop(rid)
                if reply["kind"] == FrameKind.ERROR:
                    raise ValueError(str(reply.get("error", "rejected")))
                # optimistic ledger: the next STATS frame overwrites
                self._slots_free = max(0, self._slots_free - 1)
                if self.block_size:
                    self._blocks_free -= -(-total // self.block_size)
        except Exception:
            with self._lock:
                self._inflight.discard(rid)
            raise
        return rid

    def step(self) -> List[SimpleNamespace]:
        """Return requests finished since the last pump.  Raises when
        the worker is dead OR silent past ``frame_timeout`` — a
        successful return is a genuine liveness proof, which is what
        makes ``ReplicaHandle.pump``'s heartbeat semantics hold for a
        process the router cannot observe directly."""
        now = time.monotonic()
        with self._lock:
            if self._dead is not None:
                raise ConnectionError(self._dead)
            silence = now - self._last_frame
            if silence > self.frame_timeout:
                raise ConnectionError(
                    f"worker {self.name} silent for "
                    f"{silence:.1f}s (> frame_timeout "
                    f"{self.frame_timeout}s); last STATS reported "
                    f"{self._worker_inflight} inflight")
            if (self.phi_kill_floor is not None
                    and silence >= self.phi_kill_floor):
                phi = self._phi.phi(silence)
                if phi >= self.phi_dead:
                    raise ConnectionError(
                        f"worker {self.name} phi={phi:.1f} (>= "
                        f"phi_dead {self.phi_dead}) after "
                        f"{silence:.2f}s silence; last STATS reported "
                        f"{self._worker_inflight} inflight")
            finished, self._finished = self._finished, []
            return finished

    @property
    def has_work(self) -> bool:
        # a dead/stale proxy must claim work so ReplicaHandle.pump
        # actually calls step() and hits the failover path — an idle
        # corpse would otherwise keep "heartbeating" forever
        with self._lock:
            if self._dead is not None or self._finished:
                return True
            silence = time.monotonic() - self._last_frame
            if silence > self.frame_timeout:
                return True
            if (self.phi_kill_floor is not None
                    and silence >= self.phi_kill_floor
                    and self._phi.phi(silence) >= self.phi_dead):
                return True
            return bool(self._inflight)

    # --------------------------------------------- suspicion gradient
    def phi_value(self, now: Optional[float] = None) -> float:
        """Current phi-accrual suspicion for this replica (0.0 until
        the detector has its minimum interarrival history)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if self._dead is not None:
                # already past suspicion: the failover path owns a dead
                # proxy, and the phi gauges must stay finite
                return 0.0
            return self._phi.phi(now - self._last_frame)

    def suspect(self, now: Optional[float] = None) -> bool:
        """True when suspicion crosses ``phi_suspect`` but the replica
        is not (yet) dead — the gray zone: demote in placement, keep
        serving in-flight work, no failover."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if self._dead is not None:
                return False
            return self._phi.phi(now - self._last_frame) \
                >= self.phi_suspect

    def slots_free(self) -> int:
        with self._lock:
            return 0 if self._dead is not None else self._slots_free

    def blocks_free(self) -> float:
        with self._lock:
            return 0.0 if self._dead is not None else self._blocks_free

    def engine_metrics(self) -> Optional[Dict[str, float]]:
        """Latest engine introspection dict from STATS, or None when
        the worker's engine doesn't report one (FakeEngine).  A dead
        replica reports None like slots_free/blocks_free report zero:
        the fleet gauges must not keep aggregating a corpse's cached
        numbers while its handle awaits the reap."""
        with self._lock:
            if self._dead is not None:
                return None
            em = self._engine_metrics
            return dict(em) if em else None

    def prefix_heads(self) -> List[str]:
        """Latest advertised hot prefix heads from STATS ([] while
        none arrived, or once the replica is dead — a corpse must not
        keep feeding the routing table)."""
        with self._lock:
            if self._dead is not None:
                return []
            return list(self._prefix_heads)

    def profile_snapshot(self) -> Optional[dict]:
        """Latest continuous-profiler snapshot the worker shipped over
        STATS (None while none arrived — unprofiled worker — or once
        the replica is dead: a corpse's flame must not keep merging
        into the fleet view as if it were live)."""
        with self._lock:
            if self._dead is not None:
                return None
            return self._profile

    def blocks_needed(self, prompt_len: int,
                      max_new_tokens: int) -> Optional[float]:
        if not self.block_size:
            return None  # scheduler falls back to its own default
        return float(
            -(-(int(prompt_len) + int(max_new_tokens)) // self.block_size))

    # ------------------------------------------------- streaming extras
    def drain_token_events(
        self, now: Optional[float] = None
    ) -> List[Tuple[int, List[int], float]]:
        """TOKEN frames received since the last drain, each stamped with
        its true arrival time (``now`` is ignored: receipt already
        happened — this is the TTFT-semantics change)."""
        with self._lock:
            events, self._token_events = self._token_events, []
            return events

    def cancel(self, rid: int) -> bool:
        """Withdraw a placed request: drop its frames from here on and
        send CANCEL so the worker frees the slot + KV blocks.  Returns
        False when the frame could not be delivered — a dead worker
        cancelled everything anyway, but the caller counts it into
        ``serving_cancel_send_failures_total`` because a LIVE worker
        that missed a cancel keeps decoding a dropped request."""
        with self._lock:
            self._inflight.discard(rid)
        try:
            self._conn.send(FrameKind.CANCEL, rid=rid)
        except (ConnectionError, OSError, TimeoutError) as e:
            self.cancel_send_failures += 1
            if not self._cancel_fail_logged:
                # once per replica: every queued cancel fails the same
                # way once the connection is gone — one line carries
                # the signal, a line per request is log spam mid-death
                self._cancel_fail_logged = True
                logger.debug(
                    "CANCEL send to replica %s failed "
                    "(counted, logged once): %s", self.name, e)
            return False
        return True

    # -------------------------------------------------------- lifecycle
    @property
    def dead(self) -> Optional[str]:
        # locked so the None -> reason transition in _mark_dead is
        # never half-observed next to the state it guards (_inflight,
        # _submit_replies are only consistent with _dead under _lock)
        with self._lock:
            return self._dead

    def close(self, goodbye: bool = True) -> None:
        self._closing = True
        if goodbye and self.dead is None:
            try:
                self._conn.send(FrameKind.GOODBYE)
                # half-close and let the reader drain to EOF: a full
                # close with unread STATS in our buffer would RST the
                # stream and can destroy the in-flight GOODBYE — the
                # worker would never learn it should exit
                self._conn.half_close()
                self._reader.join(timeout=2.0)
            except (ConnectionError, OSError, TimeoutError):
                pass
        self._mark_dead("closed by router", graceful=True)
        self._reader.join(timeout=2.0)
