"""Length-prefixed msgpack frame protocol for the replica data plane.

The control plane speaks a 2-method gRPC envelope (common/rpc.py); the
data plane cannot — token streaming wants many small one-way messages
per request with no per-message round trip, and a replica worker must
stay importable on a bare image.  So the fabric uses the dependency-
lightest thing that works: a TCP socket carrying ``[4-byte big-endian
length][msgpack map]`` frames (msgpack is already the wire format of
``common/serialize.py``; frames here are plain maps, no class registry
needed — both ends of this protocol ship in this repo).

Frame kinds (the ``kind`` key of every frame):

====================  ======  =============================================
kind                  dir     payload
====================  ======  =============================================
``HELLO``             w -> r  ``addr``, ``slots_free``, ``blocks_free``,
                              ``block_size``, ``engine`` — capability
                              handshake, first frame on every connection
``SUBMIT``            r -> w  ``rid``, ``prompt`` (list[int]),
                              ``max_new_tokens``; optional ``trace`` —
                              a W3C-style traceparent
                              (``00-<trace_id>-<span_id>-01``, see
                              utils/tracing.py) parenting worker-side
                              spans under the request's current attempt
``SUBMITTED``         w -> r  ``rid`` — the engine admitted the request
``ERROR``             w -> r  ``rid``, ``error`` — the engine REJECTED it
                              (poison request; never a worker crash)
``CANCEL``            r -> w  ``rid`` — best-effort withdrawal
``TOKEN``             w -> r  ``rid``, ``tokens`` (list[int]) — streamed
                              as emitted; TTFT is measured at the first
                              one RECEIVED; echoes ``trace`` when the
                              SUBMIT carried one (wire-sniffer
                              correlation)
``DONE``              w -> r  ``rid``, ``tokens`` — the full,
                              authoritative output; plus ``trace``,
                              ``spans`` (worker-side span dicts in the
                              worker's monotonic clock) and ``sent_at``
                              (worker clock at send — the proxy's
                              anchor for translating span times into
                              router time) when the SUBMIT was traced
``STATS``             w -> r  ``slots_free``, ``blocks_free``,
                              ``inflight``, ``generated_tokens`` —
                              capacity refresh AND liveness heartbeat
``HEARTBEAT``         r -> w  ping; the worker answers with a STATS
``GOODBYE``           either  graceful shutdown of the peer
====================  ======  =============================================

Direction: ``r`` = router proxy, ``w`` = worker.

Unknown keys in any frame are ignored by both ends (frames are plain
msgpack maps), so the trace headers are backward- and forward-
compatible: an untraced router talks to a tracing worker and vice
versa.
"""

from __future__ import annotations

import select
import socket
import struct
import threading
import time
from typing import List, Optional, Tuple

import msgpack

# one token frame can carry a whole max-length output plus slack; a
# larger announced length is a corrupt/hostile peer, not a big message
MAX_FRAME_BYTES = 16 * 1024 * 1024
_LEN = struct.Struct(">I")


class FrameKind:
    HELLO = "HELLO"
    SUBMIT = "SUBMIT"
    SUBMITTED = "SUBMITTED"
    ERROR = "ERROR"
    CANCEL = "CANCEL"
    TOKEN = "TOKEN"
    DONE = "DONE"
    STATS = "STATS"
    HEARTBEAT = "HEARTBEAT"
    GOODBYE = "GOODBYE"


# Frame keys that are sent but deliberately not read by any current
# receiver — dlint's DL013 schema-drift checker flags every other
# sent-but-never-read key.  Each entry carries the reason the key stays
# on the wire anyway; an entry whose key gains a reader (or loses its
# last sender) becomes a stale declaration and is itself flagged.
_FRAME_OPTIONAL_KEYS = {
    (FrameKind.HELLO, "addr"): (
        "self-identification for wire sniffers and debug logging: the "
        "proxy already knows the addr it dialed, but a capture of the "
        "handshake alone must name the worker"
    ),
}


class FrameProtocolError(ConnectionError):
    """The peer violated the frame protocol (oversized/truncated frame)."""


def parse_addr(addr: str) -> Tuple[str, int]:
    host, port = addr.rsplit(":", 1)
    return host, int(port)


def connect(addr: str, timeout: float = 5.0) -> socket.socket:
    """TCP-connect to a worker; TCP_NODELAY because the whole point is
    many small latency-sensitive frames."""
    sock = socket.create_connection(parse_addr(addr), timeout=timeout)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    sock.settimeout(None)
    return sock


class FrameConnection:
    """One framed duplex connection; sends are thread-safe, receives
    belong to a single reader (buffered, so a receive timeout mid-frame
    never loses stream sync).

    ``send_timeout`` bounds every ``sendall``: a peer that stops
    reading (SIGSTOPped process, wedged event loop) fills the kernel
    send buffer, and an unbounded blocking send there would freeze the
    caller — for the router-side proxy that would be the whole router
    pump — instead of surfacing the failover-able TimeoutError.
    Receives are unaffected (they wait in select, never in a blocking
    socket call)."""

    def __init__(self, sock: socket.socket,
                 send_timeout: Optional[float] = 10.0):
        if send_timeout is not None:
            sock.settimeout(send_timeout)
        self._sock = sock
        self._send_lock = threading.Lock()
        self._buf = bytearray()
        self._eof = False
        self._closed = False
        # error stashed by recv_many when a tear lands MID-BATCH:
        # raised by the next recv so the frames received before the
        # tear are never lost to the error that followed them
        self._deferred_exc: Optional[Exception] = None

    # ------------------------------------------------------------ send
    def send(self, kind: str, **payload) -> None:
        payload["kind"] = kind
        body = msgpack.packb(payload, use_bin_type=True)
        if len(body) > MAX_FRAME_BYTES:
            raise FrameProtocolError(
                f"frame of {len(body)} bytes exceeds cap {MAX_FRAME_BYTES}")
        with self._send_lock:
            if self._closed:
                raise ConnectionError("frame connection closed")
            # dlint: disable=DL003 bounded by send_timeout (socket timeout set in __init__); a wedged peer raises TimeoutError into the failover path instead of freezing lock users
            self._sock.sendall(_LEN.pack(len(body)) + body)

    # ------------------------------------------------------------ recv
    def _parse_one(self) -> Optional[dict]:
        if len(self._buf) < _LEN.size:
            return None
        (n,) = _LEN.unpack(bytes(self._buf[:_LEN.size]))
        if n > MAX_FRAME_BYTES:
            raise FrameProtocolError(
                f"peer announced a {n}-byte frame (cap {MAX_FRAME_BYTES})")
        if len(self._buf) < _LEN.size + n:
            return None
        body = bytes(self._buf[_LEN.size:_LEN.size + n])
        del self._buf[:_LEN.size + n]
        frame = msgpack.unpackb(body, raw=False, strict_map_key=False)
        if not isinstance(frame, dict) or "kind" not in frame:
            raise FrameProtocolError("frame body is not a kinded map")
        return frame

    def recv(self, timeout: Optional[float] = None) -> Optional[dict]:
        """One frame, or ``None`` on clean EOF (peer closed at a frame
        boundary).  Raises ``TimeoutError`` when ``timeout`` elapses
        first — buffered partial bytes are KEPT, so the next call
        resumes mid-frame — and ``ConnectionError`` on a torn stream
        (EOF inside a frame: the SIGKILLed-worker signature)."""
        exc = self._deferred_exc
        if exc is not None:
            # a recv_many batch hit this error AFTER already receiving
            # complete frames: those frames were delivered, the error
            # was deferred to here so none of them could be lost
            self._deferred_exc = None
            raise exc
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            frame = self._parse_one()
            if frame is not None:
                return frame
            if self._eof or self._closed:
                if self._buf:
                    raise FrameProtocolError(
                        "connection closed mid-frame "
                        f"({len(self._buf)} trailing bytes)")
                return None
            wait = None if deadline is None else \
                max(0.0, deadline - time.monotonic())
            ready, _, _ = select.select([self._sock], [], [], wait)
            if not ready:
                raise TimeoutError("no frame within timeout")
            try:
                chunk = self._sock.recv(1 << 16)
            except OSError as e:
                raise ConnectionError(f"recv failed: {e}") from e
            if not chunk:
                self._eof = True
                continue
            self._buf += chunk

    def recv_many(self, timeout: Optional[float] = None,
                  max_frames: int = 256) -> Optional[List[dict]]:
        """One blocking :meth:`recv` plus every frame already buffered
        (or immediately readable) behind it, as one batch — the
        reader-thread coalescing primitive: a consumer dispatches the
        whole batch under ONE lock crossing instead of one per TOKEN
        frame.  Built ON ``recv`` (zero-timeout tail reads), so a
        fault-injecting subclass's per-frame ``recv`` override applies
        to every frame in the batch — chaos coverage is not weakened
        by batching.

        Returns ``None`` on clean EOF with nothing buffered; a torn
        stream mid-batch DEFERS its error (raised by the next call) so
        the frames that preceded the tear are delivered, matching what
        per-frame reads would have seen."""
        first = self.recv(timeout=timeout)
        if first is None:
            return None
        frames = [first]
        while len(frames) < max_frames:
            try:
                nxt = self.recv(timeout=0)
            except TimeoutError:
                break  # nothing more buffered or readable right now
            except Exception as e:  # torn mid-batch: deliver, defer
                self._deferred_exc = e
                break
            if nxt is None:
                break  # EOF at a frame boundary; next call returns None
            frames.append(nxt)
        return frames

    # ----------------------------------------------------------- close
    def half_close(self) -> None:
        """Shut down the WRITE side only, letting already-sent frames
        (a GOODBYE) drain to the peer.  A full close with unread data
        in our receive buffer would RST the connection and can destroy
        the in-flight farewell."""
        try:
            self._sock.shutdown(socket.SHUT_WR)
        except OSError:
            pass

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()

    @property
    def closed(self) -> bool:
        return self._closed
