"""Local-process worker supervisor: spawn / monitor / respawn replicas.

The deployment-shaped end of the fabric.  On a laptop or a single TPU
host, :class:`WorkerSupervisor` IS the scheduler: it spawns
``python -m dlrover_tpu.serving.remote.worker`` subprocesses, reads
each worker's self-announced address (the worker binds port 0 itself —
see the race note on :func:`~dlrover_tpu.common.rpc.find_free_port`),
connects a :class:`~dlrover_tpu.serving.remote.proxy.
RemoteReplicaHandle`, and joins it to the router.  In a cluster the
same seam is the autoscale loop's ``engine_factory``: the Scaler
(in-memory in tests, PodScaler/ActorScaler stubs in deployments)
creates nodes, the :class:`~dlrover_tpu.serving.router.autoscale.
ReplicaProvisioner` turns each node into a replica by calling
:meth:`WorkerSupervisor.engine_factory` — so a scale-up launches REAL
processes.

Every spawned process is registered in a module-level table so a
crashing test session can always be swept clean (:func:`reap_orphans`,
wired into ``tests/conftest.py``).
"""

from __future__ import annotations

import os
import random
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from dlrover_tpu.common.announce import read_announced_value
from dlrover_tpu.common.constants import ServingFabric
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.serving.remote.proxy import RemoteReplicaHandle
from dlrover_tpu.serving.router.replica import base_replica_name

# every live worker Popen, across all supervisors in the process —
# the session-end reaper's ground truth
_ALL_WORKERS: List[subprocess.Popen] = []
_ALL_LOCK = threading.Lock()


def _register(proc: subprocess.Popen) -> None:
    with _ALL_LOCK:
        # prune already-exited entries so a long-lived router process
        # doesn't accumulate dead Popen objects one per spawn
        _ALL_WORKERS[:] = [p for p in _ALL_WORKERS if p.poll() is None]
        _ALL_WORKERS.append(proc)


def reap_orphans(grace: float = 1.0) -> int:
    """Kill every worker subprocess still alive (SIGTERM, then SIGKILL
    after ``grace``).  Returns how many needed reaping.  Safe to call
    repeatedly; tests/conftest.py runs it at session end so one failing
    test can never strand workers that hang the suite."""
    with _ALL_LOCK:
        procs, _ALL_WORKERS[:] = list(_ALL_WORKERS), []
    live = [p for p in procs if p.poll() is None]
    for p in live:
        try:
            p.terminate()
        except OSError:
            pass
    deadline = time.monotonic() + grace
    for p in live:
        try:
            p.wait(timeout=max(0.0, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            try:
                p.kill()
                p.wait(timeout=grace)
            except (OSError, subprocess.TimeoutExpired):
                pass
    return len(live)


def serving_worker_command(
    python: Optional[str] = None,
    engine: str = "llama",
    host: str = "0.0.0.0",
    port: int = 0,
    extra_args: Optional[List[str]] = None,
) -> List[str]:
    """The replica-process command line, shared by this supervisor and
    the k8s/ray scaler stubs.  ``port=0`` is deliberate and should stay:
    the worker binds the port itself and announces it — pre-picking one
    here would reintroduce the bind-then-close race."""
    return [
        python or sys.executable,
        "-m", "dlrover_tpu.serving.remote.worker",
        "--engine", engine,
        "--host", host,
        "--port", str(int(port)),
        *(extra_args or []),
    ]


class WorkerRecord:
    """One supervised worker process (plus its crash-loop history —
    the sliding-window crash timestamps, the planned backoff schedule
    and any quarantine sentence ride the record chain across respawn
    generations, so a flapping worker cannot launder its history by
    getting a fresh record)."""

    def __init__(self, name: str, proc: subprocess.Popen, addr: str,
                 proxy: RemoteReplicaHandle, managed: bool):
        self.name = name
        self.proc = proc
        self.addr = addr
        self.proxy = proxy
        self.managed = managed       # supervisor respawns it on death
        self.respawns = 0
        # crash timestamps still inside the respawn window (monotonic)
        self.crash_times: List[float] = []
        # actual respawn spawn times (the chaos suite asserts strictly
        # increasing gaps here — the anti-hot-loop proof)
        self.respawn_times: List[float] = []
        # planned schedule: {exit_at, respawn_at, backoff_s} per crash
        self.respawn_schedule: List[dict] = []
        self.respawn_at = 0.0        # next planned respawn (pending)
        self.quarantine_until = 0.0


class WorkerSupervisor:
    """Spawn and babysit local worker processes for a router."""

    def __init__(
        self,
        router=None,
        worker_args: Optional[List[str]] = None,
        engine: str = "fake",
        host: str = "127.0.0.1",
        spawn_timeout: float = 30.0,
        respawn: bool = True,
        max_respawns: int = 5,
        respawn_window: float = 60.0,
        backoff_base: float = 0.5,
        backoff_max: float = 30.0,
        backoff_jitter: float = 0.25,
        quarantine_seconds: float = 120.0,
        seed: Optional[int] = None,
        name_prefix: str = "worker",
        recorder=None,
    ):
        """``max_respawns`` is a SLIDING-WINDOW budget: that many
        crash-respawns within ``respawn_window`` seconds sends the
        worker to quarantine for ``quarantine_seconds`` (it comes back
        with a clean window afterwards — the fleet is never silently
        permanently smaller).  Each respawn waits an exponential
        backoff: ``backoff_base * 2**(crashes_in_window - 1)`` capped
        at ``backoff_max``, stretched by up to ``backoff_jitter``
        (seeded — chaos tests pass ``seed`` for reproducible
        schedules) so a mass crash doesn't respawn in lockstep."""
        self.router = router
        # fabric flight recorder (utils/tracing.FlightRecorder): worker
        # spawn/exit/respawn events land next to the router's
        # replica/request events, so a postmortem dump shows the
        # process-level story too.  Defaults to the router's own.
        if recorder is None and router is not None:
            recorder = getattr(router, "recorder", None)
        self.recorder = recorder
        self.worker_args = list(worker_args or [])
        self.engine = engine
        self.host = host
        self.spawn_timeout = float(spawn_timeout)
        self.respawn = bool(respawn)
        self.max_respawns = int(max_respawns)
        self.respawn_window = float(respawn_window)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self.backoff_jitter = float(backoff_jitter)
        self.quarantine_seconds = float(quarantine_seconds)
        self._rng = random.Random(seed)
        self.name_prefix = name_prefix
        self.workers: Dict[str, WorkerRecord] = {}
        # dead records waiting out their backoff before respawn
        self.pending: Dict[str, WorkerRecord] = {}
        # crash-loopers sitting out their quarantine sentence
        self.quarantined: Dict[str, WorkerRecord] = {}
        self.quarantined_total = 0
        self._next = 0
        self._lock = threading.Lock()

    # ----------------------------------------------------------- spawn
    def _command(self) -> List[str]:
        return serving_worker_command(
            engine=self.engine, host=self.host,
            extra_args=self.worker_args,
        )

    def spawn(self, name: Optional[str] = None,
              join: bool = True, managed: bool = True) -> WorkerRecord:
        """Launch one worker, wait for its address announce, connect the
        proxy and (``join=True``) join it to the router."""
        with self._lock:
            if name is None:
                name = f"{self.name_prefix}-{self._next}"
                self._next += 1
            if name in self.workers:
                raise ValueError(f"worker {name} already supervised")
        proc = subprocess.Popen(
            self._command(),
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
        )
        _register(proc)
        try:
            addr = self._read_announce(proc)
            proxy = RemoteReplicaHandle(addr, name=name)
        except Exception:
            try:
                proc.kill()
            except OSError:
                pass
            raise
        record = WorkerRecord(name, proc, addr, proxy, managed)
        with self._lock:
            self.workers[name] = record
        if join and self.router is not None:
            self.router.join_replica(name, proxy)
        if self.recorder is not None:
            self.recorder.record(
                "worker_spawn", worker=name, pid=proc.pid, addr=addr)
        logger.info("spawned serving worker %s (pid %d) at %s",
                    name, proc.pid, addr)
        return record

    def _read_announce(self, proc: subprocess.Popen) -> str:
        """First ``DLROVER_WORKER_ADDR=`` stdout line — the shared
        announce handshake (common/announce.py): off-thread timeout,
        fail-fast on an already-dead child, stdout drained for the
        process's lifetime so a chatty worker can't fill the pipe and
        read as a dead replica."""
        return read_announced_value(
            proc,
            ServingFabric.WORKER_ANNOUNCE_PREFIX,
            timeout=self.spawn_timeout,
            what="worker",
        )

    # ------------------------------------------------- autoscale seam
    def engine_factory(self, node) -> RemoteReplicaHandle:
        """``ReplicaProvisioner`` adapter: one cluster node -> one real
        worker process -> its proxy engine.  The provisioner does the
        ``join_replica`` itself, and the autoscaler owns the lifecycle,
        so these records are unmanaged (no supervisor respawn — a death
        flows through router failover and the autoscaler's
        replacement-node plan instead)."""
        record = self.spawn(name=node.name, join=False, managed=False)
        return record.proxy

    # ----------------------------------------------------- monitoring
    def poll(self, now: Optional[float] = None) -> int:
        """Reap exited processes and restore fleet capacity — but never
        in a hot loop.  A crash schedules a respawn after an
        exponential (jittered) backoff; crashes beyond the sliding-
        window budget send the worker to quarantine instead, and a
        served quarantine earns a fresh window.  The router's own
        failover already requeued the dead worker's requests — this
        loop only manages processes.  ``now`` is injectable so chaos
        tests drive the schedule deterministically."""
        now = time.monotonic() if now is None else now
        respawned = 0
        with self._lock:
            dead = [
                r for r in self.workers.values()
                if r.proc.poll() is not None
            ]
        for record in dead:
            with self._lock:
                self.workers.pop(record.name, None)
            record.proxy.close(goodbye=False)
            if self.recorder is not None:
                self.recorder.record(
                    "worker_exit", worker=record.name,
                    pid=record.proc.pid, rc=record.proc.returncode,
                    now=now)
            logger.warning(
                "serving worker %s (pid %d) exited rc=%s",
                record.name, record.proc.pid, record.proc.returncode)
            if not (
                self.respawn and record.managed
                and record.proc.returncode != 0
            ):
                # rc == 0 is a VOLUNTARY exit (GOODBYE after the router
                # retired the replica on drain/scale-down) — respawning
                # it would fight the scale decision; only crashes
                # (signals / nonzero rc) are restored
                continue
            record.crash_times = [
                t for t in record.crash_times
                if now - t <= self.respawn_window
            ] + [now]
            crashes = len(record.crash_times)
            if crashes > self.max_respawns:
                record.quarantine_until = now + self.quarantine_seconds
                self.quarantined[record.name] = record
                self.quarantined_total += 1
                self._count_quarantine()
                if self.recorder is not None:
                    self.recorder.record(
                        "worker_quarantined", worker=record.name,
                        crashes_in_window=crashes,
                        until=record.quarantine_until, now=now)
                logger.error(
                    "serving worker %s quarantined for %.0fs: %d "
                    "crashes inside %.0fs (respawn budget %d) — a hot "
                    "respawn loop helps nobody",
                    record.name, self.quarantine_seconds, crashes,
                    self.respawn_window, self.max_respawns)
                continue
            delay = min(
                self.backoff_max,
                self.backoff_base * (2 ** (crashes - 1)),
            ) * (1.0 + self.backoff_jitter * self._rng.random())
            record.respawn_at = now + delay
            record.respawn_schedule.append({
                "exit_at": now, "respawn_at": record.respawn_at,
                "backoff_s": delay,
            })
            self.pending[record.name] = record
            if self.recorder is not None:
                self.recorder.record(
                    "worker_respawn_scheduled", worker=record.name,
                    backoff_s=round(delay, 3),
                    crashes_in_window=crashes, now=now)
        # quarantine exits: the sentence served buys a clean window
        for name, record in list(self.quarantined.items()):
            if now >= record.quarantine_until:
                del self.quarantined[name]
                record.crash_times = []
                record.respawn_at = now
                self.pending[name] = record
                if self.recorder is not None:
                    self.recorder.record(
                        "worker_quarantine_exit", worker=name, now=now)
                logger.warning(
                    "serving worker %s leaves quarantine; respawning "
                    "with a fresh crash window", name)
        # due respawns
        for name, record in list(self.pending.items()):
            if now < record.respawn_at:
                continue
            del self.pending[name]
            base = base_replica_name(name)
            try:
                fresh = self.spawn(
                    name=f"{base}#r{record.respawns + 1}")
            except Exception as e:
                # a transient spawn failure (announce timeout under
                # load) must not abort the loop NOR permanently shrink
                # the fleet: other pending workers still get
                # processed, and this one retries after one base delay
                # (NOT counted as a crash — the worker never ran)
                logger.warning(
                    "respawn of %s failed (retrying in %.1fs): %s",
                    name, self.backoff_base, e)
                record.respawn_at = now + self.backoff_base
                self.pending[name] = record
                continue
            fresh.respawns = record.respawns + 1
            fresh.crash_times = record.crash_times
            fresh.respawn_times = record.respawn_times + [now]
            fresh.respawn_schedule = record.respawn_schedule
            respawned += 1
        return respawned

    def capacity_debt(self, now: Optional[float] = None) -> List[dict]:
        """Fleet capacity currently lost to quarantine — the feed
        :class:`~dlrover_tpu.serving.router.autoscale.ServingAutoScaler`
        polls every ``on_step`` to issue replacement-node plans the
        SAME poll a worker is quarantined (instead of serving traffic
        one worker short for the whole sentence).  One record per
        quarantined worker, keyed on the base name so respawn suffixes
        cannot mint duplicate debts; the record disappears when the
        worker leaves quarantine (a clean exit retires the debt by
        itself — no double-provisioning)."""
        with self._lock:
            return [
                {
                    "key": f"quarantine:{base_replica_name(name)}",
                    "kind": "quarantine",
                    "source": name,
                    "until": record.quarantine_until,
                }
                for name, record in self.quarantined.items()
            ]

    def _count_quarantine(self) -> None:
        """Count one quarantine into the router's metric surface
        (``serving_worker_quarantined_total``).  Incremented, not
        assigned: several supervisors can share one router (healthy
        fleet + chaos fleet in tests, per-host supervisors in a
        deployment) and each must add to the fleet-wide counter."""
        metrics = getattr(self.router, "metrics", None)
        if metrics is not None:
            metrics.worker_quarantined += 1

    def live_worker_bases(self) -> List[str]:
        """Base names of workers whose process is alive right now —
        the fleet coordinator's serving-side ground truth for lease
        reconstruction (a borrowed host with a live worker is
        mid-borrow even if the router has not seen its join yet)."""
        with self._lock:
            records = list(self.workers.values())
        return sorted({
            base_replica_name(r.name) for r in records
            if r.proc.poll() is None
        })

    # ------------------------------------------------------- metrics
    def render_worker_state(self) -> str:
        """Per-worker state as labeled Prometheus text — wire via
        ``MetricsExporter.add_text_source``.  One
        ``serving_worker_state{worker="…",state="…"} 1`` sample per
        supervised worker: ``running`` (process alive), ``backoff``
        (crashed, waiting out its exponential respawn delay) or
        ``quarantined`` (respawn budget blown, sitting out the
        sentence) — the dashboard answer to "the fleet gauge says 3
        but placement says 2: WHICH worker is sitting out, and why"."""
        from dlrover_tpu.utils.metric_registry import metric_help
        from dlrover_tpu.utils.profiler import escape_label_value

        with self._lock:
            states = [(r.name, "running") for r in self.workers.values()]
            states += [(name, "backoff") for name in self.pending]
            states += [(name, "quarantined") for name in self.quarantined]

        lines = []
        help_text = metric_help("serving_worker_state")
        if help_text:
            lines.append(f"# HELP serving_worker_state {help_text}")
        lines.append("# TYPE serving_worker_state gauge")
        for name, state in sorted(states):
            lines.append(
                "serving_worker_state{"
                f'worker="{escape_label_value(name)}",state="{state}"'
                "} 1")
        return "\n".join(lines) + "\n"

    # -------------------------------------------------------- chaos
    def kill(self, name: str, sig: int = signal.SIGKILL) -> int:
        """Chaos hook: signal a worker process (default SIGKILL — the
        mid-stream crash the fabric exists to survive).  Returns the
        pid signalled."""
        with self._lock:
            record = self.workers.get(name)
            supervised = sorted(self.workers)
        if record is None:
            raise ValueError(
                f"no supervised worker named {name!r}; supervised: "
                f"{supervised or '(none)'}")
        os.kill(record.proc.pid, sig)
        return record.proc.pid

    # ----------------------------------------------------- lifecycle
    def shutdown(self, grace: float = 2.0) -> None:
        """Graceful stop: GOODBYE every proxy (workers exit on their
        own), then escalate to SIGTERM/SIGKILL for stragglers."""
        with self._lock:
            records = list(self.workers.values())
            self.workers.clear()
        # pending/quarantined records hold no live process — dropping
        # them just cancels future respawns, which is what shutdown is
        self.pending.clear()
        self.quarantined.clear()
        for r in records:
            r.proxy.close(goodbye=True)
        deadline = time.monotonic() + grace
        for r in records:
            try:
                r.proc.wait(
                    timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                try:
                    r.proc.kill()
                    r.proc.wait(timeout=grace)
                except (OSError, subprocess.TimeoutExpired):
                    pass

    def __enter__(self) -> "WorkerSupervisor":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()
