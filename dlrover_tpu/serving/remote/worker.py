"""Serving worker process: ``python -m dlrover_tpu.serving.remote.worker``.

One worker = one replica process.  It binds port 0 ITSELF (the listener
reports the kernel-assigned port through the stdout announce line and
the HELLO frame — a pre-picked ``find_free_port`` would race another
process between bind-and-close and re-bind), hosts an engine speaking
the router's duck-typed engine protocol, and pushes TOKEN frames the
moment tokens exist instead of waiting for request completion.  The
engine is either the in-repo test :class:`FakeEngine` (deterministic,
numpy-only — what chaos tests SIGKILL) or a real
:class:`~dlrover_tpu.serving.engine.InferenceEngine` behind
:class:`~dlrover_tpu.serving.router.replica.InferenceEngineAdapter`
(imported lazily: the fake path must work on a jax-less image).

Startup contract (read by ``supervisor.py`` and the k8s/ray stubs):
the first matching stdout line is ``DLROVER_WORKER_ADDR=<host>:<port>``.
"""

from __future__ import annotations

import argparse
import itertools
import os
import signal
import socket
import sys
import threading
import time
from types import SimpleNamespace
from typing import Dict, List, Optional

import numpy as np

from dlrover_tpu.common.constants import ServingFabric
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.serving.prefixcache import head_key
from dlrover_tpu.serving.remote.protocol import FrameConnection, FrameKind
from dlrover_tpu.utils.tracing import parse_traceparent, trace_sampled


class FakeEngine:  # dlint: disable=DL011 stands in for the remote worker PROCESS: driven only by that process's single-threaded frame loop, router-side chains reach it through duck fan-out, never at runtime
    """Deterministic engine for fabric tests and jax-less images: each
    ``step()`` appends ``tokens_per_step`` tokens (value = rid % 997) to
    every active request.  Speaks the full router engine protocol plus
    the streaming extras (``inflight_outputs``, ``cancel``)."""

    def __init__(self, slots: int = 4, blocks: int = 10_000,
                 block_size: int = 4, tokens_per_step: int = 4,
                 max_len: int = 4096, step_delay: float = 0.0,
                 content_tokens: bool = False):
        self.max_slots = int(slots)
        self.block_size = int(block_size)
        self.total_blocks = int(blocks)
        self.used_blocks = 0
        self.tokens_per_step = int(tokens_per_step)
        self.max_len = int(max_len)
        # per-step sleep: lets chaos tests catch a worker MID-stream
        self.step_delay = float(step_delay)
        # content-derived tokens: token_i = (prompt hash + i) % 997
        # instead of rid % 997.  rid-keyed tokens differ across
        # replicas (each proxy numbers its own submits), so hedging's
        # byte-identical-stream gate needs tokens that are a function
        # of the REQUEST, like a greedy LLM's — opt-in so every
        # existing rid-based assertion stays untouched
        self.content_tokens = bool(content_tokens)
        self._next = 0
        self.active: Dict[int, dict] = {}
        self.generated_tokens = 0
        # prompt-head hit counts: the fake's stand-in for the real
        # engine's committed-prefix hot-head ranking, so fabric/router
        # tests exercise prefix-routing advertisements without jax
        self._head_hits: Dict[str, int] = {}
        # wall seconds of the most recent step() — decode-step
        # histogram attribution when this engine runs in-process
        self.last_step_seconds: Optional[float] = None

    def add_request(self, prompt, max_new_tokens: int) -> int:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        total = prompt.size + int(max_new_tokens)
        if total > self.max_len:
            raise ValueError(
                f"prompt {prompt.size} + max_new {max_new_tokens} "
                f"exceeds engine max_len {self.max_len}")
        head = head_key(prompt, self.block_size)
        if head is not None:
            self._head_hits[head] = self._head_hits.get(head, 0) + 1
        rid = self._next
        self._next += 1
        need = -(-total // self.block_size)
        self.used_blocks += need
        base = rid
        if self.content_tokens:
            base = (int(prompt.astype(np.int64).sum()) * 31
                    + int(prompt.size))
        self.active[rid] = {
            "remaining": int(max_new_tokens), "output": [],
            "blocks": need, "base": base,
        }
        return rid

    def step(self) -> List:
        t0 = time.perf_counter()
        if self.step_delay:
            time.sleep(self.step_delay)
        finished = []
        for rid in list(self.active):
            st = self.active[rid]
            take = min(self.tokens_per_step, st["remaining"])
            if self.content_tokens:
                pos = len(st["output"])
                st["output"].extend(
                    (st["base"] + pos + i) % 997 for i in range(take))
            else:
                st["output"].extend([st["base"] % 997] * take)
            st["remaining"] -= take
            self.generated_tokens += take
            if st["remaining"] <= 0:
                self.used_blocks -= st["blocks"]
                finished.append(
                    SimpleNamespace(rid=rid, output=st["output"]))
                del self.active[rid]
        self.last_step_seconds = time.perf_counter() - t0
        return finished

    @property
    def has_work(self) -> bool:
        return bool(self.active)

    def slots_free(self) -> int:
        return max(0, self.max_slots - len(self.active))

    def blocks_free(self) -> float:
        return float(self.total_blocks - self.used_blocks)

    def blocks_needed(self, prompt_len: int, max_new_tokens: int) -> float:
        return float(-(-(prompt_len + max_new_tokens) // self.block_size))

    def prefix_heads(self, n: int = 8) -> List[str]:
        """Hottest prompt-head digests seen by this fake (hex) — the
        advertisement the router's prefix-routing table is fed from,
        same surface as the real engine's committed-prefix ranking."""
        live = sorted(((hits, hx) for hx, hits in
                       self._head_hits.items()), reverse=True)
        return [hx for _, hx in live[:n]]

    # streaming extras -------------------------------------------------
    def inflight_outputs(self) -> Dict[int, List[int]]:
        """Live output snapshot per running request — the worker diffs
        these against what it already streamed as TOKEN frames."""
        return {rid: st["output"] for rid, st in self.active.items()}

    def cancel(self, rid: int) -> bool:
        """Free the request's slot + blocks.  Always True: local
        delivery cannot fail, and an already-finished rid is a
        successfully-delivered no-op (the router-side contract on
        ``ReplicaHandle`` — False would be miscounted as a CANCEL
        send failure when a cancel races completion)."""
        st = self.active.pop(rid, None)
        if st is not None:
            self.used_blocks -= st["blocks"]
        return True


class WorkerServer:
    """Frame server around one engine.  Accepts one router connection
    at a time (the router owns its replicas 1:1) and re-listens after a
    disconnect so a restarted router can re-adopt a warm worker."""

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 0,
                 stats_interval: float = ServingFabric.STATS_INTERVAL,
                 engine_kind: str = "fake", fault_schedule=None,
                 trace_sample_rate: float = 1.0, profiler=None,
                 profile_ship_interval: float = 2.0):
        self.engine = engine
        # contprof.ContinuousProfiler (role "worker"): its folded-stack
        # table rides STATS as an additive "profile" key, throttled to
        # profile_ship_interval so liveness-cadence STATS stay small
        self.profiler = profiler
        self.profile_ship_interval = float(profile_ship_interval)
        self._last_profile_ship = 0.0
        self.stats_interval = float(stats_interval)
        self.engine_kind = engine_kind
        # head-sampling agreement with the router: a received context
        # that asserts the sampled flag IS the router's keep verdict
        # (it omits the traceparent for sampled-out requests and keeps
        # propagating for incidents) and is always honored; this rate
        # only gates contexts that DON'T assert sampling, via the same
        # deterministic trace_sampled() predicate the router uses, so
        # both sides agree with no coordination frame
        self.trace_sample_rate = float(trace_sample_rate)
        # chaos seam (serving/remote/faults.py): a FaultSchedule here
        # perturbs every outgoing frame — torn streams, stalled STATS,
        # duplicated TOKENs — so degradation paths are TESTED, not
        # hoped for.  None (the default) costs nothing.
        self.fault_schedule = fault_schedule
        # bind-port-0-yourself: the ONLY race-free way to pick a port
        self._listener = socket.create_server(
            (host, int(port)), reuse_port=False)
        self._listener.settimeout(0.2)
        self.host = host
        self.port = self._listener.getsockname()[1]
        self.addr = f"{host}:{self.port}"
        self.stop_event = threading.Event()
        self._conn: Optional[FrameConnection] = None
        # SUBMIT rid (router-side) <-> engine rid maps
        self._erid_by_rid: Dict[int, int] = {}
        self._rid_by_erid: Dict[int, int] = {}
        self._streamed: Dict[int, int] = {}  # erid -> tokens streamed
        # erid -> hedge attempt ordinal from SUBMIT (absent for
        # unhedged submits): echoed on DONE so the router can audit
        # which dispatch attempt won a hedge race
        self._attempt_by_erid: Dict[int, int] = {}
        # erid -> trace bookkeeping for SUBMITs that carried a
        # traceparent header: worker-side spans (request lifetime,
        # decode steps, engine time) go back on the DONE frame in THIS
        # process's monotonic clock plus a sent_at anchor the proxy
        # uses to translate them into router time
        self._trace_by_erid: Dict[int, dict] = {}
        # last consistent STATS numbers; the heartbeat thread falls
        # back to these when a live read races an engine mutation.
        # Shared by the heartbeat thread and the serve loop: outside
        # __init__ it is ONLY read or swapped under _stats_seq_lock
        self._last_stats_payload: Dict[str, object] = dict(
            slots_free=0, blocks_free=0.0, inflight=0,
            generated_tokens=0,
        )
        # per-send STATS ordinal: generated_tokens alone cannot order
        # two snapshots taken without a decode step between them (e.g.
        # before/after a SUBMIT), so a recv-side reorder could resurrect
        # a consumed slot.  The lock pins seq order to WIRE order —
        # an atomic draw alone would let the heartbeat thread and the
        # serve loop interleave draw and send, handing the higher seq
        # to the older snapshot
        self._stats_seq = itertools.count(1)
        self._stats_seq_lock = threading.Lock()

    # ------------------------------------------------------- lifecycle
    def announce(self, stream=None) -> None:
        stream = stream or sys.stdout
        print(f"{ServingFabric.WORKER_ANNOUNCE_PREFIX}{self.addr}",
              file=stream, flush=True)

    def crash(self) -> None:
        """Test hook: die abruptly mid-stream (socket torn, no GOODBYE) —
        the in-process stand-in for SIGKILL."""
        self.stop_event.set()
        conn = self._conn
        if conn is not None:
            conn.close()
        self._listener.close()

    def serve_forever(self) -> None:
        try:
            while not self.stop_event.is_set():
                try:
                    sock, _ = self._listener.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                sock.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                from dlrover_tpu.serving.remote.faults import maybe_faulty

                self._conn = maybe_faulty(sock, self.fault_schedule)
                try:
                    self._serve_connection(self._conn)
                except (ConnectionError, TimeoutError, OSError) as e:
                    logger.warning("router connection dropped: %s", e)
                finally:
                    self._conn.close()
                    self._conn = None
        finally:
            self._listener.close()

    # ------------------------------------------------------ connection
    def _serve_connection(self, conn: FrameConnection) -> None:
        eng = self.engine
        self._erid_by_rid.clear()
        self._rid_by_erid.clear()
        self._streamed.clear()
        self._trace_by_erid.clear()
        self._attempt_by_erid.clear()
        conn.send(
            FrameKind.HELLO,
            addr=self.addr,
            slots_free=eng.slots_free(),
            blocks_free=self._finite_blocks(),
            block_size=getattr(eng, "block_size", 0),
            engine=self.engine_kind,
        )
        # liveness off-thread: a long engine.step() (first-call jit
        # compile on a real engine runs tens of seconds) must not
        # starve STATS, or the proxy's frame_timeout would read a
        # healthy-but-compiling worker as dead and fail it over —
        # FrameConnection sends are lock-serialized, so this is safe
        # alongside the pump's TOKEN/DONE sends
        hb_stop = threading.Event()

        def _heartbeat():
            while not hb_stop.wait(self.stats_interval):
                try:
                    self._send_stats(conn)
                except (ConnectionError, OSError):
                    return
                except Exception:
                    # capacity accessors race the serve thread's engine
                    # mutations (e.g. a deque mutating mid-iteration in
                    # blocks_free) — a torn READ must not kill the
                    # liveness beat; resend the last consistent numbers
                    try:
                        self._send_stats(conn, cached=True)
                    except (ConnectionError, OSError):
                        return

        hb = threading.Thread(target=_heartbeat, daemon=True,
                              name="worker-heartbeat")
        hb.start()
        try:
            while not self.stop_event.is_set():
                # drain every pending control frame before pumping:
                # SUBMIT latency must not queue behind a decode step
                busy = eng.has_work
                frame = self._recv_one(conn, 0.0 if busy else 0.02)
                while frame is not None:
                    if not self._handle(conn, frame):
                        return
                    frame = self._recv_one(conn, 0.0)
                if eng.has_work:
                    self._pump(conn)
        finally:
            hb_stop.set()
            hb.join(timeout=1.0)

    def _recv_one(self, conn: FrameConnection,
                  timeout: float) -> Optional[dict]:
        try:
            frame = conn.recv(timeout=timeout)
        except TimeoutError:
            return None
        if frame is None:
            raise ConnectionError("router closed the connection")
        return frame

    def _handle(self, conn: FrameConnection, frame: dict) -> bool:
        kind = frame.get("kind")
        if kind == FrameKind.SUBMIT:
            rid = int(frame["rid"])
            try:
                erid = self.engine.add_request(
                    np.asarray(frame["prompt"], np.int32),
                    int(frame["max_new_tokens"]),
                )
            except ValueError as e:
                # an impossible request is the ENGINE's verdict, not a
                # worker failure: report it, stay alive
                conn.send(FrameKind.ERROR, rid=rid, error=str(e))
                return True
            self._erid_by_rid[rid] = erid
            self._rid_by_erid[erid] = rid
            attempt = frame.get("attempt")
            if isinstance(attempt, int):
                self._attempt_by_erid[erid] = attempt
            tp = frame.get("trace")
            if isinstance(tp, str) and tp \
                    and self._trace_wanted(tp):
                self._trace_by_erid[erid] = {
                    "trace": tp, "t0": time.monotonic(),
                    "t_first": None, "steps": 0, "engine_s": 0.0,
                    # the header every TOKEN/DONE frame for this
                    # request echoes — built ONCE here: the verdict
                    # and the parse are per-request, so the per-frame
                    # hot path below pays a dict lookup, not a parse
                    # or a fresh dict per frame
                    "hdr": {"trace": tp},
                }
            conn.send(FrameKind.SUBMITTED, rid=rid)
        elif kind == FrameKind.CANCEL:
            rid = int(frame["rid"])
            erid = self._erid_by_rid.pop(rid, None)
            if erid is not None:
                self._rid_by_erid.pop(erid, None)
                self._streamed.pop(erid, None)
                self._trace_by_erid.pop(erid, None)
                self._attempt_by_erid.pop(erid, None)
                cancel = getattr(self.engine, "cancel", None)
                if cancel is not None:
                    cancel(erid)
                # freed capacity must be visible to the router's
                # placement ledger NOW, not a stats-interval later —
                # a cancel exists to reclaim the slot for live traffic
                self._send_stats(conn)
        elif kind == FrameKind.HEARTBEAT:
            self._send_stats(conn)
        elif kind == FrameKind.GOODBYE:
            logger.info("router said goodbye; worker %s exits", self.addr)
            self.stop_event.set()
            return False
        return True

    # ------------------------------------------------------------ pump
    def _pump(self, conn: FrameConnection) -> None:
        from dlrover_tpu.serving.router.replica import stream_deltas

        t0 = time.monotonic()
        finished = self.engine.step()
        step_s = time.monotonic() - t0
        # attribute the step to every traced request that was aboard
        # (whole-batch attribution: a batched decode step serves all of
        # them at once — per-request engine_seconds overlap by design)
        for erid, rec in self._trace_by_erid.items():
            if erid in self._rid_by_erid:
                rec["steps"] += 1
                rec["engine_s"] += step_s
                if rec["t_first"] is None:
                    rec["t_first"] = time.monotonic()
        # stream the deltas FIRST — TTFT is measured at the receiver.
        # prune=False: _streamed keeps the positions of just-finished
        # requests so the DONE path below flushes only their SUFFIX
        outputs = getattr(self.engine, "inflight_outputs", None)
        if outputs is not None:
            for erid, toks in stream_deltas(
                    outputs(), self._streamed, prune=False):
                rid = self._rid_by_erid.get(erid)
                if rid is not None:
                    conn.send(FrameKind.TOKEN, rid=rid,
                              tokens=[int(t) for t in toks],
                              **self._trace_header(erid))
        for ereq in finished:
            rid = self._rid_by_erid.pop(ereq.rid, None)
            sent = self._streamed.pop(ereq.rid, 0)
            trace_kw = self._trace_header(ereq.rid)
            rec = self._trace_by_erid.pop(ereq.rid, None)
            attempt = self._attempt_by_erid.pop(ereq.rid, None)
            if rid is None:
                continue  # cancelled while decoding
            self._erid_by_rid.pop(rid, None)
            out = [int(t) for t in ereq.output]
            if len(out) > sent:
                conn.send(FrameKind.TOKEN, rid=rid, tokens=out[sent:],
                          **trace_kw)
            # DONE carries the full output: authoritative completion —
            # plus this worker's spans and a sent_at clock anchor so
            # the router can graft them into the request's trace (and
            # the SUBMIT's hedge attempt ordinal echoed back, when one
            # rode in)
            attempt_kw = {} if attempt is None else {"attempt": attempt}
            conn.send(FrameKind.DONE, rid=rid, tokens=out, **trace_kw,
                      **self._trace_spans(rec), **attempt_kw)
        if finished:
            self._send_stats(conn)

    def _trace_wanted(self, traceparent: str) -> bool:
        """Worker-side verdict for a SUBMIT's trace context.  A context
        asserting the sampled flag (``…-01``) carries the ROUTER's keep
        decision — it only propagates traces it retains, and the
        incident override (a failover retry's worker spans must come
        back even at 1% sampling) rides that decision, so it is honored
        as-is, never re-derived and vetoed here.  Undecided contexts
        (flags ``00``, e.g. a foreign sender delegating the decision)
        fall back to the same deterministic predicate the router uses,
        keyed on the trace_id, so both sides agree without
        coordination.  Unparseable context samples in (degrade toward
        keeping data)."""
        if self.trace_sample_rate >= 1.0:
            return True
        parsed = parse_traceparent(traceparent)
        if parsed is None:
            return True
        if traceparent.rsplit("-", 1)[-1] == "01":
            return True
        return trace_sampled(parsed[0], self.trace_sample_rate)

    _NO_TRACE_HEADER: dict = {}

    def _trace_header(self, erid: int) -> dict:
        """Per-frame trace echo, cached per request at SUBMIT time —
        a sampled-out request (no record) pays one dict miss per
        frame and ships zero trace bytes; a traced one reuses the
        SAME header dict for its whole lifetime (callers ``**`` it
        into the frame payload, never mutate it)."""
        rec = self._trace_by_erid.get(erid)
        return self._NO_TRACE_HEADER if rec is None else rec["hdr"]

    def _trace_spans(self, rec: Optional[dict]) -> dict:
        if rec is None:
            return {}
        now = time.monotonic()
        return {
            "sent_at": now,
            "spans": [
                {"name": "worker.request", "start": rec["t0"],
                 "end": now, "attrs": {"engine": self.engine_kind}},
                {"name": "worker.decode", "parent": "worker.request",
                 "start": rec["t_first"] or rec["t0"], "end": now,
                 "attrs": {"steps": rec["steps"],
                           "engine_seconds": round(rec["engine_s"], 6)}},
            ],
        }

    def _finite_blocks(self) -> float:
        free = self.engine.blocks_free()
        # msgpack floats carry inf fine, but cap it so downstream
        # arithmetic (ledger subtraction) stays well-behaved
        return min(float(free), 1e18)

    def _send_stats(self, conn: FrameConnection,
                    cached: bool = False) -> None:
        payload = None
        if not cached:
            eng = self.engine
            # built into a LOCAL first: the heartbeat thread and the
            # serve loop both run this, and the shared cached copy is
            # only ever touched under _stats_seq_lock below
            payload = dict(
                slots_free=eng.slots_free(),
                blocks_free=self._finite_blocks(),
                inflight=len(self._rid_by_erid),
                generated_tokens=int(
                    getattr(eng, "generated_tokens", 0)),
            )
            # raw-speed engine introspection (spec accept ratio, int8
            # KV pool size, chunked-prefill seconds) rides STATS so the
            # router renders remote fleets like local ones; receivers
            # ignore unknown keys, so old proxies stay compatible
            em = getattr(eng, "engine_metrics", None)
            if em is not None:
                payload["engine_metrics"] = {
                    k: float(v) for k, v in em().items()
                }
            # hottest committed prefix heads (hex digests) ride STATS
            # as their own additive key — they are identities, not
            # numbers, so they cannot live in engine_metrics' float
            # namespace; receivers ignore unknown keys (DL004 holds)
            heads = getattr(eng, "prefix_heads", None)
            if heads is not None:
                payload["prefix_heads"] = [
                    str(h) for h in heads()
                ]
            # continuous-profiler tables ride STATS as their own
            # additive key, throttled well below the liveness cadence
            # (tables are cumulative, so a skipped ship loses nothing);
            # the trimmed top-N snapshot keeps the frame small.  The
            # throttle check is benign under the heartbeat/serve-loop
            # race: the worst interleaving ships one extra snapshot
            prof = self.profiler
            if prof is not None:
                now = time.monotonic()
                if now - self._last_profile_ship >= \
                        self.profile_ship_interval:
                    self._last_profile_ship = now
                    payload["profile"] = prof.snapshot(top=32)
        # seq is assigned at SEND time (never stored in the cached
        # payload): a cached liveness resend carries stale numbers
        # under a fresh ordinal, same last-send-wins semantics as
        # before, but now reorderable by the receiver.  Draw, payload
        # swap and send share the lock so seq order == wire order ==
        # snapshot order (the send itself is bounded by the
        # connection's send_timeout); before the swap moved in here, a
        # heartbeat and the serve loop could interleave draw and send
        # and hand the higher seq to the OLDER snapshot
        with self._stats_seq_lock:
            if payload is not None:
                self._last_stats_payload = payload
            # dlint: disable=DL007 serializing the send IS this lock's contract — seq order must equal wire order, and the send is bounded by the connection's send_timeout
            conn.send(FrameKind.STATS, seq=next(self._stats_seq),
                      **self._last_stats_payload)


def _build_llama_engine(args) -> object:
    """Real-engine path (lazy imports: jax must not gate ``--engine
    fake``).  Weights are randomly initialized — the checkpoint-loading
    rung is recorded in ROADMAP, not faked here."""
    import jax
    import jax.numpy as jnp

    from dlrover_tpu.models.llama import LlamaConfig, LlamaModel
    from dlrover_tpu.serving.engine import InferenceEngine
    from dlrover_tpu.serving.router.replica import InferenceEngineAdapter

    cfg = LlamaConfig.tiny(max_seq_len=args.max_len, dtype=jnp.float32)
    model = LlamaModel(cfg)
    variables = model.init(
        jax.random.PRNGKey(args.seed), jnp.zeros((1, 8), jnp.int32))
    return InferenceEngineAdapter(InferenceEngine(
        cfg, variables, max_slots=args.slots, chunk=4, paged=True,
        block_size=args.block_size, seed=args.seed,
        kv_dtype=args.kv_dtype if args.kv_dtype != "bf16" else None,
        prefill_chunk=args.prefill_chunk,
        speculative_k=args.speculative_k,
        attention_impl=args.attention_impl,
    ))


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="dlrover_tpu.serving.remote.worker",
        description="One serving replica process (frame protocol).",
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="0 (default): bind a kernel-assigned port and "
                        "announce it — never pre-pick a port")
    p.add_argument("--engine", choices=("fake", "llama"), default="fake")
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--tokens-per-step", type=int, default=4)
    p.add_argument("--block-size", type=int, default=4)
    p.add_argument("--blocks", type=int, default=10_000)
    p.add_argument("--max-len", type=int, default=4096)
    p.add_argument("--kv-dtype", choices=("bf16", "int8", "int4"),
                   default="bf16",
                   help="llama engine KV pool storage: int8 = "
                        "per-(token, head)-scale quantized pools "
                        "(~2x the block budget at the same HBM), "
                        "int4 = packed two-codes-per-byte pools "
                        "(~3.7x budget; coarser rounding, bounded "
                        "by the drift gates)")
    p.add_argument("--attention-impl",
                   choices=("auto", "xla", "pallas"), default="auto",
                   help="llama engine paged decode attention: "
                        "pallas = fused kernel reading (quantized) "
                        "pools in place, xla = fused gather, auto = "
                        "one-shot measured pick at engine build "
                        "(never selects the slower impl).  Forcing "
                        "pallas on a NON-TPU backend runs the kernel "
                        "in interpret mode — a parity/debug harness "
                        "whose multi-second steps can starve the "
                        "fabric's SUBMIT-ack liveness window; auto "
                        "refuses it off-TPU for exactly that reason")
    p.add_argument("--prefill-chunk", type=int, default=0,
                   help="llama engine: prefill long prompts this many "
                        "tokens per step, interleaved with decode "
                        "(bounds the batch's inter-token gap to one "
                        "chunk; 0 = whole-bucket prefill)")
    p.add_argument("--speculative-k", type=int, default=0,
                   help="llama engine: prompt-lookup speculative "
                        "decode, committing up to K tokens per "
                        "verify dispatch (0 disables)")
    p.add_argument("--step-delay", type=float, default=0.0)
    p.add_argument("--content-tokens", action="store_true",
                   help="fake engine: derive tokens from the prompt "
                        "content instead of the engine-local rid, so "
                        "two replicas produce identical streams for "
                        "the same request (the hedging byte-equality "
                        "gates need this)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--stats-interval", type=float,
                   default=ServingFabric.STATS_INTERVAL)
    p.add_argument("--trace-sample-rate", type=float, default=1.0,
                   help="head-sampling rate for trace contexts that "
                        "do NOT assert the sampled flag (a flagged "
                        "context carries the router's keep verdict, "
                        "incident overrides included, and is always "
                        "honored); the verdict is deterministic per "
                        "trace_id, so both sides agree without "
                        "coordination")
    p.add_argument("--profile", action="store_true",
                   help="run the always-on sampling profiler "
                        "(utils/contprof): folded-stack tables ride "
                        "STATS frames to the router, which forwards "
                        "them into the fleet /fleet/profile merge")
    p.add_argument("--profile-hz", type=float, default=19.0,
                   help="profiler sampling rate (seeded-jittered; the "
                        "default 19 Hz avoids phase-locking periodic "
                        "work)")
    p.add_argument("--crash-after", type=float, default=0.0,
                   help="chaos: hard-exit (rc 9) this many seconds "
                        "after startup — the crash-loop worker the "
                        "supervisor's quarantine exists for")
    args = p.parse_args(argv)

    if args.engine == "llama":
        engine = _build_llama_engine(args)
    else:
        engine = FakeEngine(
            slots=args.slots, blocks=args.blocks,
            block_size=args.block_size,
            tokens_per_step=args.tokens_per_step,
            max_len=args.max_len, step_delay=args.step_delay,
            content_tokens=args.content_tokens,
        )
    from dlrover_tpu.serving.remote.faults import FaultSchedule

    profiler = None
    if args.profile:
        from dlrover_tpu.utils.contprof import ContinuousProfiler

        profiler = ContinuousProfiler(
            role="worker", hz=args.profile_hz, seed=args.seed)
        profiler.start()
    server = WorkerServer(
        engine, host=args.host, port=args.port,
        stats_interval=args.stats_interval, engine_kind=args.engine,
        fault_schedule=FaultSchedule.from_env(),
        trace_sample_rate=args.trace_sample_rate,
        profiler=profiler,
    )
    if args.crash_after > 0:
        # a real abrupt death (no GOODBYE, no atexit, nonzero rc): the
        # supervisor must read it as a crash and meter its respawns
        crash = threading.Timer(
            args.crash_after, lambda: os._exit(9))
        crash.daemon = True
        crash.start()

    terminated = threading.Event()

    def _term(signum, _frame):  # pragma: no cover - signal path
        terminated.set()
        server.stop_event.set()

    signal.signal(signal.SIGTERM, _term)
    server.announce()
    logger.info("serving worker up at %s (engine=%s)",
                server.addr, args.engine)
    server.serve_forever()
    # rc 0 is reserved for a GOODBYE-initiated exit (the router
    # DECIDED to retire this worker; the supervisor must not respawn).
    # An external SIGTERM is not a scale decision — exit 143 so the
    # supervisor restores the fleet.
    return 143 if terminated.is_set() else 0


if __name__ == "__main__":  # pragma: no cover - subprocess entry
    sys.exit(main())
