"""Remote replica fabric: out-of-process serving workers.

The data-plane counterpart of the control plane's get/report RPC
envelope (common/rpc.py): serving replicas become real OS processes
that the router reaches over a streaming token protocol instead of
in-process engine objects.

- :mod:`protocol`   — length-prefixed msgpack frames over TCP
  (SUBMIT / CANCEL / TOKEN / DONE / STATS / HEARTBEAT / GOODBYE);
- :mod:`worker`     — ``python -m dlrover_tpu.serving.remote.worker``,
  a replica process hosting an engine and pushing TOKEN frames as
  they are emitted;
- :mod:`proxy`      — :class:`RemoteReplicaHandle`, the router-side
  engine proxy satisfying the duck-typed ``ReplicaHandle`` engine
  contract, so failover/heartbeat reaping work unchanged;
- :mod:`supervisor` — spawn/monitor/respawn local worker processes
  (exponential-backoff respawns, crash-loop quarantine) and plug them
  into the autoscale Scaler seam;
- :mod:`faults`     — seeded, schedule-driven frame-level fault
  injection (torn streams, stalled heartbeats, duplicated/dropped
  frames) pluggable into both proxy and worker — the chaos seam the
  degradation paths are proven through.
"""

from dlrover_tpu.serving.remote.protocol import (  # noqa: F401
    FrameConnection,
    FrameKind,
    FrameProtocolError,
    connect,
    parse_addr,
)
from dlrover_tpu.serving.remote.faults import (  # noqa: F401
    FaultSchedule,
    FaultyFrameConnection,
)
from dlrover_tpu.serving.remote.proxy import (  # noqa: F401
    RemoteReplicaHandle,
)
from dlrover_tpu.serving.remote.supervisor import (  # noqa: F401
    WorkerSupervisor,
    reap_orphans,
    serving_worker_command,
)

# NOTE: worker.py (FakeEngine, WorkerServer, main) is deliberately NOT
# re-exported here — ``python -m dlrover_tpu.serving.remote.worker``
# imports this package first, and a package-level import of the module
# being run trips runpy's double-import warning.  Import it directly:
# ``from dlrover_tpu.serving.remote import worker``.
