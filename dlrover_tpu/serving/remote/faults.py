"""Frame-level fault injection for the serving fabric.

The only way to trust a degradation path is to exercise it on purpose
(chaos engineering: the failure drill, not the postmortem).  SIGKILL
covers "the process died"; everything subtler — a frame that arrives
late, a connection torn mid-length-prefix, a duplicated TOKEN, a
heartbeat that stalls while the socket stays open, a DONE that never
comes — lives between the engine and the wire, and nothing could
inject it.  This module is that seam: a **seeded, schedule-driven**
wrapper over :class:`~dlrover_tpu.serving.remote.protocol.
FrameConnection` that perturbs frames at SEND time, pluggable into
both ends of the protocol:

- the worker (``WorkerServer(fault_schedule=...)`` or the
  ``DLROVER_SERVING_FAULTS`` env var on a spawned worker process)
  perturbs worker->router frames: TOKEN / DONE / STATS / HELLO;
- the proxy (``RemoteReplicaHandle(fault_schedule=...)``) perturbs
  router->worker frames: SUBMIT / CANCEL / GOODBYE.

A schedule is a list of fault specs (JSON-friendly dicts):

``op``
    ``delay`` (sleep ``seconds`` before the send), ``dup`` (send the
    frame twice), ``drop`` (swallow it), ``stall`` (swallow every
    matching frame for ``seconds`` after the trigger — the
    heartbeat-stall / silent-worker signature), ``tear`` (write half a
    length prefix to the raw socket and close it — the torn-stream
    signature a SIGKILL mid-send leaves).
``kind``
    frame kind to match (``"TOKEN"``, ``"STATS"``, ...) or ``"*"``.
``after``
    trigger on the Nth matching frame (1-based, default 1).
``count``
    for delay/dup/drop: how many consecutive matching frames the
    fault applies to (default 1).
``jitter``
    for delay: extra seconds, scaled by the schedule's seeded RNG —
    the same seed replays the same perturbation.

Every firing is appended to :attr:`FaultSchedule.injected` so a chaos
test can assert the schedule actually executed (a fault suite whose
faults silently never fire proves nothing).
"""

from __future__ import annotations

import json
import random
import threading
import time
from typing import Dict, List, Optional

from dlrover_tpu.common.constants import ServingFabric
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.serving.remote.protocol import FrameConnection

_OPS = ("delay", "dup", "drop", "stall", "tear")


class FaultSchedule:
    """Deterministic, thread-safe decision engine for frame faults.

    One schedule serves all connections of one endpoint; counters are
    cumulative across reconnects (a worker that is re-adopted after a
    torn connection keeps marching through the same schedule).
    """

    def __init__(self, specs: List[Dict], seed: int = 0):
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._lock = threading.Lock()
        self.specs: List[Dict] = []
        for raw in specs:
            spec = dict(raw)
            op = spec.get("op")
            if op not in _OPS:
                raise ValueError(
                    f"unknown fault op {op!r} (one of {_OPS})")
            spec.setdefault("kind", "*")
            spec.setdefault("after", 1)
            spec.setdefault("count", 1)
            spec.setdefault("seconds", 0.0)
            spec.setdefault("jitter", 0.0)
            spec["_seen"] = 0          # matching frames observed
            spec["_stall_until"] = None
            self.specs.append(spec)
        #: log of fired injections: {op, kind, t} per event
        self.injected: List[Dict] = []

    @classmethod
    def from_env(cls, environ=None) -> Optional["FaultSchedule"]:
        """Schedule from ``DLROVER_SERVING_FAULTS`` (JSON:
        ``{"seed": 0, "faults": [...]}``), or None when unset —
        the env seam spawned worker processes are armed through."""
        import os

        environ = os.environ if environ is None else environ
        raw = environ.get(ServingFabric.FAULTS_ENV)
        if not raw:
            return None
        payload = json.loads(raw)
        return cls(payload.get("faults", []),
                   seed=int(payload.get("seed", 0)))

    # ------------------------------------------------------- decisions
    def actions_for(self, kind: str) -> List[Dict]:
        """The fault actions to apply to one outgoing frame of
        ``kind`` (in schedule order).  Mutates trigger counters — call
        exactly once per send attempt."""
        now = time.monotonic()
        fired: List[Dict] = []
        with self._lock:
            for spec in self.specs:
                if spec["kind"] not in ("*", kind):
                    continue
                if spec["op"] == "stall":
                    until = spec["_stall_until"]
                    if until is not None:
                        if now < until:
                            fired.append(self._fire(spec, kind, now))
                        continue
                    spec["_seen"] += 1
                    if spec["_seen"] == spec["after"]:
                        spec["_stall_until"] = now + spec["seconds"]
                        fired.append(self._fire(spec, kind, now))
                    continue
                spec["_seen"] += 1
                first = spec["after"]
                if first <= spec["_seen"] < first + spec["count"]:
                    action = self._fire(spec, kind, now)
                    if spec["op"] == "delay" and spec["jitter"]:
                        action["seconds"] += (
                            spec["jitter"] * self._rng.random())
                    fired.append(action)
        return fired

    def _fire(self, spec: Dict, kind: str, now: float) -> Dict:
        action = {"op": spec["op"], "kind": kind, "t": now,
                  "seconds": float(spec["seconds"])}
        self.injected.append(dict(action))
        return action

    def fired(self, op: Optional[str] = None) -> List[Dict]:
        with self._lock:
            events = list(self.injected)
        return [e for e in events if op is None or e["op"] == op]


class FaultyFrameConnection(FrameConnection):
    """A :class:`FrameConnection` whose sends pass through a
    :class:`FaultSchedule`.  Receives are untouched — injecting at the
    sender exercises the RECEIVER's real parsing/staleness/failover
    paths, which is the point."""

    def __init__(self, sock, schedule: FaultSchedule,
                 send_timeout: Optional[float] = 10.0):
        super().__init__(sock, send_timeout=send_timeout)
        self.schedule = schedule

    def send(self, kind: str, **payload) -> None:
        dup = False
        for action in self.schedule.actions_for(kind):
            op = action["op"]
            if op == "delay":
                # outside the send lock: a delayed frame must not
                # serialize every other sender behind the sleep
                time.sleep(action["seconds"])
            elif op in ("drop", "stall"):
                logger.debug("fault injection: swallowed %s frame", kind)
                return
            elif op == "dup":
                dup = True
            elif op == "tear":
                self._tear()
                raise ConnectionError(
                    "fault injection: connection torn mid-frame")
        super().send(kind, **payload)
        if dup:
            logger.debug("fault injection: duplicated %s frame", kind)
            super().send(kind, **payload)

    def _tear(self) -> None:
        """Write HALF a length prefix, then slam the socket shut: the
        peer's reader sees trailing bytes at EOF — the exact torn-
        stream signature a crash mid-``sendall`` leaves on the wire."""
        try:
            with self._send_lock:
                if not self._closed:
                    # dlint: disable=DL003 two bytes into a kernel buffer cannot block; bounded by the connection's send_timeout regardless
                    self._sock.sendall(b"\x00\x00")
        except OSError:
            pass
        self.close()


def maybe_faulty(sock, schedule: Optional[FaultSchedule],
                 send_timeout: Optional[float] = 10.0) -> FrameConnection:
    """The ctor seam proxy and worker share: a plain connection when no
    schedule is armed, a fault-injecting one when it is."""
    if schedule is None:
        return FrameConnection(sock, send_timeout=send_timeout)
    return FaultyFrameConnection(sock, schedule,
                                 send_timeout=send_timeout)
