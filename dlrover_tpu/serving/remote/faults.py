"""Fault injection for the serving fabric and the control plane.

The only way to trust a degradation path is to exercise it on purpose
(chaos engineering: the failure drill, not the postmortem).  SIGKILL
covers "the process died"; everything subtler — a frame that arrives
late, a connection torn mid-length-prefix, a duplicated TOKEN, a
heartbeat that stalls while the socket stays open, a DONE that never
comes, an RPC that vanishes into a restarting master — lives between
the engine and the wire, and nothing could inject it.  This module is
that seam: a **seeded, schedule-driven** decision engine
(:class:`FaultSchedule`) with three interposers:

- :class:`FaultyFrameConnection` perturbs the frame protocol on BOTH
  sides of the wire.  ``side: "send"`` specs (the default) fire at
  send time — the worker (``WorkerServer(fault_schedule=...)`` or the
  ``DLROVER_SERVING_FAULTS`` env var on a spawned worker) perturbs
  worker->router frames, the proxy
  (``RemoteReplicaHandle(fault_schedule=...)``) router->worker ones.
  ``side: "recv"`` specs fire at RECEIVE time, on the real reader
  thread — the only way to exercise the receiver's reorder and
  staleness paths (a TOKEN landing after its DONE, an old STATS
  arriving after a newer one), which TCP ordering otherwise shields
  from send-side injection;
- :class:`FaultyRpcStub` perturbs the gRPC control plane (master and
  Brain RPCs): delay / drop / error / stall on ``get`` / ``report``,
  so the retry policy (common/retry.py) and every caller's outage
  tolerance are TESTED, not hoped for.

A schedule is a list of fault specs (JSON-friendly dicts):

``op``
    ``delay`` (sleep ``seconds`` before delivery), ``dup`` (deliver
    the frame twice), ``drop`` (swallow it; for an RPC: raise a
    TRANSIENT ``ConnectionError`` — the call never reached the
    server), ``stall`` (swallow every matching frame / fail every
    matching RPC for ``seconds`` after the trigger — the
    heartbeat-stall / wedged-master signature), ``tear`` (write half a
    length prefix to the raw socket and close it — the torn-stream
    signature a SIGKILL mid-send leaves; for an RPC: a transient
    ``ConnectionError``), ``error`` (raise a NON-transient
    ``RuntimeError`` — the served-refusal class a retry policy must
    NOT retry), ``reorder`` (recv-side: hold the matching frame back
    and deliver it after the next frame — the out-of-order arrival
    the receiver's staleness guards exist for).
``kind``
    frame kind (``"TOKEN"``, ``"STATS"``, ...) or RPC method
    (``"get"``, ``"report"``) to match, or ``"*"``.
``side``
    ``"send"`` (default) or ``"recv"`` — which interposer hook the
    spec arms.  RPC stubs consult the send side.
``after``
    trigger on the Nth matching frame (1-based, default 1).
``count``
    for delay/dup/drop/error/reorder: how many consecutive matching
    frames the fault applies to (default 1).
``jitter``
    for delay: extra seconds, scaled by the schedule's seeded RNG —
    the same seed replays the same perturbation.

Every firing is appended to :attr:`FaultSchedule.injected` so a chaos
test can assert the schedule actually executed (a fault suite whose
faults silently never fire proves nothing).

Beyond the discrete per-frame ops, a schedule can also carry sustained
**link profiles** — the gray-failure plane.  Where an op fires on the
Nth matching frame and stops, a profile degrades EVERY matching frame
for as long as it is armed: ``slow`` (base ``latency`` plus seeded
uniform ``jitter`` per frame), ``lossy`` (seeded per-frame drop with
probability ``p``), ``partition`` (blackhole every frame on the
matching side — armed per-direction, this is the asymmetric partition:
one direction delivers, the other doesn't), and ``flap`` (periodic
up/down: frames deliver during the first ``duty`` fraction of each
``period`` and drop during the rest, phase-anchored at arm time).
Profiles are armed at construction (``profiles=[...]``), through the
same ``DLROVER_SERVING_FAULTS`` env payload (``"profiles": [...]``)
spawned workers inherit, or mid-run via :meth:`FaultSchedule.
arm_profile` / :meth:`~FaultSchedule.disarm_profile` — a link that
degrades while traffic is in flight, then heals.  Profile firings land
in the same ``injected`` ledger tagged with ``profile``/``profile_id``
so assertions can distinguish them from the discrete ops.
"""

from __future__ import annotations

import json
import random
import threading
import time
from typing import Dict, List, Optional

from dlrover_tpu.common.constants import ServingFabric
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.serving.remote.protocol import FrameConnection

_OPS = ("delay", "dup", "drop", "stall", "tear", "error", "reorder")
_SIDES = ("send", "recv")
_PROFILES = ("slow", "lossy", "partition", "flap")
_PROFILE_SIDES = ("send", "recv", "both")


class FaultSchedule:
    """Deterministic, thread-safe decision engine for injected faults.

    One schedule serves all connections of one endpoint; counters are
    cumulative across reconnects (a worker that is re-adopted after a
    torn connection keeps marching through the same schedule).
    """

    def __init__(self, specs: List[Dict], seed: int = 0,
                 profiles: Optional[List[Dict]] = None):
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._lock = threading.Lock()
        self.specs: List[Dict] = []
        for raw in specs:
            spec = dict(raw)
            op = spec.get("op")
            if op not in _OPS:
                raise ValueError(
                    f"unknown fault op {op!r} (one of {_OPS})")
            side = spec.setdefault("side", "send")
            if side not in _SIDES:
                raise ValueError(
                    f"unknown fault side {side!r} (one of {_SIDES})")
            spec.setdefault("kind", "*")
            spec.setdefault("after", 1)
            spec.setdefault("count", 1)
            spec.setdefault("seconds", 0.0)
            spec.setdefault("jitter", 0.0)
            spec["_seen"] = 0          # matching frames observed
            spec["_stall_until"] = None
            self.specs.append(spec)
        #: log of fired injections: {op, kind, side, t} per event
        self.injected: List[Dict] = []
        #: armed link profiles, keyed by arm id (insertion-ordered so
        #: evaluation order is deterministic)
        self.profiles: Dict[int, Dict] = {}
        self._next_profile_id = 1
        for prof in (profiles or []):
            self.arm_profile(prof)

    # -------------------------------------------------- link profiles
    def arm_profile(self, spec: Dict) -> int:
        """Arm one sustained link profile, mid-run safe; returns the
        arm id :meth:`disarm_profile` takes.  The flap phase anchors at
        arm time, so two schedules armed at different moments flap on
        their own clocks (as two real links would)."""
        prof = dict(spec)
        name = prof.get("profile")
        if name not in _PROFILES:
            raise ValueError(
                f"unknown link profile {name!r} (one of {_PROFILES})")
        side = prof.setdefault("side", "both")
        if side not in _PROFILE_SIDES:
            raise ValueError(
                f"unknown profile side {side!r} "
                f"(one of {_PROFILE_SIDES})")
        prof.setdefault("kind", "*")
        if name == "slow":
            prof.setdefault("latency", 0.05)
            prof.setdefault("jitter", 0.0)
        elif name == "lossy":
            p = float(prof.setdefault("p", 0.1))
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"lossy profile p={p} not in [0, 1]")
        elif name == "flap":
            period = float(prof.setdefault("period", 1.0))
            duty = float(prof.setdefault("duty", 0.5))
            if period <= 0.0:
                raise ValueError("flap profile period must be > 0")
            if not 0.0 <= duty <= 1.0:
                raise ValueError(
                    f"flap profile duty={duty} not in [0, 1]")
        with self._lock:
            pid = self._next_profile_id
            self._next_profile_id += 1
            prof["_armed_at"] = time.monotonic()
            self.profiles[pid] = prof
        return pid

    def disarm_profile(self, pid: int) -> None:
        """Heal one armed link profile (no-op if already disarmed)."""
        with self._lock:
            self.profiles.pop(pid, None)

    def _profile_actions(self, kind: str, side: str,
                         now: float) -> List[Dict]:
        """Profile contributions to one frame's actions — caller holds
        ``_lock``.  Emits the same action dicts the discrete ops do
        (``delay``/``drop``), tagged with the profile name and arm id
        in the ledger."""
        fired: List[Dict] = []
        for pid, prof in self.profiles.items():
            if prof["side"] not in ("both", side):
                continue
            if prof["kind"] not in ("*", kind):
                continue
            name = prof["profile"]
            if name == "slow":
                seconds = float(prof["latency"])
                if prof["jitter"]:
                    seconds += float(prof["jitter"]) * self._rng.random()
                fired.append(self._fire_profile(
                    pid, prof, "delay", kind, side, now, seconds))
            elif name == "lossy":
                if self._rng.random() < float(prof["p"]):
                    fired.append(self._fire_profile(
                        pid, prof, "drop", kind, side, now))
            elif name == "partition":
                fired.append(self._fire_profile(
                    pid, prof, "drop", kind, side, now))
            elif name == "flap":
                period = float(prof["period"])
                phase = (now - prof["_armed_at"]) % period
                if phase >= period * float(prof["duty"]):
                    fired.append(self._fire_profile(
                        pid, prof, "drop", kind, side, now))
        return fired

    def _fire_profile(self, pid: int, prof: Dict, op: str, kind: str,
                      side: str, now: float,
                      seconds: float = 0.0) -> Dict:
        action = {"op": op, "kind": kind, "t": now, "side": side,
                  "seconds": float(seconds),
                  "profile": prof["profile"], "profile_id": pid}
        self.injected.append(dict(action))
        return action

    @classmethod
    def from_env(cls, environ=None) -> Optional["FaultSchedule"]:
        """Schedule from ``DLROVER_SERVING_FAULTS`` (JSON:
        ``{"seed": 0, "faults": [...]}``), or None when unset —
        the env seam spawned worker processes are armed through."""
        import os

        environ = os.environ if environ is None else environ
        raw = environ.get(ServingFabric.FAULTS_ENV)
        if not raw:
            return None
        payload = json.loads(raw)
        return cls(payload.get("faults", []),
                   seed=int(payload.get("seed", 0)),
                   profiles=payload.get("profiles", []))

    # ------------------------------------------------------- decisions
    def actions_for(self, kind: str, side: str = "send") -> List[Dict]:
        """The fault actions to apply to one frame of ``kind`` passing
        the ``side`` hook (in schedule order).  Mutates trigger
        counters — call exactly once per delivery attempt."""
        now = time.monotonic()
        fired: List[Dict] = []
        with self._lock:
            for spec in self.specs:
                if spec["side"] != side:
                    continue
                if spec["kind"] not in ("*", kind):
                    continue
                if spec["op"] == "stall":
                    until = spec["_stall_until"]
                    if until is not None:
                        if now < until:
                            fired.append(self._fire(spec, kind, now))
                        continue
                    spec["_seen"] += 1
                    if spec["_seen"] == spec["after"]:
                        spec["_stall_until"] = now + spec["seconds"]
                        fired.append(self._fire(spec, kind, now))
                    continue
                spec["_seen"] += 1
                first = spec["after"]
                if first <= spec["_seen"] < first + spec["count"]:
                    action = self._fire(spec, kind, now)
                    if spec["op"] == "delay" and spec["jitter"]:
                        action["seconds"] += (
                            spec["jitter"] * self._rng.random())
                    fired.append(action)
            # sustained link profiles degrade every matching frame for
            # as long as they stay armed, composing after the discrete
            # ops (a dup'd frame on a slow link is delayed twice, as
            # two wire traversals would be)
            fired.extend(self._profile_actions(kind, side, now))
        return fired

    def _fire(self, spec: Dict, kind: str, now: float) -> Dict:
        action = {"op": spec["op"], "kind": kind, "t": now,
                  "side": spec["side"],
                  "seconds": float(spec["seconds"])}
        self.injected.append(dict(action))
        return action

    def fired(self, op: Optional[str] = None) -> List[Dict]:
        with self._lock:
            events = list(self.injected)
        return [e for e in events if op is None or e["op"] == op]

    def profile_fired(self, name: Optional[str] = None) -> List[Dict]:
        """Ledger entries contributed by link profiles (optionally one
        profile kind) — the "did the gray failure actually degrade
        traffic" assertion chaos tests make."""
        with self._lock:
            events = list(self.injected)
        return [e for e in events
                if "profile" in e
                and (name is None or e["profile"] == name)]


class FaultyFrameConnection(FrameConnection):
    """A :class:`FrameConnection` whose sends AND receives pass
    through a :class:`FaultSchedule`.  Send-side injection exercises
    the RECEIVER's real parsing/staleness/failover paths; recv-side
    injection (``side: "recv"`` specs) perturbs frames between the
    wire and the reader — the only place a reorder can be produced,
    since TCP delivers send-side frames in order."""

    def __init__(self, sock, schedule: FaultSchedule,
                 send_timeout: Optional[float] = 10.0):
        super().__init__(sock, send_timeout=send_timeout)
        self.schedule = schedule
        # recv-side perturbation state (single reader by protocol
        # contract, so no lock): frames queued for delivery ahead of
        # the wire, and reordered frames held back until the NEXT
        # frame passes them
        self._recv_ready: List[dict] = []
        self._recv_held: List[dict] = []

    # ------------------------------------------------------------ send
    def send(self, kind: str, **payload) -> None:
        dup = False
        for action in self.schedule.actions_for(kind, side="send"):
            op = action["op"]
            if op == "delay":
                # outside the send lock: a delayed frame must not
                # serialize every other sender behind the sleep
                time.sleep(action["seconds"])
            elif op in ("drop", "stall"):
                logger.debug("fault injection: swallowed %s frame", kind)
                return
            elif op == "dup":
                dup = True
            elif op == "error":
                raise ConnectionError(
                    "fault injection: errored %s frame" % kind)
            elif op == "tear":
                self._tear()
                raise ConnectionError(
                    "fault injection: connection torn mid-frame")
            # "reorder" is meaningless at send time (TCP re-serializes
            # it); declare such specs side="recv"
        super().send(kind, **payload)
        if dup:
            logger.debug("fault injection: duplicated %s frame", kind)
            super().send(kind, **payload)

    # ------------------------------------------------------------ recv
    def recv(self, timeout: Optional[float] = None) -> Optional[dict]:
        """One frame through the recv-side schedule.  ``reorder`` holds
        the matching frame until the next one passes it; ``dup``
        queues a second delivery; ``drop``/``stall`` swallow and read
        on; ``error``/``tear`` raise into the reader's torn-stream
        path.  Held frames flush (in held order) after the frame that
        overtook them, and at EOF — a reorder must delay a frame, not
        destroy it."""
        if self._recv_ready:
            return self._recv_ready.pop(0)
        while True:
            frame = super().recv(timeout=timeout)
            if frame is None:
                if self._recv_held:
                    return self._recv_held.pop(0)
                return None
            drop = dup = reorder = False
            for action in self.schedule.actions_for(
                    str(frame.get("kind")), side="recv"):
                op = action["op"]
                if op == "delay":
                    time.sleep(action["seconds"])
                elif op in ("drop", "stall"):
                    drop = True
                elif op == "dup":
                    dup = True
                elif op == "reorder":
                    reorder = True
                elif op == "error":
                    raise ConnectionError(
                        "fault injection: errored %s frame at recv"
                        % frame.get("kind"))
                elif op == "tear":
                    self.close()
                    raise ConnectionError(
                        "fault injection: connection torn at recv")
            if drop:
                logger.debug(
                    "fault injection: swallowed %s frame at recv",
                    frame.get("kind"))
                continue
            if reorder:
                logger.debug(
                    "fault injection: holding %s frame back (reorder)",
                    frame.get("kind"))
                self._recv_held.append(frame)
                continue
            if dup:
                logger.debug(
                    "fault injection: duplicated %s frame at recv",
                    frame.get("kind"))
                self._recv_ready.append(dict(frame))
            # the frame that overtakes releases everything held behind
            self._recv_ready.extend(self._recv_held)
            self._recv_held.clear()
            return frame

    def _tear(self) -> None:
        """Write HALF a length prefix, then slam the socket shut: the
        peer's reader sees trailing bytes at EOF — the exact torn-
        stream signature a crash mid-``sendall`` leaves on the wire."""
        try:
            with self._send_lock:
                if not self._closed:
                    # dlint: disable=DL003 two bytes into a kernel buffer cannot block; bounded by the connection's send_timeout regardless
                    self._sock.sendall(b"\x00\x00")
        except OSError:
            pass
        self.close()


class FaultyRpcStub:
    """Control-plane interposer: an :class:`~dlrover_tpu.common.rpc.
    RpcStub` (or the Brain's) whose ``get``/``report`` calls pass
    through a :class:`FaultSchedule`, keyed on the method name.

    Fault mapping, chosen so the retry policy's TRANSIENT/non-transient
    split is exercised from both sides: ``delay`` sleeps before the
    call; ``drop``/``tear`` raise ``ConnectionError`` (transient — the
    call never reached the server, a retry is correct); ``stall``
    raises ``TimeoutError`` for ``seconds`` after the trigger (the
    wedged-master window); ``error`` raises ``RuntimeError``
    (NON-transient — the served-refusal class a retry policy must
    surface immediately).  Firings land in the shared
    ``schedule.injected`` ledger, same contract as the frame side.

    Every perturbation also stamps :attr:`last_fault` with the fired
    action, and every raised exception carries the action as its
    ``injected_fault`` attribute — a delay/stall is otherwise
    indistinguishable from a genuinely slow RPC at the call site, so
    without the tag a chaos assertion cannot tell "the caller survived
    the fault" from "the fault never fired"."""

    def __init__(self, stub, schedule: FaultSchedule):
        self._stub = stub
        self.schedule = schedule
        #: the most recent fired action this stub applied (None until
        #: the first firing) — the injected-fault tag chaos tests read
        self.last_fault: Optional[Dict] = None

    def _call(self, method: str, fn, payload: bytes, timeout: float):
        for action in self.schedule.actions_for(method, side="send"):
            op = action["op"]
            self.last_fault = dict(action)
            if op == "delay":
                time.sleep(action["seconds"])
            elif op in ("drop", "tear"):
                raise self._tagged(action, ConnectionError(
                    f"fault injection: dropped {method} rpc"))
            elif op == "stall":
                raise self._tagged(action, TimeoutError(
                    f"fault injection: {method} rpc stalled"))
            elif op == "error":
                raise self._tagged(action, RuntimeError(
                    f"fault injection: {method} rpc served an error"))
            # dup/reorder have no RPC meaning (unary round trips)
        return fn(payload, timeout=timeout)

    @staticmethod
    def _tagged(action: Dict, exc: Exception) -> Exception:
        exc.injected_fault = dict(action)
        return exc

    def get(self, payload: bytes, timeout: float = 0) -> bytes:
        return self._call("get", self._stub.get, payload, timeout)

    def report(self, payload: bytes, timeout: float = 0) -> bytes:
        return self._call("report", self._stub.report, payload, timeout)

    @property
    def closed(self) -> bool:
        return self._stub.closed

    def close(self) -> None:
        self._stub.close()


def maybe_faulty(sock, schedule: Optional[FaultSchedule],
                 send_timeout: Optional[float] = 10.0) -> FrameConnection:
    """The ctor seam proxy and worker share: a plain connection when no
    schedule is armed, a fault-injecting one when it is."""
    if schedule is None:
        return FrameConnection(sock, send_timeout=send_timeout)
    return FaultyFrameConnection(sock, schedule,
                                 send_timeout=send_timeout)
