"""Phi-accrual failure detection for the remote replica fabric.

The frame-timeout cliff (``now - last_frame > frame_timeout`` →
``ConnectionError``) answers one question with one bit: alive or dead.
Gray failures — a congested link, a degraded NIC, a GC-pausing worker
— need a *gradient*: how suspicious is this silence, given how this
replica has actually been talking?  The phi-accrual detector
(Hayashibara et al., "The φ Accrual Failure Detector", SRDS 2004)
answers with a continuous suspicion level::

    phi(t) = -log10( P(silence >= t) )

under a Normal fit of the replica's recent frame-interarrival history.
phi = 1 means a 10% chance the replica is still alive and merely slow;
phi = 3 means 0.1%.  Callers pick thresholds, not timeouts: a *suspect*
threshold (demote in placement, keep serving in-flight work) and a
*dead* threshold (failover), and because phi is computed from the
replica's OWN arrival statistics, a replica that has always been
chatty is suspected after a much shorter silence than one that has
always been bursty — the adaptivity a fixed timeout cannot have.

Determinism: the detector is pure arithmetic over the observations it
is fed — same intervals, same silence, same phi — which is what the
seeded chaos tests assert.  The window is bounded (``deque(maxlen)``),
and below ``min_samples`` observations phi is 0.0: an opening silence
on a replica with no history is not evidence of anything yet (the hard
``frame_timeout`` ceiling still covers a worker that never speaks).
"""

from __future__ import annotations

import math
import threading
from collections import deque
from typing import Optional

#: Probabilities below this floor clamp — keeps phi finite (~30) so
#: threshold comparisons stay well-ordered instead of hitting -log(0).
_MIN_P = 1e-30


class PhiAccrualDetector:
    """Suspicion level of one peer from its frame-interarrival history.

    ``observe(interval)`` feeds one gap between consecutive frames;
    ``phi(silence)`` converts the current silence into suspicion.
    ``min_std`` floors the fitted deviation so a metronomically regular
    peer (std → 0) does not make any micro-jitter look like death.
    """

    def __init__(self, window: int = 128, min_samples: int = 8,
                 min_std: float = 0.02):
        if window < 2:
            raise ValueError("phi window must hold >= 2 samples")
        if min_samples < 2:
            raise ValueError("phi min_samples must be >= 2")
        self.min_samples = int(min_samples)
        self.min_std = float(min_std)
        self._intervals: deque = deque(maxlen=int(window))
        # running sums maintained alongside the deque so mean/std are
        # O(1) per step() poll, not O(window); the detector carries
        # its own lock so writers (the proxy's reader thread) and
        # readers (step()/metrics pollers on other threads) always
        # share one lock regardless of what the caller holds
        self._lock = threading.Lock()
        self._sum = 0.0
        self._sum_sq = 0.0

    def observe(self, interval: float) -> None:
        """One frame-interarrival gap, in seconds (non-positive gaps —
        two frames drained from one recv batch — carry no timing
        signal and are ignored)."""
        if interval <= 0.0:
            return
        with self._lock:
            if len(self._intervals) == self._intervals.maxlen:
                old = self._intervals[0]
                self._sum -= old
                self._sum_sq -= old * old
            self._intervals.append(interval)
            self._sum += interval
            self._sum_sq += interval * interval

    @property
    def samples(self) -> int:
        with self._lock:
            return len(self._intervals)

    def mean(self) -> float:
        with self._lock:
            return self._mean_locked()

    def _mean_locked(self) -> float:
        n = len(self._intervals)
        return self._sum / n if n else 0.0

    def std(self) -> float:
        with self._lock:
            return self._std_locked()

    def _std_locked(self) -> float:
        n = len(self._intervals)
        if n < 2:
            return self.min_std
        var = max(self._sum_sq / n - (self._sum / n) ** 2, 0.0)
        return max(math.sqrt(var), self.min_std)

    def phi(self, silence: float) -> float:
        """Suspicion after ``silence`` seconds without a frame.
        Monotone non-decreasing in ``silence``; 0.0 until
        ``min_samples`` intervals have been observed."""
        if silence <= 0.0:
            return 0.0
        with self._lock:
            if len(self._intervals) < self.min_samples:
                return 0.0
            mean = self._mean_locked()
            std = self._std_locked()
        # P(X >= silence) for X ~ N(mean, std): the Gaussian survival
        # function via erfc — numerically stable far into the tail,
        # where 1 - cdf() would round to 0
        p = 0.5 * math.erfc((silence - mean) / (std * math.sqrt(2.0)))
        return -math.log10(max(p, _MIN_P))

    def silence_for_phi(self, target_phi: float) -> Optional[float]:
        """The silence duration at which suspicion reaches
        ``target_phi`` (None below ``min_samples``) — lets operators
        sanity-check a threshold as seconds, the unit they think in."""
        with self._lock:
            if len(self._intervals) < self.min_samples:
                return None
            mean = self._mean_locked()
            std = self._std_locked()
        p = 10.0 ** (-float(target_phi))
        p = min(max(p, _MIN_P), 1.0)
        # invert the survival function: silence = mean + std * z(p)
        z = math.sqrt(2.0) * _erfc_inv(2.0 * p)
        return mean + std * z


def _erfc_inv(y: float) -> float:
    """Inverse complementary error function via bisection — math has
    no erfcinv, and this off-hot-path helper only serves the
    threshold-to-seconds view, so 60 halvings of a bracketed interval
    beat carrying a rational-approximation table."""
    y = min(max(y, 2.0 * _MIN_P), 2.0 - 2.0 * _MIN_P)
    lo, hi = -10.0, 10.0
    for _ in range(60):
        mid = 0.5 * (lo + hi)
        if math.erfc(mid) > y:
            lo = mid
        else:
            hi = mid
    return 0.5 * (lo + hi)
