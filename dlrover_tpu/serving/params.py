"""Serving-layout parameters: the training tree flattened for decode.

The serving engine (`dlrover_tpu.serving.engine`) runs a dedicated
functional forward (`dlrover_tpu.serving.model`) instead of the flax
training module — the same split the reference makes between its
training model and the vLLM inference backend it hands RL rollouts to
(reference: atorch/atorch/rl/inference_backend/vllm_backend.py:11-24,
which wraps weights into a purpose-built inference engine rather than
reusing the trainer's module).

Why a separate layout:

- every projection becomes a plain 2D ``[K, N]`` matrix so the int8
  serving path can PRE-quantize it once into the exact layout the
  Pallas kernel reads (``ops/pallas/quant_matmul.prequantize_weight``)
  — fixing the measured 0.6x w8a8 shortfall whose cause was per-call
  dynamic weight quantization;
- layers are stacked along a leading axis so prefill/decode scan over
  them with one compiled body (same trick as training ``nn.scan``);
- the tree is a plain dict of arrays — no flax module state, trivially
  shardable/donatable.

Weight entries are either an fp array ``[K, N]`` or a
``{"q": int8 [K, N], "scale": f32 [1, N]}`` pair.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from dlrover_tpu.models.llama import LlamaConfig
from dlrover_tpu.ops.pallas.quant_matmul import prequantize_weight

# weights quantized when int8=True; norms/embedding always stay fp.
# wqkv / wgu are load-time fusions: one [E, H*D+2*KV*D] matmul instead
# of three and one [E, 2F] instead of two — fewer, larger kernels (the
# standard serving fusion; decode is launch/bandwidth-bound)
_LAYER_MATS = ("wqkv", "wo", "wgu", "down",
               "wq", "wk", "wv", "wgate", "wup")


def _maybe_quant(w: jax.Array, int8: bool):
    if not int8:
        return w
    q, scale = prequantize_weight(jnp.asarray(w, jnp.float32))
    return {"q": q, "scale": scale}


def _layer_tree(
    p: Dict[str, Any], cfg: LlamaConfig, fuse: bool = True
) -> Dict[str, Any]:
    """One flax DecoderLayer param subtree -> serving 2D matrices.

    Handles both the per-layer form ([E, H, D] kernels) and the
    ``nn.scan`` stacked form ([L, E, H, D]): only trailing dims
    collapse, any leading layer axis passes through.
    """
    attn = p["attn"]

    def merge_last2(w):   # [..., E, H, D] -> [..., E, H*D]
        return w.reshape(*w.shape[:-2], w.shape[-2] * w.shape[-1])

    def merge_head_in(w):  # [..., H, D, E] -> [..., H*D, E]
        return w.reshape(*w.shape[:-3], w.shape[-3] * w.shape[-2],
                         w.shape[-1])

    wq = merge_last2(attn["q_proj"]["kernel"])
    wk = merge_last2(attn["k_proj"]["kernel"])
    wv = merge_last2(attn["v_proj"]["kernel"])

    def flat(b):  # [..., H, D] -> [..., H*D]
        return jnp.asarray(b).reshape(
            *b.shape[:-2], b.shape[-2] * b.shape[-1]
        )

    out = {
        "input_norm": p["input_norm"]["scale"],
        "post_norm": p["post_norm"]["scale"],
        "wo": merge_head_in(attn["o_proj"]["kernel"]),
        "down": p["mlp"]["down_proj"]["kernel"],
    }
    if fuse:
        out["wqkv"] = jnp.concatenate(
            [jnp.asarray(wq), jnp.asarray(wk), jnp.asarray(wv)],
            axis=-1)
        out["wgu"] = jnp.concatenate(
            [jnp.asarray(p["mlp"]["gate_proj"]["kernel"]),
             jnp.asarray(p["mlp"]["up_proj"]["kernel"])], axis=-1)
        if "bias" in attn["q_proj"]:
            # Qwen2-family qkv biases, fused to match the wqkv layout
            out["bqkv"] = jnp.concatenate(
                [flat(attn["q_proj"]["bias"]),
                 flat(attn["k_proj"]["bias"]),
                 flat(attn["v_proj"]["bias"])], axis=-1,
            )
    else:
        # UNFUSED layout for tensor-parallel serving: a fused
        # [q|k|v] (or [gate|up]) column block sharded down its last
        # axis hands device 0 all the q heads — per-matrix weights
        # shard head-correctly with a plain P(None, "tp")
        out["wq"], out["wk"], out["wv"] = (
            jnp.asarray(wq), jnp.asarray(wk), jnp.asarray(wv))
        out["wgate"] = jnp.asarray(p["mlp"]["gate_proj"]["kernel"])
        out["wup"] = jnp.asarray(p["mlp"]["up_proj"]["kernel"])
        if "bias" in attn["q_proj"]:
            out["bq"] = flat(attn["q_proj"]["bias"])
            out["bk"] = flat(attn["k_proj"]["bias"])
            out["bv"] = flat(attn["v_proj"]["bias"])
    return out


def serving_params_from_llama(
    variables: Any,
    cfg: LlamaConfig,
    int8: bool = False,
    dtype=None,
    fuse: bool = True,
) -> Dict[str, Any]:
    """Convert a ``LlamaModel`` variables dict (either per-layer
    ``layer_{i}`` naming or the ``nn.scan`` stacked form) into the
    serving layout; ``int8=True`` pre-quantizes every projection into
    the Pallas kernel layout at load time."""
    import flax.linen as nn

    if dtype is None:
        dtype = cfg.dtype
    variables = nn.meta.unbox(variables)
    params = variables["params"] if "params" in variables else variables
    if "layers" in params:  # scan form: unstack the leading layer axis
        stacked = _layer_tree(params["layers"]["layer"], cfg, fuse)
        per_layer = [
            {k: v[i] for k, v in stacked.items()}
            for i in range(cfg.num_layers)
        ]
    else:
        per_layer = [
            _layer_tree(params[f"layer_{i}"], cfg, fuse)
            for i in range(cfg.num_layers)
        ]

    # layers stay a LIST of per-layer trees — the decode loop is
    # unrolled, and an unstacked weight is a buffer the Pallas int8
    # kernel (and XLA) reads directly; a stacked array would force a
    # materialized slice copy per layer per step (measured: the copies
    # cost as much as the int8 matmuls they feed)
    def finish(name: str, w):
        if name not in _LAYER_MATS:
            return jnp.asarray(w)
        if int8:
            return _maybe_quant(w, True)
        return jnp.asarray(w, dtype)

    layers = [
        {k: finish(k, v) for k, v in lt.items()} for lt in per_layer
    ]
    embed = jnp.asarray(params["embed_tokens"]["embedding"], dtype)
    out: Dict[str, Any] = {
        "embed": embed,
        "layers": layers,
        "final_norm": params["final_norm"]["scale"],
    }
    if cfg.tie_embeddings:
        out["lm_head"] = None
    else:
        out["lm_head"] = _maybe_quant(
            jnp.asarray(params["lm_head"]["kernel"], dtype), int8
        )
    return out


def serving_params_nbytes(sp: Dict[str, Any]) -> int:
    from dlrover_tpu.optimizers.low_bit import state_nbytes

    return state_nbytes(sp)


# -- tensor-parallel serving ------------------------------------------------

# output-dim-sharded matrices (column parallel) vs input-dim-sharded
# (row parallel, psum after): the Megatron split, realized here purely
# through input placement — jit propagates the shardings and GSPMD
# inserts the collectives (scaling-book recipe; no hand-written
# collectives anywhere)
_COL_PARALLEL = ("wq", "wk", "wv", "wgate", "wup")
_ROW_PARALLEL = ("wo", "down")


def _mat_spec(name: str, P):
    if name in _COL_PARALLEL:
        return P(None, "tp")
    if name in _ROW_PARALLEL:
        return P("tp", None)
    return P()  # norms, biases of replicated mats


def shard_serving_state(
    params: Dict[str, Any], cache: Dict[str, Any], mesh, cfg: LlamaConfig
) -> tuple:
    """Place the serving params + KV cache onto a ``tp`` mesh.

    Column-parallel q/k/v/gate/up, row-parallel o/down, tp-sharded
    lm_head columns, kv-heads-sharded cache; requires the UNFUSED param
    layout (``serving_params_from_llama(fuse=False)``) and
    ``num_kv_heads % tp == 0``.  int8 ``{"q","scale"}`` pairs shard the
    codes like the fp matrix and the per-column scales with the output
    dim.  Everything else replicates."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    tp = mesh.shape.get("tp", 1)
    if cfg.num_kv_heads % tp or cfg.num_heads % tp:
        raise ValueError(
            f"tp={tp} must divide num_heads={cfg.num_heads} and "
            f"num_kv_heads={cfg.num_kv_heads}"
        )
    if any("wqkv" in lt for lt in params["layers"]):
        raise ValueError(
            "sharded serving needs the unfused param layout: build "
            "with serving_params_from_llama(..., fuse=False)"
        )

    def put(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))

    def place_mat(name: str, w):
        spec = _mat_spec(name, P)
        if isinstance(w, dict):  # int8 {"q","scale"}
            scale_spec = P(None, "tp") if name in _COL_PARALLEL else P()
            return {"q": put(w["q"], spec),
                    "scale": put(w["scale"], scale_spec)}
        return put(w, spec)

    layers = [
        {k: place_mat(k, v) for k, v in lt.items()}
        for lt in params["layers"]
    ]
    out = {
        "embed": put(params["embed"], P()),
        "final_norm": put(params["final_norm"], P()),
        "layers": layers,
    }
    head = params.get("lm_head")
    out["lm_head"] = (
        None if head is None else place_mat("wgate", head)  # col spec
    )

    kv_spec = P(None, None, "tp", None)  # [.., .., KV, D]
    scale_spec = P(None, None, "tp")     # [NB, bs, KV] int8 KV scales
    sharded_cache = {}
    for key, val in cache.items():
        if key in ("k", "v", "k_pool", "v_pool"):
            sharded_cache[key] = [put(x, kv_spec) for x in val]
        elif key in ("k_scale", "v_scale"):
            sharded_cache[key] = [put(x, scale_spec) for x in val]
        else:
            sharded_cache[key] = put(val, P())
    return out, sharded_cache
