"""Serving-layout parameters: the training tree flattened for decode.

The serving engine (`dlrover_tpu.serving.engine`) runs a dedicated
functional forward (`dlrover_tpu.serving.model`) instead of the flax
training module — the same split the reference makes between its
training model and the vLLM inference backend it hands RL rollouts to
(reference: atorch/atorch/rl/inference_backend/vllm_backend.py:11-24,
which wraps weights into a purpose-built inference engine rather than
reusing the trainer's module).

Why a separate layout:

- every projection becomes a plain 2D ``[K, N]`` matrix so the int8
  serving path can PRE-quantize it once into the exact layout the
  Pallas kernel reads (``ops/pallas/quant_matmul.prequantize_weight``)
  — fixing the measured 0.6x w8a8 shortfall whose cause was per-call
  dynamic weight quantization;
- layers are stacked along a leading axis so prefill/decode scan over
  them with one compiled body (same trick as training ``nn.scan``);
- the tree is a plain dict of arrays — no flax module state, trivially
  shardable/donatable.

Weight entries are either an fp array ``[K, N]`` or a
``{"q": int8 [K, N], "scale": f32 [1, N]}`` pair.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from dlrover_tpu.models.llama import LlamaConfig
from dlrover_tpu.ops.pallas.quant_matmul import prequantize_weight

# weights quantized when int8=True; norms/embedding always stay fp.
# wqkv / wgu are load-time fusions: one [E, H*D+2*KV*D] matmul instead
# of three and one [E, 2F] instead of two — fewer, larger kernels (the
# standard serving fusion; decode is launch/bandwidth-bound)
_LAYER_MATS = ("wqkv", "wo", "wgu", "down")


def _maybe_quant(w: jax.Array, int8: bool):
    if not int8:
        return w
    q, scale = prequantize_weight(jnp.asarray(w, jnp.float32))
    return {"q": q, "scale": scale}


def _layer_tree(p: Dict[str, Any], cfg: LlamaConfig) -> Dict[str, Any]:
    """One flax DecoderLayer param subtree -> serving 2D matrices.

    Handles both the per-layer form ([E, H, D] kernels) and the
    ``nn.scan`` stacked form ([L, E, H, D]): only trailing dims
    collapse, any leading layer axis passes through.
    """
    attn = p["attn"]

    def merge_last2(w):   # [..., E, H, D] -> [..., E, H*D]
        return w.reshape(*w.shape[:-2], w.shape[-2] * w.shape[-1])

    def merge_head_in(w):  # [..., H, D, E] -> [..., H*D, E]
        return w.reshape(*w.shape[:-3], w.shape[-3] * w.shape[-2],
                         w.shape[-1])

    wq = merge_last2(attn["q_proj"]["kernel"])
    wk = merge_last2(attn["k_proj"]["kernel"])
    wv = merge_last2(attn["v_proj"]["kernel"])
    out = {
        "input_norm": p["input_norm"]["scale"],
        "post_norm": p["post_norm"]["scale"],
        "wqkv": jnp.concatenate([jnp.asarray(wq), jnp.asarray(wk),
                                 jnp.asarray(wv)], axis=-1),
        "wo": merge_head_in(attn["o_proj"]["kernel"]),
        "wgu": jnp.concatenate(
            [jnp.asarray(p["mlp"]["gate_proj"]["kernel"]),
             jnp.asarray(p["mlp"]["up_proj"]["kernel"])], axis=-1),
        "down": p["mlp"]["down_proj"]["kernel"],
    }
    if "bias" in attn["q_proj"]:
        # Qwen2-family qkv biases, fused to match the wqkv layout
        def flat(b):  # [..., H, D] -> [..., H*D]
            return jnp.asarray(b).reshape(
                *b.shape[:-2], b.shape[-2] * b.shape[-1]
            )

        out["bqkv"] = jnp.concatenate(
            [flat(attn["q_proj"]["bias"]), flat(attn["k_proj"]["bias"]),
             flat(attn["v_proj"]["bias"])], axis=-1,
        )
    return out


def serving_params_from_llama(
    variables: Any,
    cfg: LlamaConfig,
    int8: bool = False,
    dtype=None,
) -> Dict[str, Any]:
    """Convert a ``LlamaModel`` variables dict (either per-layer
    ``layer_{i}`` naming or the ``nn.scan`` stacked form) into the
    serving layout; ``int8=True`` pre-quantizes every projection into
    the Pallas kernel layout at load time."""
    import flax.linen as nn

    if dtype is None:
        dtype = cfg.dtype
    variables = nn.meta.unbox(variables)
    params = variables["params"] if "params" in variables else variables
    if "layers" in params:  # scan form: unstack the leading layer axis
        stacked = _layer_tree(params["layers"]["layer"], cfg)
        per_layer = [
            {k: v[i] for k, v in stacked.items()}
            for i in range(cfg.num_layers)
        ]
    else:
        per_layer = [
            _layer_tree(params[f"layer_{i}"], cfg)
            for i in range(cfg.num_layers)
        ]

    # layers stay a LIST of per-layer trees — the decode loop is
    # unrolled, and an unstacked weight is a buffer the Pallas int8
    # kernel (and XLA) reads directly; a stacked array would force a
    # materialized slice copy per layer per step (measured: the copies
    # cost as much as the int8 matmuls they feed)
    def finish(name: str, w):
        if name not in _LAYER_MATS:
            return jnp.asarray(w)
        if int8:
            return _maybe_quant(w, True)
        return jnp.asarray(w, dtype)

    layers = [
        {k: finish(k, v) for k, v in lt.items()} for lt in per_layer
    ]
    embed = jnp.asarray(params["embed_tokens"]["embedding"], dtype)
    out: Dict[str, Any] = {
        "embed": embed,
        "layers": layers,
        "final_norm": params["final_norm"]["scale"],
    }
    if cfg.tie_embeddings:
        out["lm_head"] = None
    else:
        out["lm_head"] = _maybe_quant(
            jnp.asarray(params["lm_head"]["kernel"], dtype), int8
        )
    return out


def serving_params_nbytes(sp: Dict[str, Any]) -> int:
    from dlrover_tpu.optimizers.low_bit import state_nbytes

    return state_nbytes(sp)
