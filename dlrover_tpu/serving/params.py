"""Serving-layout parameters: the training tree flattened for decode.

The serving engine (`dlrover_tpu.serving.engine`) runs a dedicated
functional forward (`dlrover_tpu.serving.model`) instead of the flax
training module — the same split the reference makes between its
training model and the vLLM inference backend it hands RL rollouts to
(reference: atorch/atorch/rl/inference_backend/vllm_backend.py:11-24,
which wraps weights into a purpose-built inference engine rather than
reusing the trainer's module).

Why a separate layout:

- every projection becomes a plain 2D ``[K, N]`` matrix so the int8
  serving path can PRE-quantize it once into the exact layout the
  Pallas kernel reads (``ops/pallas/quant_matmul.prequantize_weight``)
  — fixing the measured 0.6x w8a8 shortfall whose cause was per-call
  dynamic weight quantization;
- layers are stacked along a leading axis so prefill/decode scan over
  them with one compiled body (same trick as training ``nn.scan``);
- the tree is a plain dict of arrays — no flax module state, trivially
  shardable/donatable.

Weight entries are either an fp array ``[K, N]`` or a
``{"q": int8 [K, N], "scale": f32 [1, N]}`` pair.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from dlrover_tpu.models.llama import LlamaConfig
from dlrover_tpu.ops.pallas.quant_matmul import prequantize_weight

# weights quantized when int8=True; norms/embedding always stay fp
_LAYER_MATS = ("wq", "wk", "wv", "wo", "gate", "up", "down")


def _maybe_quant(w: jax.Array, int8: bool):
    if not int8:
        return w
    q, scale = prequantize_weight(jnp.asarray(w, jnp.float32))
    return {"q": q, "scale": scale}


def _layer_tree(p: Dict[str, Any], cfg: LlamaConfig) -> Dict[str, Any]:
    """One flax DecoderLayer param subtree -> serving 2D matrices.

    Handles both the per-layer form ([E, H, D] kernels) and the
    ``nn.scan`` stacked form ([L, E, H, D]): only trailing dims
    collapse, any leading layer axis passes through.
    """
    attn = p["attn"]

    def merge_last2(w):   # [..., E, H, D] -> [..., E, H*D]
        return w.reshape(*w.shape[:-2], w.shape[-2] * w.shape[-1])

    def merge_head_in(w):  # [..., H, D, E] -> [..., H*D, E]
        return w.reshape(*w.shape[:-3], w.shape[-3] * w.shape[-2],
                         w.shape[-1])

    return {
        "input_norm": p["input_norm"]["scale"],
        "post_norm": p["post_norm"]["scale"],
        "wq": merge_last2(attn["q_proj"]["kernel"]),
        "wk": merge_last2(attn["k_proj"]["kernel"]),
        "wv": merge_last2(attn["v_proj"]["kernel"]),
        "wo": merge_head_in(attn["o_proj"]["kernel"]),
        "gate": p["mlp"]["gate_proj"]["kernel"],
        "up": p["mlp"]["up_proj"]["kernel"],
        "down": p["mlp"]["down_proj"]["kernel"],
    }


def serving_params_from_llama(
    variables: Any,
    cfg: LlamaConfig,
    int8: bool = False,
    dtype=None,
) -> Dict[str, Any]:
    """Convert a ``LlamaModel`` variables dict (either per-layer
    ``layer_{i}`` naming or the ``nn.scan`` stacked form) into the
    serving layout; ``int8=True`` pre-quantizes every projection into
    the Pallas kernel layout at load time."""
    import flax.linen as nn

    if dtype is None:
        dtype = cfg.dtype
    variables = nn.meta.unbox(variables)
    params = variables["params"] if "params" in variables else variables
    if "layers" in params:  # scan form: leading layer axis already there
        stacked = _layer_tree(params["layers"]["layer"], cfg)
    else:
        per_layer = [
            _layer_tree(params[f"layer_{i}"], cfg)
            for i in range(cfg.num_layers)
        ]
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *per_layer
        )

    def quant_stacked(name: str, w: jax.Array):
        if name not in _LAYER_MATS or not int8:
            return jnp.asarray(w, dtype if name in _LAYER_MATS else w.dtype)
        qs = [_maybe_quant(w[i], True) for i in range(w.shape[0])]
        return {
            "q": jnp.stack([x["q"] for x in qs]),
            "scale": jnp.stack([x["scale"] for x in qs]),
        }

    layers = {k: quant_stacked(k, v) for k, v in stacked.items()}
    embed = jnp.asarray(params["embed_tokens"]["embedding"], dtype)
    out: Dict[str, Any] = {
        "embed": embed,
        "layers": layers,
        "final_norm": params["final_norm"]["scale"],
    }
    if cfg.tie_embeddings:
        out["lm_head"] = None
    else:
        out["lm_head"] = _maybe_quant(
            jnp.asarray(params["lm_head"]["kernel"], dtype), int8
        )
    return out


def serving_params_nbytes(sp: Dict[str, Any]) -> int:
    from dlrover_tpu.optimizers.low_bit import state_nbytes

    return state_nbytes(sp)
