"""Start-time fair queueing within one priority band.

Plain FIFO inside a band means the band belongs to whoever submits
fastest.  :class:`WfqBandQueue` replaces it with the classic SFQ
virtual-clock discipline (Goyal et al.): each arrival is tagged

``vstart  = max(band_vclock, tenant_last_vfinish)``
``vfinish = vstart + cost / weight``        (cost = 1 request)

and the band serves ascending ``vfinish``; the virtual clock advances
to the start tag of each departing request.  A tenant that floods
only pushes ITS OWN tags into the future — other tenants' tags stay
near the clock and keep being served at their weight share, which is
the whole noisy-neighbor story in two lines of arithmetic.

Implementation notes (the gateway's deadline-heap idiom, adapted):

- the live set is a dict ``id(request) -> (vfinish, seq, vstart,
  request)``; removal (placement, expiry, cancel, shed) is an O(1)
  dict pop — no heap surgery, no lazy tombstones to sweep;
- the scheduler's window scan asks for the ``limit`` smallest tags
  via ``heapq.nsmallest`` — O(n log limit) only on rounds the
  placement index actually scans (the idle short-circuit keys on the
  gateway's ``queue_gen``, which bumps on every WFQ insert AND pop);
- failover requeues bypass the heap into a FRONT deque served before
  any tagged arrival — a replica crash must not send a half-served
  request behind a flood, which is the band's pre-tenancy contract;
- with a single tenant every ``vfinish`` is strictly increasing and
  the seq tiebreak makes the order EXACTLY FIFO — the trivial
  registry reproduces pre-tenancy behavior bit-for-bit, which is what
  the step-engine equivalence suite replays.

Not thread-safe by itself: the owning gateway already serializes all
queue mutation under its admission lock.
"""

from __future__ import annotations

import heapq
from collections import deque
from typing import Callable, Deque, Dict, Iterator, List, Optional, Tuple

Entry = Tuple[float, int, float, object]


class WfqBandQueue:
    """One priority band's queue, fair-ordered across tenants."""

    def __init__(self, weight_of: Callable[[str], float],
                 shared_counts: Optional[Dict[str, int]] = None):
        #: tenant name -> WFQ weight (> 0; the registry validates)
        self._weight_of = weight_of
        self._front: Deque[object] = deque()
        self._live: Dict[int, Entry] = {}
        self._seq = 0
        self.vclock = 0.0
        self._last_vfinish: Dict[str, float] = {}
        # per-band tenant -> queued count (shed planning reads this)
        self._counts: Dict[str, int] = {}
        # cross-band tenant -> queued count shared with the gateway's
        # sibling bands (per-tenant max_queued is a TENANT bound, not
        # a per-band one)
        self._shared = shared_counts if shared_counts is not None else {}

    # ------------------------------------------------------- bookkeeping
    @staticmethod
    def _tenant(req) -> str:
        return getattr(req, "tenant", "default")

    def _count(self, tenant: str, delta: int) -> None:
        for book in (self._counts, self._shared):
            n = book.get(tenant, 0) + delta
            if n > 0:
                book[tenant] = n
            else:
                book.pop(tenant, None)

    # ------------------------------------------------------------ insert
    def append(self, req) -> None:
        """Tag and enqueue one arrival (virtual-clock discipline)."""
        tenant = self._tenant(req)
        weight = max(1e-9, float(self._weight_of(tenant)))
        vstart = max(self.vclock,
                     self._last_vfinish.get(tenant, 0.0))
        vfinish = vstart + 1.0 / weight
        self._last_vfinish[tenant] = vfinish
        self._seq += 1
        self._live[id(req)] = (vfinish, self._seq, vstart, req)
        self._count(tenant, +1)

    def appendleft(self, req) -> None:
        """Failover requeue: ahead of every tagged arrival, untagged —
        the request already won its place once and lost it to a crash,
        not to fair queueing."""
        self._front.appendleft(req)
        self._count(self._tenant(req), +1)

    # ------------------------------------------------------------ remove
    def remove(self, req) -> None:
        """Depart one request (placement pop, single cancel).  Raises
        ``ValueError`` when absent — deque-compatible, the gateway's
        remove() contract."""
        entry = self._live.pop(id(req), None)
        if entry is not None:
            # SFQ: the virtual clock follows the start tag of the
            # request entering service
            self.vclock = max(self.vclock, entry[2])
            self._count(self._tenant(req), -1)
            return
        self._front.remove(req)  # ValueError propagates when absent
        self._count(self._tenant(req), -1)

    def discard_ids(self, ids) -> None:
        """Bulk removal by ``id(request)`` — the expiry/cancel
        partition path (mass expiry must not be O(n) per entry)."""
        dropped = [e for i, e in self._live.items() if i in ids]
        for entry in dropped:
            del self._live[id(entry[3])]
            self._count(self._tenant(entry[3]), -1)
        if self._front:
            kept = deque()
            for req in self._front:
                if id(req) in ids:
                    self._count(self._tenant(req), -1)
                else:
                    kept.append(req)
            self._front = kept

    def clear_all(self) -> List[object]:
        """Take EVERY queued request (the legacy single-tenant
        brown-out clear), in service order.  Counts come down one by
        one — the shared cross-band book also carries the sibling
        bands' entries, which must survive this band's clear."""
        out = list(self)
        for req in out:
            self._count(self._tenant(req), -1)
        self._front.clear()
        self._live.clear()
        return out

    def pop_shed(self, plan: List[Tuple[str, int]]) -> List[object]:
        """Take requests per a shed plan ``[(tenant, n)]``, newest
        (largest vfinish) first within each tenant — the least
        entitled queue positions go first; front-deque entries
        (failover survivors) go only after a tenant's tagged queue is
        exhausted."""
        out: List[object] = []
        for tenant, n in plan:
            if n <= 0:
                continue
            mine = sorted(
                (e for e in self._live.values()
                 if self._tenant(e[3]) == tenant),
                reverse=True)
            for entry in mine[:n]:
                req = entry[3]
                del self._live[id(req)]
                self._count(tenant, -1)
                out.append(req)
            n -= min(n, len(mine))
            if n > 0 and self._front:
                kept = deque()
                taken = 0
                for req in reversed(self._front):
                    if taken < n and self._tenant(req) == tenant:
                        taken += 1
                        self._count(tenant, -1)
                        out.append(req)
                    else:
                        kept.appendleft(req)
                self._front = kept
        return out

    # ------------------------------------------------------------- views
    def scan(self, limit: int) -> List[object]:
        """The first ``limit`` requests in service order: front deque,
        then ascending (vfinish, seq)."""
        out: List[object] = []
        for req in self._front:
            if len(out) >= limit:
                return out
            out.append(req)
        rest = limit - len(out)
        if rest > 0 and self._live:
            for entry in heapq.nsmallest(rest, self._live.values()):
                out.append(entry[3])
        return out

    def counts_by_tenant(self) -> Dict[str, int]:
        return dict(self._counts)

    def __iter__(self) -> Iterator[object]:
        yield from self._front
        for entry in sorted(self._live.values()):
            yield entry[3]

    def __len__(self) -> int:
        return len(self._front) + len(self._live)

    def __bool__(self) -> bool:
        return bool(self._front) or bool(self._live)
