"""Per-tenant QoS: identity, quotas, fair queueing, shed policy.

The gateway's priority bands answer "how urgent is this request";
this package answers "WHO is asking" — without it one flooding client
eats an entire band and every other tenant in it starves behind a
queue that is, formally, perfectly FIFO-fair.

Layers (policy + accounting only — no router imports, so the router
stack can import tenancy without a cycle):

- :mod:`registry` — :class:`TenantSpec` (quota QPS, queue/in-flight
  caps, WFQ weight, SLO class, shed class) + :class:`TenantRegistry`
  (resolution with a configurable default tenant, token-bucket quota
  state, per-tenant accounting) and the BOUNDED ``tenant_class`` label
  vocabulary that keeps per-tenant metrics DL010-clean;
- :mod:`wfq` — :class:`WfqBandQueue`, the start-time-fair-queueing
  virtual-clock heap that replaces plain FIFO *within* each priority
  band (the same lazy-heap idiom as the gateway's deadline heap).

The wiring lives in the router stack: the gateway resolves tenants and
admits through the buckets, the scheduler's window preserves WFQ
order, the brown-out sweep sheds proportionally by over-use, and the
SLO engine burns per tenant class.
"""

from dlrover_tpu.serving.tenancy.registry import (  # noqa: F401
    SHED_CLASSES,
    TENANT_CLASSES,
    TenantRegistry,
    TenantSpec,
    plan_shed,
)
from dlrover_tpu.serving.tenancy.wfq import WfqBandQueue  # noqa: F401
