"""Tenant identity, quotas and shed policy.

A tenant is the unit of isolation the fleet promises: each one gets a
rate quota (token bucket), buffer bounds (max queued / max in-flight),
a weighted-fair-queueing weight, an SLO class and a shed class.  The
registry is deliberately small-N: tenants are REGISTERED (a config
surface, not a per-request discovery), unknown tenant ids resolve to
one configurable default tenant — so an adversarial id stream can
neither crash admission nor grow per-tenant state without bound.

Metric cardinality is the trap DL010 exists for: per-tenant label
VALUES on a Prometheus family would explode with the tenant
population.  Every exported family therefore labels by
``tenant_class`` from the bounded :data:`TENANT_CLASSES` vocabulary;
raw tenant ids stay in logs, traces and JSON summaries only.
"""

from __future__ import annotations

import dataclasses
import json
import threading
from typing import Dict, Iterable, List, Optional, Tuple

#: The bounded metric-label vocabulary (``tenant_class``).  Closed by
#: design: adding a class means adding it HERE, where the registry
#: validates against it and the renderers enumerate it — never from a
#: request field.
TENANT_CLASSES = ("premium", "standard", "background")

#: Brown-out shed ordering: ``first`` sheds before ``fair`` sheds
#: before ``last`` (multipliers on the fair-share allowance below).
SHED_CLASSES = ("first", "fair", "last")

_SHED_RANK = {name: i for i, name in enumerate(SHED_CLASSES)}
_SHED_ALLOWANCE_MULT = {"first": 0.0, "fair": 1.0, "last": 2.0}


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant's QoS contract.

    ``quota_qps=None`` means unmetered (the default tenant ships that
    way — quotas are an opt-in per registered tenant); ``burst`` is
    the token-bucket capacity (defaults to one second of quota).
    ``weight`` is the WFQ share within a priority band; zero or
    negative weight is a CONFIG ERROR (it would starve the tenant
    structurally, which no operator means) and raises here rather
    than at the first starved request."""

    name: str
    quota_qps: Optional[float] = None
    burst: Optional[float] = None
    max_queued: Optional[int] = None
    max_inflight: Optional[int] = None
    weight: float = 1.0
    tenant_class: str = "standard"
    shed_class: str = "fair"

    def __post_init__(self):
        if self.weight <= 0.0:
            raise ValueError(
                f"tenant {self.name!r}: weight must be > 0 "
                f"(got {self.weight}) — a zero-weight tenant would "
                "never be served; delete it instead")
        if self.tenant_class not in TENANT_CLASSES:
            raise ValueError(
                f"tenant {self.name!r}: tenant_class "
                f"{self.tenant_class!r} not in the bounded vocabulary "
                f"{TENANT_CLASSES} (DL010: label values must be "
                "closed)")
        if self.shed_class not in SHED_CLASSES:
            raise ValueError(
                f"tenant {self.name!r}: shed_class "
                f"{self.shed_class!r} not in {SHED_CLASSES}")
        if self.quota_qps is not None and self.quota_qps <= 0:
            raise ValueError(
                f"tenant {self.name!r}: quota_qps must be > 0 or "
                f"None (got {self.quota_qps})")

    @property
    def bucket_capacity(self) -> float:
        if self.burst is not None:
            return max(1.0, float(self.burst))
        if self.quota_qps is not None:
            return max(1.0, float(self.quota_qps))
        return 1.0

    @property
    def shed_rank(self) -> int:
        return _SHED_RANK[self.shed_class]

    @property
    def shed_allowance_mult(self) -> float:
        return _SHED_ALLOWANCE_MULT[self.shed_class]


class _TokenBucket:
    """Classic token bucket: ``rate`` tokens/s up to ``capacity``.
    ``retry_after_s`` after a refusal is the time to the NEXT whole
    token — the honest Retry-After hint (coming back sooner cannot
    succeed; later wastes admitted capacity)."""

    __slots__ = ("rate", "capacity", "tokens", "stamp")

    def __init__(self, rate: float, capacity: float, now: float):
        self.rate = float(rate)
        self.capacity = float(capacity)
        self.tokens = float(capacity)
        self.stamp = float(now)

    def _refill(self, now: float) -> None:
        if now > self.stamp:
            self.tokens = min(
                self.capacity,
                self.tokens + (now - self.stamp) * self.rate)
        self.stamp = max(self.stamp, now)

    def consume(self, now: float) -> bool:
        self._refill(now)
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False

    def retry_after_s(self, now: float) -> float:
        self._refill(now)
        if self.tokens >= 1.0:
            return 0.0
        return (1.0 - self.tokens) / max(1e-9, self.rate)


class TenantRegistry:
    """Registered tenants + resolution + quota state + accounting.

    Thread-safe where it must be: the gateway consults it under its
    own admission lock, but a :class:`~dlrover_tpu.serving.router.
    stepengine.ShardedRouterFront` shares ONE registry across N
    shard gateways (a per-shard registry would multiply every quota
    by N), so bucket consumption takes the registry's own lock."""

    def __init__(self, specs: Iterable[TenantSpec] = (),
                 default_tenant: str = "default"):
        self.default_tenant = str(default_tenant)
        self._specs: Dict[str, TenantSpec] = {}
        self._buckets: Dict[str, _TokenBucket] = {}
        self._lock = threading.Lock()
        # per-tenant lifecycle accounting (names are bounded by the
        # registry: unknown ids resolve to the default tenant first)
        self.admitted: Dict[str, int] = {}
        self.quota_rejected: Dict[str, int] = {}
        self.shed: Dict[str, int] = {}
        self.tokens: Dict[str, int] = {}
        # SLO-burn WFQ boost: tenant_class -> temporary weight
        # multiplier (>= 1.0).  Fed by the router's observe phase from
        # the SLO engine's per-class burn rates; a class burning its
        # error budget gets a BOUNDED multiplier on every member
        # tenant's WFQ weight until the burn recovers, then the boost
        # decays geometrically back to 1.0.  Keyed on the closed
        # TENANT_CLASSES vocabulary, never raw ids.
        self._class_boost: Dict[str, float] = {}
        for spec in specs:
            self.register(spec)
        if self.default_tenant not in self._specs:
            self.register(TenantSpec(name=self.default_tenant))

    # ------------------------------------------------------ membership
    def register(self, spec: TenantSpec) -> TenantSpec:
        self._specs[spec.name] = spec
        self._buckets.pop(spec.name, None)  # re-arm on re-register
        self.admitted.setdefault(spec.name, 0)
        self.quota_rejected.setdefault(spec.name, 0)
        self.shed.setdefault(spec.name, 0)
        self.tokens.setdefault(spec.name, 0)
        return spec

    def names(self) -> List[str]:
        return list(self._specs)

    def get(self, name: str) -> Optional[TenantSpec]:
        return self._specs.get(name)

    def resolve(self, name: Optional[str]) -> TenantSpec:
        """Unknown (or absent) tenant ids land on the default tenant —
        admission NEVER crashes on identity, and per-tenant state stays
        bounded by the registered set."""
        if name is not None:
            spec = self._specs.get(name)
            if spec is not None:
                return spec
        return self._specs[self.default_tenant]

    @property
    def trivial(self) -> bool:
        """Only the default tenant is registered — the single-tenant
        fleet; callers keep the exact legacy (pre-tenancy) behavior."""
        return len(self._specs) == 1

    # ----------------------------------------------------------- quota
    def try_admit(self, spec: TenantSpec,
                  now: float) -> Tuple[bool, float]:
        """Consume one quota token; ``(admitted, retry_after_s)``.
        Unmetered tenants always admit."""
        if spec.quota_qps is None:
            return True, 0.0
        with self._lock:
            bucket = self._buckets.get(spec.name)
            if bucket is None or bucket.rate != spec.quota_qps:
                bucket = _TokenBucket(
                    spec.quota_qps, spec.bucket_capacity, now)
                self._buckets[spec.name] = bucket
            if bucket.consume(now):
                return True, 0.0
            return False, bucket.retry_after_s(now)

    # ------------------------------------------------------ accounting
    def count_admitted(self, name: str) -> None:
        self.admitted[name] = self.admitted.get(name, 0) + 1

    def count_quota_rejected(self, name: str) -> None:
        self.quota_rejected[name] = self.quota_rejected.get(name, 0) + 1

    def count_shed(self, name: str) -> None:
        self.shed[name] = self.shed.get(name, 0) + 1

    def by_class(self, counts: Dict[str, int]) -> Dict[str, float]:
        """Aggregate a per-tenant counter dict onto the bounded
        ``tenant_class`` vocabulary — the only shape metrics export."""
        out = {cls: 0.0 for cls in TENANT_CLASSES}
        for name, n in counts.items():
            out[self.resolve(name).tenant_class] += float(n)
        return out

    def note_tokens(self, tenant: Optional[str], n: int) -> None:
        """Book ``n`` generated tokens against the tenant (unknown ids
        land on the default tenant, so the book stays bounded by the
        registered set — same resolution rule as admission)."""
        if n <= 0:
            return
        name = self.resolve(tenant).name
        self.tokens[name] = self.tokens.get(name, 0) + int(n)

    def usage_snapshot(self) -> Dict[str, Dict[str, object]]:
        """Per-tenant usage books keyed by RAW tenant id — the JSON
        shape the ``/tenants/usage`` endpoint serves.  Raw ids are fine
        HERE (an on-demand JSON document, bounded by the registered
        set); they must never become Prometheus label values (DL010)."""
        out: Dict[str, Dict[str, object]] = {}
        for name in sorted(self._specs):
            spec = self._specs[name]
            out[name] = {
                "tenant_class": spec.tenant_class,
                "weight": spec.weight,
                "boosted_weight": self.boosted_weight(spec),
                "admitted": int(self.admitted.get(name, 0)),
                "quota_rejected": int(self.quota_rejected.get(name, 0)),
                "shed": int(self.shed.get(name, 0)),
                "tokens": int(self.tokens.get(name, 0)),
            }
        return out

    # ------------------------------------------- SLO-burn weight boost
    def update_slo_boosts(self, burns: Dict[str, float],
                          max_boost: float = 4.0,
                          decay: float = 0.5) -> None:
        """Drive the per-class WFQ boost from SLO burn rates.

        A class burning error budget (burn > 1.0) gets its boost raised
        to the burn rate, clamped to ``max_boost`` and never lowered by
        a same-round smaller burn; once the burn recovers (<= 1.0) the
        boost decays geometrically toward 1.0 and snaps there — the
        boost is TEMPORARY by construction, so a past incident cannot
        permanently skew the fair queue."""
        for cls, burn in burns.items():
            if cls not in TENANT_CLASSES:
                continue
            cur = self._class_boost.get(cls, 1.0)
            if burn > 1.0:
                new = min(float(max_boost), max(cur, float(burn)))
            else:
                new = 1.0 + (cur - 1.0) * float(decay)
                if new < 1.001:
                    new = 1.0
            if new <= 1.0:
                self._class_boost.pop(cls, None)
            else:
                self._class_boost[cls] = new

    def boost_of(self, tenant_class: str) -> float:
        return self._class_boost.get(tenant_class, 1.0)

    def boosted_weight(self, spec: TenantSpec) -> float:
        """The WFQ weight admission should use: the spec's configured
        weight times its class's current (bounded, decaying) boost."""
        return spec.weight * self.boost_of(spec.tenant_class)

    # ----------------------------------------------------- persistence
    _SPEC_FIELDS = ("quota_qps", "burst", "max_queued", "max_inflight",
                    "weight", "tenant_class", "shed_class")

    def to_file(self, path: str) -> None:
        """Persist the registered specs as JSON (atomic enough for a
        config file: whole-document write).  Only the QoS contracts are
        saved — usage books and quota bucket state are runtime, not
        config."""
        doc = {
            "default_tenant": self.default_tenant,
            "tenants": [
                dict(name=s.name,
                     **{f: getattr(s, f) for f in self._SPEC_FIELDS})
                for s in self._specs.values()
            ],
        }
        with open(path, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")

    @staticmethod
    def _specs_from_doc(doc: dict) -> Tuple[str, List[TenantSpec]]:
        default = str(doc.get("default_tenant", "default"))
        specs = []
        for entry in doc.get("tenants", []):
            kwargs = {k: entry[k] for k in TenantRegistry._SPEC_FIELDS
                      if k in entry}
            specs.append(TenantSpec(name=str(entry["name"]), **kwargs))
        return default, specs

    @classmethod
    def from_file(cls, path: str) -> "TenantRegistry":
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
        default, specs = cls._specs_from_doc(doc)
        return cls(specs, default_tenant=default)

    def reload_file(self, path: str) -> Tuple[int, int]:
        """Live reload IN PLACE (SIGHUP / admin endpoint): specs in the
        file are (re-)registered, registered tenants absent from it are
        dropped — except the default tenant, which always exists.
        Usage books for surviving tenants are kept (a config reload
        must not zero the accounting); a re-registered spec re-arms its
        quota bucket exactly like :meth:`register`.  The file is parsed
        and VALIDATED before any mutation, so a malformed reload leaves
        the live registry untouched.  Returns ``(registered,
        removed)``."""
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
        default, specs = self._specs_from_doc(doc)
        self.default_tenant = default
        for spec in specs:
            self.register(spec)
        keep = {s.name for s in specs} | {self.default_tenant}
        removed = [n for n in self._specs if n not in keep]
        for name in removed:
            del self._specs[name]
            self._buckets.pop(name, None)
        if self.default_tenant not in self._specs:
            self.register(TenantSpec(name=self.default_tenant))
        return len(specs), len(removed)


def plan_shed(counts: Dict[str, int], registry: TenantRegistry,
              keep_total: int) -> List[Tuple[str, int]]:
    """How many queued requests to shed per tenant to bring a band of
    ``sum(counts.values())`` down to ``keep_total``, taking from the
    tenants FURTHEST OVER their fair share first.

    Fair share of the survivor budget is weight-proportional over the
    tenants present, scaled by the shed-class multiplier (``first``
    tenants keep nothing, ``last`` keep double).  Two passes: the
    overage pass takes only above-allowance requests in
    (shed_rank, overage-descending) order; if the budget still is not
    met — every tenant within allowance but the band as a whole over
    budget — a second pass takes proportionally from what remains.
    Returns ``[(tenant, n_to_shed)]`` in take order."""
    total = sum(counts.values())
    to_shed = total - max(0, int(keep_total))
    if to_shed <= 0:
        return []
    weights = {t: registry.resolve(t).weight for t in counts}
    wsum = sum(weights.values()) or 1.0
    allow = {
        t: (registry.resolve(t).shed_allowance_mult
            * (weights[t] / wsum) * max(0, int(keep_total)))
        for t in counts
    }
    order = sorted(
        counts,
        key=lambda t: (registry.resolve(t).shed_rank,
                       -(counts[t] - allow[t])))
    plan: List[Tuple[str, int]] = []
    left = {t: counts[t] for t in counts}
    # pass 1: over-allowance only, worst offenders first
    for t in order:
        if to_shed <= 0:
            break
        over = int(min(left[t], max(0.0, counts[t] - allow[t])))
        take = min(over, to_shed)
        if take > 0:
            plan.append((t, take))
            left[t] -= take
            to_shed -= take
    # pass 2: the band is over budget even with everyone within
    # allowance — take the remainder in the same order
    for t in order:
        if to_shed <= 0:
            break
        take = min(left[t], to_shed)
        if take > 0:
            plan.append((t, take))
            left[t] -= take
            to_shed -= take
    return plan
