"""Functional Llama forward for serving: prefill + per-slot decode.

The inference-engine half of the reference's RL serving story
(atorch/atorch/rl/inference_backend/vllm_backend.py:11-24): a
purpose-built decode path instead of the training module, because
serving wants different things than training —

- **per-slot positions**: every batch row is an independent sequence at
  its own decode position (continuous batching), so the KV cache is
  written with a per-row scatter and masked with per-row lengths; the
  training module's cache clock is a single shared offset
  (models/llama.py:271).
- **prefill/decode split**: prefill is one causal pass over a
  right-padded prompt bucket ([1, Lp]); decode is a one-token step for
  all slots at once.  Right-padding needs NO validity bookkeeping: a
  pad entry at cache index i > pos is invisible to the ``key <= pos``
  mask until the sequence itself overwrites index i with a real token.
- **chunked decode**: ``decode_chunk`` runs N steps inside one
  ``lax.scan`` so the host syncs once per chunk, not per token (the
  multi-step scheduling trick of serving engines — and on this rig the
  host<->device hop is a slow debug tunnel, so it is the difference
  between measuring the model and measuring the RPC).
- **pre-quantized int8 weights**: every projection may be
  ``{"q", "scale"}``; only activations quantize per call and weights
  stream from HBM at int8 width through XLA's native int8 MXU dot —
  decode's actual bottleneck (see ``_mm``).

All functions are pure; the engine (serving/engine.py) owns jit and
cache state.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from dlrover_tpu.models.llama import LlamaConfig, apply_rope, rope_frequencies
from dlrover_tpu.ops.attention import dot_product_attention

from dlrover_tpu.rl.generation import select_token


def _mm(x: jax.Array, w: Any, dtype, wide: bool = False) -> jax.Array:
    """x @ w for fp or pre-quantized ({"q","scale"}) weights.

    Every int8 matmul — decode AND prefill — runs XLA's NATIVE int8
    dot: per-row activation scales, int8xint8 -> int32 on the MXU,
    per-column weight scales applied on the OUTPUT (column scales
    commute with the contraction, so this matches dequantize-first
    numerics).  Measured on v5e (benchmarks/probes/int8_decode_probe*):
    at decode shapes (M=8, h2048) the native dot streams weights at
    331 GB/s vs the Pallas kernel's 259 and bf16's wins grow with N
    (square 1.25x, qkv-fused 1.51x, lm head 1.83x) — XLA's own
    pipeline beats the hand-tiled kernel at every serving shape, so
    the Pallas path is gone (it remains in ops/ for the training-side
    frozen-layer use).  ``wide`` is kept for call-site documentation
    only.
    """
    if isinstance(w, dict):
        amax = jnp.maximum(
            jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1,
                    keepdims=True),
            1e-8,
        )
        xq = jnp.round(
            x.astype(jnp.float32) / amax * 127.0
        ).astype(jnp.int8)
        out = jax.lax.dot_general(
            xq, w["q"],
            (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        return (
            out.astype(jnp.float32) * (amax / 127.0) * w["scale"]
        ).astype(dtype)
    return (x.astype(dtype) @ w.astype(dtype)).astype(dtype)


def _rmsnorm(x: jax.Array, scale: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps) * scale.astype(jnp.float32))


def _split_heads(x: jax.Array, n_heads: int, d: int) -> jax.Array:
    b, t = x.shape[:2]
    return x.reshape(b, t, n_heads, d)


def _write_cache(cache: jax.Array, kv: jax.Array,
                 positions: jax.Array) -> jax.Array:
    """Per-row BLOCK scatter: writes kv[b]'s full K-token run at
    cache[b, positions[b] : positions[b]+K] (dynamic_update_slice block
    semantics — K=1 is the plain decode write; the speculative verify
    and the engine's cache-slack sizing both rely on the K-row case)."""
    def one(c, x, p):
        return jax.lax.dynamic_update_slice(c, x, (p, 0, 0))
    return jax.vmap(one)(cache, kv, positions.astype(jnp.int32))


def _layer_weights(layers, i: int) -> Dict[str, Any]:
    """Layer ``i``'s weights: params store an unstacked per-layer LIST
    so each weight is its own buffer — read directly by the Pallas int8
    kernel / XLA with no per-step slice copies (serving/params.py)."""
    return layers[i]


def _qkv_split(cfg: LlamaConfig, qkv: jax.Array):
    d = cfg.head_dim_
    qd = cfg.num_heads * d
    kvd = cfg.num_kv_heads * d
    return (
        _split_heads(qkv[..., :qd], cfg.num_heads, d),
        _split_heads(qkv[..., qd:qd + kvd], cfg.num_kv_heads, d),
        _split_heads(qkv[..., qd + kvd:], cfg.num_kv_heads, d),
    )


def _attn_proj(lp, h, cfg: LlamaConfig, dtype, wide: bool = False):
    """q/k/v projections for either param layout: fused ``wqkv``
    (single-chip decode: fewer, larger launches) or unfused
    ``wq/wk/wv`` (tensor-parallel serving: per-matrix column sharding
    keeps head semantics — params.py shard_serving_state)."""
    d = cfg.head_dim_
    if "wqkv" in lp:
        qkv = _mm(h, lp["wqkv"], dtype, wide)
        if "bqkv" in lp:  # Qwen2-family qkv biases
            qkv = qkv + lp["bqkv"].astype(dtype)
        return _qkv_split(cfg, qkv)

    def one(wn: str, bn: str, heads: int):
        y = _mm(h, lp[wn], dtype, wide)
        if bn in lp:
            y = y + lp[bn].astype(dtype)
        return _split_heads(y, heads, d)

    return (
        one("wq", "bq", cfg.num_heads),
        one("wk", "bk", cfg.num_kv_heads),
        one("wv", "bv", cfg.num_kv_heads),
    )


def _mlp(lp, h, cfg: LlamaConfig, dtype, wide: bool = False):
    f = cfg.intermediate_size
    if "wgu" in lp:
        gu = _mm(h, lp["wgu"], dtype, wide)
        act = jax.nn.silu(gu[..., :f]) * gu[..., f:]
    else:
        act = jax.nn.silu(_mm(h, lp["wgate"], dtype, wide)) * _mm(
            h, lp["wup"], dtype, wide)
    return _mm(act, lp["down"], dtype, wide)


def decode_step(
    params: Dict[str, Any],
    cfg: LlamaConfig,
    cache: Dict[str, Any],         # {"k","v"}: per-layer LISTS of
    tokens: jax.Array,             #   [B, L, KV, D] buffers
    positions: jax.Array,          # [B] write position per slot
    attention_impl: str = "xla",
    kernel_interpret: bool = False,
) -> Tuple[jax.Array, Dict[str, Any]]:
    """One decode step for all slots; returns (logits [B, V], cache).

    Implemented as :func:`verify_step` with K=1 so the decode and
    speculative-verify programs are identical by construction — a
    change to one cannot silently break the other's greedy-match
    invariant.  The layer loop stays python-unrolled and qkv / gate+up
    run as single fused matmuls — decode is launch/bandwidth-bound, so
    fewer, larger kernels over unsliced weights is the win (module
    docstring).  ``attention_impl="pallas"`` routes the paged-cache
    attention read through the fused kernel (the K=1 single-query
    path — exactly this function's case).
    """
    logits, cache = verify_step(params, cfg, cache, tokens[:, None],
                                positions,
                                attention_impl=attention_impl,
                                kernel_interpret=kernel_interpret)
    return logits[:, 0, :], cache


def verify_step(
    params: Dict[str, Any],
    cfg: LlamaConfig,
    cache: Dict[str, Any],
    tokens: jax.Array,             # [B, K]: last committed token + K-1 drafts
    positions: jax.Array,          # [B] position of tokens[:, 0]
    slots: Optional[jax.Array] = None,
    logits_index: Optional[jax.Array] = None,
    attention_impl: str = "xla",
    kernel_interpret: bool = False,
) -> Tuple[jax.Array, Dict[str, Any]]:
    """Speculative VERIFY: process K tokens per slot in one dispatch and
    return next-token logits at every position ([B, K, V], cache).

    ``tokens[:, 0]`` is each slot's last committed token (what
    ``decode_step`` would process) and ``tokens[:, 1:]`` are draft
    continuations; ``logits[:, i]`` predicts the token AFTER
    ``tokens[:, i]``, so the caller accepts the longest prefix where
    ``argmax(logits[:, i]) == tokens[:, i+1]`` and takes one bonus token
    from the first mismatch.  Decode is bandwidth-bound (weights stream
    once regardless of K<=8 riding the matmul M-dim), so a verify step
    costs ~one decode step while committing up to K tokens — the
    speculative-decoding trade (beyond-reference capability; the
    reference serves via vLLM, vllm_backend.py:11-24).

    Cache safety on rejection: K entries are written at
    ``positions..positions+K-1``; after accepting ``a`` drafts the
    caller advances the position pointer by ``a+1`` only — entries past
    it are invisible to the ``key <= pos`` mask and get overwritten
    when the sequence actually reaches them.  No rewind needed.

    ``slots`` generalizes the batch dim to a SUBSET of cache slots:
    ``tokens [G, K]`` / ``positions [G]`` operate on cache rows (or
    paged table rows) ``slots [G]`` while the rest of the cache rides
    along untouched — this is the chunked-prefill program (a prompt
    chunk is exactly a draft-free K-token run attending to what the
    previous chunks already cached), so decode, speculative verify and
    chunk prefill stay ONE transformer program by construction.
    ``logits_index [B or G]`` gathers a single time index per row
    before the lm head (returns ``[*, 1, V]``): chunk prefill only
    needs the prompt-final position's logits, and K-1 wasted
    vocab-width matmuls per chunk is exactly the kind of cost a
    bounded prefill chunk exists to avoid.

    ``attention_impl="pallas"`` (paged caches, K=1, full batch only —
    the decode hot path) replaces the gather-then-attend read with the
    fused paged kernel (ops/pallas/paged_attention): blocks stream IN
    PLACE from the pools with dequantization folded inside, so the
    dense (bf16-width) view is never materialized.  Every other shape
    (speculative verify, chunk prefill, slot subsets) keeps the gather
    path; ``kernel_interpret`` runs the kernel in Pallas interpret
    mode (the CPU parity harness).
    """
    dtype = cfg.dtype
    d = cfg.head_dim_
    n_rep = cfg.num_heads // cfg.num_kv_heads
    b, klen = tokens.shape
    x = jnp.take(params["embed"], tokens, axis=0)            # [B, K, E]
    pos_k = positions[:, None] + jnp.arange(klen)[None, :]   # [B, K]
    angles = rope_frequencies(d, cfg.max_seq_len, cfg.rope_theta)[
        pos_k]                                               # [B, K, d/2]

    # paged cache ({"k_pool","v_pool","table"}, quantized pools add
    # {"k_scale","v_scale"}; packed int4 pools are recognized by their
    # half-width code dim) vs dense ({"k","v"}): same transformer
    # loop, different cache plumbing (serving/paged.py)
    paged = "table" in cache
    quant = "k_scale" in cache
    packed4 = (
        quant and cache["k_pool"][0].shape[-1] != d
    )
    use_kernel = (
        paged and attention_impl == "pallas" and klen == 1
        and slots is None and logits_index is None
    )
    if paged:
        from dlrover_tpu.serving.paged import (
            gather_blocks,
            gather_blocks_q,
            gather_blocks_q4,
            scatter_tokens,
            scatter_tokens_q,
            scatter_tokens_q4,
        )

        scatter_q = scatter_tokens_q4 if packed4 else scatter_tokens_q
        gather_q = gather_blocks_q4 if packed4 else gather_blocks_q
        table = cache["table"]
        if slots is not None:
            table = jnp.take(table, slots, axis=0)           # [G, MB]
    if use_kernel:
        from dlrover_tpu.ops.pallas.paged_attention import (
            paged_decode_attention,
        )

        lengths = positions.astype(jnp.int32) + 1

    new_k, new_v = [], []
    new_ks, new_vs = [], []
    for i in range(cfg.num_layers):
        lp = _layer_weights(params["layers"], i)
        h = _rmsnorm(x, lp["input_norm"], cfg.rms_norm_eps).astype(dtype)
        q, k, v = _attn_proj(lp, h, cfg, dtype)
        q = apply_rope(q, angles)
        k = apply_rope(k, angles)
        ck = cv = None
        if paged and quant:
            kp, ksc = scatter_q(
                cache["k_pool"][i], cache["k_scale"][i], table,
                k, positions)
            vp, vsc = scatter_q(
                cache["v_pool"][i], cache["v_scale"][i], table,
                v, positions)
            if use_kernel:
                o = paged_decode_attention(
                    q[:, 0], kp, vp, table, lengths,
                    k_scale=ksc, v_scale=vsc,
                    interpret=kernel_interpret)[:, None]
            else:
                ck = gather_q(kp, ksc, table, dtype)
                cv = gather_q(vp, vsc, table, dtype)
            new_k.append(kp)
            new_v.append(vp)
            new_ks.append(ksc)
            new_vs.append(vsc)
        elif paged:
            kp = scatter_tokens(cache["k_pool"][i], table,
                                k.astype(cache["k_pool"][i].dtype),
                                positions)
            vp = scatter_tokens(cache["v_pool"][i], table,
                                v.astype(cache["v_pool"][i].dtype),
                                positions)
            if use_kernel:
                o = paged_decode_attention(
                    q[:, 0], kp, vp, table, lengths,
                    interpret=kernel_interpret)[:, None]
            else:
                ck = gather_blocks(kp, table)
                cv = gather_blocks(vp, table)
            new_k.append(kp)
            new_v.append(vp)
        elif slots is not None:
            # dense slot-subset write: [G, K] advanced-index scatter
            # (out-of-bounds positions drop, matching the paged trash
            # sink), then gather the G rows back for attention
            ck_full = cache["k"][i].at[slots[:, None], pos_k].set(
                k.astype(cache["k"][i].dtype))
            cv_full = cache["v"][i].at[slots[:, None], pos_k].set(
                v.astype(cache["v"][i].dtype))
            ck = jnp.take(ck_full, slots, axis=0)
            cv = jnp.take(cv_full, slots, axis=0)
            new_k.append(ck_full)
            new_v.append(cv_full)
        else:
            ck = _write_cache(cache["k"][i], k, positions)
            cv = _write_cache(cache["v"][i], v, positions)
            new_k.append(ck)
            new_v.append(cv)
        if not use_kernel:
            o = _attn_verify(q, ck, cv, positions, n_rep)
        o = o.astype(dtype).reshape(b, klen, cfg.num_heads * d)
        x = x + _mm(o, lp["wo"], dtype)
        h = _rmsnorm(x, lp["post_norm"], cfg.rms_norm_eps).astype(dtype)
        x = x + _mlp(lp, h, cfg, dtype)

    x = _rmsnorm(x, params["final_norm"], cfg.rms_norm_eps)
    if logits_index is not None:
        x = jnp.take_along_axis(
            x, logits_index.astype(jnp.int32)[:, None, None], axis=1
        )                                                    # [*, 1, E]
    logits = _lm_head(params, x.astype(dtype), cfg)          # [B, K|1, V]
    if paged and quant:
        out_cache = dict(cache, k_pool=new_k, v_pool=new_v,
                         k_scale=new_ks, v_scale=new_vs)
    elif paged:
        out_cache = dict(cache, k_pool=new_k, v_pool=new_v)
    else:
        out_cache = {"k": new_k, "v": new_v}
    return logits, out_cache


def _attn_verify(
    q: jax.Array,            # [B, K, H, D]
    cache_k: jax.Array,      # [B, L, KV, D]
    cache_v: jax.Array,
    positions: jax.Array,    # [B] position of q[:, 0]
    n_rep: int,
) -> jax.Array:
    """GQA attention for a K-token run against the cache WITHOUT
    materializing the n_rep-expanded cache (a ``jnp.repeat`` would
    stream 4x the cache bytes per step on a 16:4 model — decode is
    bandwidth-bound, so that costs as much as the weight reads).
    Query i may see keys at ``key_pos <= positions + i`` (causal within
    the run, everything committed before it); K=1 is plain decode.
    q folds to [B, K, KV, G, D] and both einsums contract against the
    unexpanded cache; f32 accumulation on the MXU via
    preferred_element_type."""
    b, qlen, h, d = q.shape
    kv = cache_k.shape[2]
    g = h // kv
    qg = q.reshape(b, qlen, kv, g, d)
    scores = jnp.einsum(
        "bqkgd,blkd->bkgql", qg, cache_k,
        preferred_element_type=jnp.float32,
    ) / jnp.sqrt(float(d))
    key_pos = jnp.arange(cache_k.shape[1])
    q_pos = positions[:, None] + jnp.arange(qlen)[None, :]   # [B, K]
    mask = key_pos[None, None, :] <= q_pos[:, :, None]       # [B, K, L]
    scores = jnp.where(
        mask[:, None, None, :, :], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bkgql,blkd->bqkgd", probs.astype(cache_v.dtype), cache_v,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(b, qlen, h, d)


def _lm_head(params, x, cfg: LlamaConfig) -> jax.Array:
    # compute dtype mirrors the training module (models/llama.py lm_head:
    # bf16 matmul; tied path attends in param_dtype) so greedy decode
    # agrees with the trainer's forward down to tie-breaks
    if params.get("lm_head") is None:  # tied embeddings
        logits = x.astype(cfg.param_dtype) @ params["embed"].astype(
            cfg.param_dtype).T
    else:
        logits = _mm(x, params["lm_head"], cfg.dtype)
    logits = logits.astype(jnp.float32)
    if cfg.logit_scale != 1.0:
        logits = logits * cfg.logit_scale
    return logits


def prefill(
    params: Dict[str, Any],
    cfg: LlamaConfig,
    tokens: jax.Array,        # [G, Lp] right-padded prompt bucket(s)
    real_len: jax.Array,      # [G] (or scalar) actual prompt lengths
) -> Tuple[jax.Array, list, list]:
    """Causal pass over a GROUP of same-bucket prompts; returns
    (last_logits [G, V], per-layer k list of [G, Lp, KV, D], v list) —
    the engine scatters the K/V into decode-cache slots.  Rows are
    independent (causal attention never crosses the batch dim), so a
    group of G prompts costs one dispatch instead of G — the admission
    path batches same-bucket arrivals through here.  Pad garbage beyond
    ``real_len`` is harmless: decode overwrites/masks it (module
    docstring)."""
    dtype = cfg.dtype
    d = cfg.head_dim_
    lp_len = tokens.shape[1]
    x = jnp.take(params["embed"], tokens, axis=0)          # [1, Lp, E]
    angles = rope_frequencies(d, cfg.max_seq_len, cfg.rope_theta)[
        jnp.arange(lp_len)]

    ks, vs = [], []
    for i in range(cfg.num_layers):
        lp = _layer_weights(params["layers"], i)
        h = _rmsnorm(x, lp["input_norm"], cfg.rms_norm_eps).astype(dtype)
        q, k, v = _attn_proj(lp, h, cfg, dtype, wide=True)
        q = apply_rope(q, angles)
        k = apply_rope(k, angles)
        o = dot_product_attention(q, k, v, causal=True,
                                  sp_ulysses=False).astype(dtype)
        o = o.reshape(o.shape[0], lp_len, cfg.num_heads * d)
        x = x + _mm(o, lp["wo"], dtype, wide=True)
        h = _rmsnorm(x, lp["post_norm"], cfg.rms_norm_eps).astype(dtype)
        x = x + _mlp(lp, h, cfg, dtype, wide=True)
        ks.append(k)
        vs.append(v)
    x = _rmsnorm(x, params["final_norm"], cfg.rms_norm_eps)
    last_idx = (jnp.atleast_1d(real_len).astype(jnp.int32) - 1)
    last = jnp.take_along_axis(
        x, last_idx[:, None, None].astype(jnp.int32), axis=1
    )                                                     # [G, 1, E]
    logits = _lm_head(params, last.astype(dtype), cfg)[:, 0, :]
    return logits, ks, vs


