"""JAX wiring for KvVariable embeddings: hybrid host/device train step.

Reference counterpart: the TFPlus python layer that plugs KvVariable
gathers into the TF graph (tfplus/kv_variable/python/ops) and the sparse
PS training path of dlrover's L5.  The TPU design splits the step:

  host   : unique(ids) -> KvVariable.lookup -> dense slab [u, dim]
  device : jit( slab[inverse] -> model -> loss; grad w.r.t. slab + dense )
  host   : KvVariable.apply_gradients(unique_ids, slab_grad)

Everything inside jit has static shapes (the slab is padded to a bucket
size so XLA compiles once per bucket, not per batch), keeping the MXU
busy while the hash table stays in host RAM where dynamic vocab belongs.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.sparse.kv_variable import KvVariable


def pad_bucket(n: int, bucket: int = 512) -> int:
    """Round up to a bucket size so jit sees few distinct shapes."""
    if n <= bucket:
        return bucket
    out = bucket
    while out < n:
        out *= 2
    return out


def unique_pad(
    ids: np.ndarray, bucket: int = 512
) -> Tuple[np.ndarray, np.ndarray, int]:
    """np.unique only — returns (unique_ids, inverse, padded_len).

    The *slab* (not the id list) is padded to the bucket size with zero
    rows: padded positions never touch the hash table, so they can't
    inflate frequency/LRU stats of a real id, and they receive zero
    gradient because no batch position maps to them.
    """
    flat = np.ascontiguousarray(ids).reshape(-1)
    uniq, inverse = np.unique(flat, return_inverse=True)
    return (uniq, inverse.reshape(ids.shape).astype(np.int32),
            pad_bucket(len(uniq), bucket))


class KvEmbedding:
    """One embedding feature backed by a KvVariable.

    ``lookup_for_step`` produces the device-ready (slab, inverse) pair;
    after the jitted step returns d(loss)/d(slab), ``apply_slab_grad``
    routes per-row gradients into the native sparse optimizer.
    """

    def __init__(self, var: KvVariable, bucket: int = 512):
        self.var = var
        self.bucket = bucket
        self._pending: Optional[Tuple[np.ndarray, int]] = None

    def lookup_for_step(
        self, ids: np.ndarray, train: bool = True
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        uniq, inverse, padded_len = unique_pad(ids, self.bucket)
        slab = np.zeros((padded_len, self.var.dim), dtype=np.float32)
        if len(uniq):
            slab[: len(uniq)], _ = self.var.lookup(uniq, train=train)
        if train:
            self._pending = (uniq, len(uniq))
        return jnp.asarray(slab), jnp.asarray(inverse)

    def apply_slab_grad(
        self, slab_grad: Any, slab_hessian: Any = None
    ) -> int:
        assert self._pending is not None, "no pending lookup"
        uniq, n = self._pending
        self._pending = None
        if n == 0:
            return 0
        g = np.asarray(slab_grad)[:n]
        hs = None if slab_hessian is None else np.asarray(slab_hessian)[:n]
        return self.var.apply_gradients(uniq, g, hessians=hs)


class SparseTrainStep:
    """Hybrid train step over dense params + named KvEmbedding features.

    ``loss_fn(dense_params, embeddings: {name: [batch..., dim]}, batch)``
    runs under jit; embeddings are device-gathered from the slabs.
    Dense params are updated by the caller-provided optax update fn;
    sparse rows by the native kernels.
    """

    def __init__(
        self,
        loss_fn: Callable[..., jnp.ndarray],
        embeddings: Dict[str, KvEmbedding],
        dense_update: Optional[Callable] = None,
    ):
        from dlrover_tpu.sparse.kv_variable import HESSIAN_OPTIMIZERS

        for name, emb in embeddings.items():
            if emb.var.optimizer in HESSIAN_OPTIMIZERS:
                raise ValueError(
                    f"embedding {name!r} uses {emb.var.optimizer}, which "
                    "needs Hutchinson hessian estimates SparseTrainStep "
                    "does not compute — drive KvEmbedding.apply_slab_grad "
                    "(slab_hessian=...) directly, or pick a first-order "
                    "sparse optimizer"
                )
        self.embeddings = embeddings
        self._dense_update = dense_update
        self._loss_fn = loss_fn
        self._jitted = jax.jit(self._device_step)

    def _device_step(self, dense_params, slabs, inverses, batch):
        def compute(dense, slabs_):
            embs = {
                name: jnp.take(slabs_[name], inverses[name], axis=0)
                for name in slabs_
            }
            return self._loss_fn(dense, embs, batch)

        (loss, dense_grads), slab_grads = _value_and_both_grads(
            compute, dense_params, slabs)
        return loss, dense_grads, slab_grads

    def __call__(self, dense_params, id_batches: Dict[str, np.ndarray],
                 batch: Any):
        """Returns (loss, new_dense_params)."""
        slabs, inverses = {}, {}
        for name, emb in self.embeddings.items():
            slabs[name], inverses[name] = emb.lookup_for_step(
                id_batches[name], train=True)
        loss, dense_grads, slab_grads = self._jitted(
            dense_params, slabs, inverses, batch)
        for name, emb in self.embeddings.items():
            emb.apply_slab_grad(slab_grads[name])
        if self._dense_update is not None:
            dense_params = self._dense_update(dense_params, dense_grads)
        return loss, dense_params


def _value_and_both_grads(fn, dense, slabs):
    """((loss, d/d_dense), d/d_slabs) in one backward pass."""

    def wrapped(d, s):
        return fn(d, s)

    (loss, (dg, sg)) = jax.value_and_grad(wrapped, argnums=(0, 1))(
        dense, slabs)
    return (loss, dg), sg
