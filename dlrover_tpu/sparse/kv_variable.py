"""KvVariable: dynamic-vocabulary embedding variable on the native store.

Parity targets in the reference:
- `KvVariable` core (tfplus/tfplus/kv_variable/kernels/kv_variable.h:88-1021)
  — gather-or-insert/zeros, frequency admission, eviction, full/delta
  export-import, sharded storage;
- op registry (kv_variable/ops/kv_variable_ops.cc:37-560);
- python layer `get_kv_variable` (tfplus/kv_variable/python/ops).

TPU-native shape: the variable lives in host RAM (a C++ striped hash
table); training gathers a dense [n_unique, dim] slab that JAX moves to
the device, and the sparse optimizer applies per-row updates back on the
host.  See :mod:`dlrover_tpu.sparse.embedding` for the JAX wiring.
"""

from __future__ import annotations

import ctypes
import dataclasses
import time
from typing import Dict, Optional, Tuple

import numpy as np

from dlrover_tpu.sparse import native

# slot requirements per optimizer kernel (rows store dim*(1+slots) floats)
OPTIMIZER_SLOTS = {
    "sgd": 0,
    "adagrad": 1,
    "momentum": 1,
    "adam": 2,
    "ftrl": 2,
    "adabelief": 2,
    "group_adam": 2,
    "group_adagrad": 1,
    "adadelta": 2,
    "lamb": 2,
    "amsgrad": 3,
    # hessian / rectified families (reference training_ops.cc: AdaHessian,
    # LambHessian, RectifiedAdam, AdaDQH kernels)
    "adahessian": 2,
    "lamb_hessian": 2,
    "radam": 2,
    "adadqh": 2,
}

# optimizers whose apply_gradients step consumes a Hutchinson
# hessian-diagonal estimate alongside the gradient
HESSIAN_OPTIMIZERS = frozenset({"adahessian", "lamb_hessian"})


def _days_now() -> int:
    return int(time.time() // 86400)


@dataclasses.dataclass
class KvOptimizerConfig:
    """Hyperparameters for the native sparse optimizers (reference
    training_ops.cc kernels)."""

    name: str = "adagrad"
    learning_rate: float = 0.05
    eps: float = 1e-8
    beta1: float = 0.9
    beta2: float = 0.999
    momentum: float = 0.9
    weight_decay: float = 0.0
    ftrl_l1: float = 0.0
    ftrl_l2: float = 0.0
    ftrl_lr_power: float = 0.5
    group_l21: float = 0.0
    adadelta_rho: float = 0.95


class KvVariable:
    """A hash-table embedding variable with optimizer slots.

    Args:
        dim: embedding dimension.
        optimizer: one of OPTIMIZER_SLOTS (decides slot storage).
        init_scale: stddev of the N(0, scale) row init; 0 = zeros.
        min_frequency: admission threshold — ids seen fewer times get a
            zero embedding and no training until admitted (reference
            kv_variable.h:326-352 low-frequency filter).
        seed: init seed; row init is a pure function of (seed, id).
    """

    def __init__(
        self,
        dim: int,
        optimizer: str = "adagrad",
        init_scale: float = 0.01,
        min_frequency: int = 0,
        seed: int = 0,
        opt_config: Optional[KvOptimizerConfig] = None,
    ):
        if optimizer not in OPTIMIZER_SLOTS:
            raise ValueError(f"unknown sparse optimizer: {optimizer}")
        self.dim = dim
        self.optimizer = optimizer
        self.num_slots = OPTIMIZER_SLOTS[optimizer]
        self.stride = dim * (1 + self.num_slots)
        self.opt = opt_config or KvOptimizerConfig(name=optimizer)
        self.opt.name = optimizer
        self._lib = native.load_library()
        self._handle = self._lib.kv_create(
            dim, self.num_slots, seed, float(init_scale), int(min_frequency)
        )
        if not self._handle:
            raise RuntimeError("kv_create failed")
        self._step = 0  # for adam-family bias correction

    def __del__(self):
        try:
            if getattr(self, "_handle", None):
                self._lib.kv_free(self._handle)
                self._handle = None
        except Exception:
            pass

    # -- introspection ----------------------------------------------------
    def __len__(self) -> int:
        return int(self._lib.kv_size(self._handle))

    @property
    def version(self) -> int:
        return int(self._lib.kv_version(self._handle))

    def storage_bytes(self) -> int:
        return int(self._lib.kv_storage_bytes(self._handle))

    def frequencies(self, ids: np.ndarray) -> np.ndarray:
        ids = np.ascontiguousarray(ids, dtype=np.int64)
        out = np.zeros(len(ids), dtype=np.uint32)
        self._lib.kv_frequencies(
            self._handle, native.as_ptr(ids, ctypes.c_int64), len(ids),
            native.as_ptr(out, ctypes.c_uint32))
        return out

    # -- gather -----------------------------------------------------------
    def lookup(
        self, ids: np.ndarray, train: bool = True
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Gather rows for (possibly repeated) ids.

        Returns (values [n, dim] float32, admitted [n] bool).  With
        ``train=True`` unknown ids are inserted and frequencies counted
        (gather-or-insert); otherwise unknown ids read zeros
        (gather-or-zeros) and admitted is all-True heuristically.
        """
        ids = np.ascontiguousarray(ids, dtype=np.int64)
        n = len(ids)
        out = np.empty((n, self.dim), dtype=np.float32)
        if train:
            admitted = np.empty(n, dtype=np.uint8)
            self._lib.kv_gather_or_insert(
                self._handle, native.as_ptr(ids, ctypes.c_int64), n,
                native.as_ptr(out, ctypes.c_float),
                native.as_ptr(admitted, ctypes.c_uint8), _days_now())
            return out, admitted.astype(bool)
        self._lib.kv_gather_or_zeros(
            self._handle, native.as_ptr(ids, ctypes.c_int64), n,
            native.as_ptr(out, ctypes.c_float))
        return out, np.ones(n, dtype=bool)

    # -- scatter ----------------------------------------------------------
    def scatter(
        self, ids: np.ndarray, updates: np.ndarray, op: str = "add"
    ) -> int:
        """Elementwise row update; returns rows actually touched (absent
        or unadmitted ids are skipped)."""
        ops = {"add": 0, "sub": 1, "mul": 2, "div": 3, "assign": 4}
        ids = np.ascontiguousarray(ids, dtype=np.int64)
        updates = np.ascontiguousarray(updates, dtype=np.float32)
        assert updates.shape == (len(ids), self.dim)
        return int(self._lib.kv_scatter(
            self._handle, native.as_ptr(ids, ctypes.c_int64),
            native.as_ptr(updates, ctypes.c_float), len(ids), ops[op]))

    # -- training ---------------------------------------------------------
    def apply_gradients(
        self,
        ids: np.ndarray,
        grads: np.ndarray,
        hessians: Optional[np.ndarray] = None,
    ) -> int:
        """One sparse optimizer step for unique ``ids`` with per-row
        ``grads`` [n, dim].  Rows absent or unadmitted are skipped (their
        forward value was zeros).  Returns rows updated.

        The hessian-family optimizers (:data:`HESSIAN_OPTIMIZERS`) consume
        ``hessians`` — per-row Hutchinson hessian-diagonal estimates of the
        same shape as ``grads`` (reference: tfplus AdaHessian ops take a
        ``hessian`` input tensor).
        """
        ids = np.ascontiguousarray(ids, dtype=np.int64)
        grads = np.ascontiguousarray(grads, dtype=np.float32)
        assert grads.shape == (len(ids), self.dim), grads.shape
        n = len(ids)
        o = self.opt
        if o.name in HESSIAN_OPTIMIZERS and hessians is None:
            raise ValueError(
                f"{o.name} requires hessians (Hutchinson diagonal "
                "estimates) alongside grads"
            )
        if o.name not in HESSIAN_OPTIMIZERS and hessians is not None:
            raise ValueError(f"{o.name} does not take hessians")
        self._step += 1
        lib, h = self._lib, self._handle
        idp = native.as_ptr(ids, ctypes.c_int64)
        gp = native.as_ptr(grads, ctypes.c_float)
        if o.name in HESSIAN_OPTIMIZERS:
            hessians = np.ascontiguousarray(hessians, dtype=np.float32)
            assert hessians.shape == grads.shape, hessians.shape
            hp = native.as_ptr(hessians, ctypes.c_float)
            fn = (lib.kv_apply_adahessian if o.name == "adahessian"
                  else lib.kv_apply_lamb_hessian)
            return int(fn(h, idp, gp, hp, n, o.learning_rate, o.beta1,
                          o.beta2, o.eps, self._step, o.weight_decay))
        if o.name == "sgd":
            # plain scatter-sub of lr*g — no slots
            return self.scatter(ids, o.learning_rate * grads, op="sub")
        if o.name == "adagrad":
            return int(lib.kv_apply_adagrad(h, idp, gp, n, o.learning_rate,
                                            o.eps))
        if o.name == "momentum":
            return int(lib.kv_apply_momentum(h, idp, gp, n, o.learning_rate,
                                             o.momentum))
        if o.name == "adam":
            return int(lib.kv_apply_adam(h, idp, gp, n, o.learning_rate,
                                         o.beta1, o.beta2, o.eps, self._step,
                                         o.weight_decay))
        if o.name == "ftrl":
            return int(lib.kv_apply_ftrl(h, idp, gp, n, o.learning_rate,
                                         o.ftrl_l1, o.ftrl_l2,
                                         o.ftrl_lr_power))
        if o.name == "adabelief":
            return int(lib.kv_apply_adabelief(h, idp, gp, n, o.learning_rate,
                                              o.beta1, o.beta2, o.eps,
                                              self._step))
        if o.name == "group_adagrad":
            return int(lib.kv_apply_group_adagrad(h, idp, gp, n,
                                                  o.learning_rate, o.eps,
                                                  o.group_l21))
        if o.name == "group_adam":
            return int(lib.kv_apply_group_adam(h, idp, gp, n, o.learning_rate,
                                               o.beta1, o.beta2, o.eps,
                                               self._step, o.group_l21))
        if o.name == "amsgrad":
            return int(lib.kv_apply_amsgrad(h, idp, gp, n, o.learning_rate,
                                            o.beta1, o.beta2, o.eps,
                                            self._step, o.weight_decay))
        if o.name == "adadelta":
            return int(lib.kv_apply_adadelta(h, idp, gp, n, o.learning_rate,
                                             o.adadelta_rho, o.eps))
        if o.name == "lamb":
            return int(lib.kv_apply_lamb(h, idp, gp, n, o.learning_rate,
                                         o.beta1, o.beta2, o.eps, self._step,
                                         o.weight_decay))
        if o.name == "radam":
            return int(lib.kv_apply_radam(h, idp, gp, n, o.learning_rate,
                                          o.beta1, o.beta2, o.eps,
                                          self._step, o.weight_decay))
        if o.name == "adadqh":
            return int(lib.kv_apply_adadqh(h, idp, gp, n, o.learning_rate,
                                           o.beta1, o.beta2, o.eps,
                                           self._step, o.weight_decay))
        raise AssertionError(o.name)

    # -- eviction / hybrid storage ---------------------------------------
    def evict(self, min_frequency: int = 0, max_age_days: int = 0) -> int:
        """Drop rows below ``min_frequency`` or idle for more than
        ``max_age_days`` (reference feature eviction)."""
        oldest_day = _days_now() - max_age_days if max_age_days > 0 else 0
        return int(self._lib.kv_evict(self._handle, int(min_frequency),
                                      int(oldest_day)))

    def enable_secondary(self, path: str) -> None:
        """Open the disk tier (hybrid embedding: reference
        hybrid_embedding/table_manager.h).  Cold rows move there via
        :meth:`spill` and fault back in transparently on lookup."""
        rc = self._lib.kv_secondary_open(self._handle, path.encode())
        if rc != 0:
            raise OSError(f"cannot open secondary tier at {path}")

    def spill(self, max_resident_rows: int) -> int:
        """LRU-spill rows to the secondary tier until at most
        ``max_resident_rows`` stay in RAM.  Returns rows spilled."""
        spilled = int(self._lib.kv_spill(self._handle, int(max_resident_rows)))
        if spilled < 0:
            raise OSError("secondary tier not open")
        return spilled

    def secondary_size(self) -> int:
        return int(self._lib.kv_secondary_size(self._handle))

    # -- export / import --------------------------------------------------
    def export(self, since_version: int = 0) -> Dict[str, np.ndarray]:
        """Full (since_version=0) or delta export of rows incl. optimizer
        slots + admission metadata (reference FullOrDeltaExport)."""
        cap = int(self._lib.kv_export_count(self._handle, since_version))
        ids = np.empty(cap, dtype=np.int64)
        values = np.empty((cap, self.stride), dtype=np.float32)
        freqs = np.empty(cap, dtype=np.uint32)
        days = np.empty(cap, dtype=np.uint32)
        versions = np.empty(cap, dtype=np.uint64)
        n = int(self._lib.kv_export(
            self._handle, since_version,
            native.as_ptr(ids, ctypes.c_int64),
            native.as_ptr(values, ctypes.c_float),
            native.as_ptr(freqs, ctypes.c_uint32),
            native.as_ptr(days, ctypes.c_uint32),
            native.as_ptr(versions, ctypes.c_uint64), cap))
        return {
            "ids": ids[:n].copy(),
            "values": values[:n].copy(),
            "freqs": freqs[:n].copy(),
            "days": days[:n].copy(),
            "versions": versions[:n].copy(),
            "step": np.int64(self._step),
        }

    def import_(self, snapshot: Dict[str, np.ndarray]) -> None:
        ids = np.ascontiguousarray(snapshot["ids"], dtype=np.int64)
        values = np.ascontiguousarray(snapshot["values"], dtype=np.float32)
        n = len(ids)
        assert values.shape == (n, self.stride), values.shape
        freqs = np.ascontiguousarray(
            snapshot.get("freqs", np.ones(n)), dtype=np.uint32)
        days = np.ascontiguousarray(
            snapshot.get("days", np.zeros(n)), dtype=np.uint32)
        versions = np.ascontiguousarray(
            snapshot.get("versions", np.zeros(n)), dtype=np.uint64)
        self._lib.kv_import(
            self._handle, native.as_ptr(ids, ctypes.c_int64),
            native.as_ptr(values, ctypes.c_float),
            native.as_ptr(freqs, ctypes.c_uint32),
            native.as_ptr(days, ctypes.c_uint32),
            native.as_ptr(versions, ctypes.c_uint64), n)
        if "step" in snapshot:
            self._step = max(self._step, int(snapshot["step"]))

    def retain_shard(self, shard: int, num_shards: int) -> int:
        """Keep only ids hashing to ``shard`` — elastic resharding after a
        full import (reference sharded export/import)."""
        return int(self._lib.kv_retain_shard(self._handle, shard, num_shards))

    # -- checkpoint through CheckpointStorage -----------------------------
    def save(self, storage, path: str) -> None:
        """Write a full snapshot through a
        :class:`dlrover_tpu.common.storage.CheckpointStorage`."""
        import io

        snap = self.export()
        buf = io.BytesIO()
        np.savez(buf, **snap)
        storage.write(buf.getvalue(), path)

    def restore(self, storage, path: str) -> bool:
        import io

        data = storage.read(path, mode="rb")
        if not data:
            return False
        snap = dict(np.load(io.BytesIO(data)))
        self.import_(snap)
        return True


def get_kv_variable(
    name: str,
    embedding_dim: int,
    registry: Optional[Dict[str, KvVariable]] = None,
    **kwargs,
) -> KvVariable:
    """variable_scope-style accessor (reference python `get_kv_variable`):
    returns the existing variable for ``name`` or creates it."""
    registry = _GLOBAL_REGISTRY if registry is None else registry
    if name in registry:
        var = registry[name]
        if var.dim != embedding_dim:
            raise ValueError(
                f"kv_variable {name} exists with dim={var.dim}, "
                f"requested {embedding_dim}")
        return var
    var = KvVariable(embedding_dim, **kwargs)
    registry[name] = var
    return var


_GLOBAL_REGISTRY: Dict[str, KvVariable] = {}
