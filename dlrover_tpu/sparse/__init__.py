"""Sparse embedding subsystem (TPU-native TFPlus KvVariable counterpart).

Host-RAM dynamic-vocab hash-table embeddings with native C++ kernels,
frequency admission / eviction, hybrid RAM+disk storage, full/delta
export-import, and a hybrid host/device JAX train step.
"""

from dlrover_tpu.sparse.kv_variable import (  # noqa: F401
    KvOptimizerConfig,
    KvVariable,
    get_kv_variable,
)
