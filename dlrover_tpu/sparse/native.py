"""ctypes bindings + on-demand build of the native KvStore library.

The reference builds its KvVariable ops with Bazel against TensorFlow
headers (tfplus/WORKSPACE); here the store is a freestanding C++17
library with a C ABI, compiled once with g++ and loaded via ctypes —
no framework headers, and every call releases the GIL (ctypes does this
for CDLL), so lookups overlap with JAX dispatch.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

from dlrover_tpu.common.log import default_logger as logger

_SRC = os.path.join(os.path.dirname(__file__), "..", "native", "kvstore",
                    "kv_store.cc")
_BUILD_DIR = os.path.join(os.path.dirname(__file__), "..", "native", "_build")

_lib = None
_lib_lock = threading.Lock()


def _build_library(src: str, out: str) -> None:
    os.makedirs(os.path.dirname(out), exist_ok=True)
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        "-o", out, src,
    ]
    logger.info("building kvstore native library: %s", " ".join(cmd))
    subprocess.run(cmd, check=True, capture_output=True, text=True)


def load_library() -> ctypes.CDLL:
    """Load (building if stale) the kvstore shared library."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        so = os.environ.get("DLROVER_KVSTORE_SO")
        if not so:
            src = os.path.abspath(_SRC)
            so = os.path.join(os.path.abspath(_BUILD_DIR), "libkvstore.so")
            if (not os.path.exists(so)
                    or os.path.getmtime(so) < os.path.getmtime(src)):
                try:
                    # dlint: disable=DL007 the lib lock serializes the one-time native build; every holder is this compile-and-load path and must wait for the .so anyway
                    _build_library(src, so)
                except subprocess.CalledProcessError as e:
                    raise RuntimeError(
                        f"kvstore build failed:\n{e.stderr}"
                    ) from e
        lib = ctypes.CDLL(so)
        _declare(lib)
        _lib = lib
        return lib


def _declare(lib: ctypes.CDLL) -> None:
    c = ctypes
    p = c.POINTER
    lib.kv_create.restype = c.c_void_p
    lib.kv_create.argtypes = [c.c_uint32, c.c_uint32, c.c_uint64, c.c_float,
                              c.c_uint32]
    lib.kv_free.argtypes = [c.c_void_p]
    lib.kv_size.restype = c.c_int64
    lib.kv_size.argtypes = [c.c_void_p]
    lib.kv_version.restype = c.c_uint64
    lib.kv_version.argtypes = [c.c_void_p]
    lib.kv_storage_bytes.restype = c.c_uint64
    lib.kv_storage_bytes.argtypes = [c.c_void_p]
    lib.kv_gather_or_insert.argtypes = [
        c.c_void_p, p(c.c_int64), c.c_int64, p(c.c_float), p(c.c_uint8),
        c.c_uint32]
    lib.kv_gather_or_zeros.argtypes = [
        c.c_void_p, p(c.c_int64), c.c_int64, p(c.c_float)]
    lib.kv_frequencies.argtypes = [
        c.c_void_p, p(c.c_int64), c.c_int64, p(c.c_uint32)]
    lib.kv_scatter.restype = c.c_int64
    lib.kv_scatter.argtypes = [
        c.c_void_p, p(c.c_int64), p(c.c_float), c.c_int64, c.c_int]
    lib.kv_apply_adagrad.restype = c.c_int64
    lib.kv_apply_adagrad.argtypes = [
        c.c_void_p, p(c.c_int64), p(c.c_float), c.c_int64, c.c_float,
        c.c_float]
    lib.kv_apply_adam.restype = c.c_int64
    lib.kv_apply_adam.argtypes = [
        c.c_void_p, p(c.c_int64), p(c.c_float), c.c_int64, c.c_float,
        c.c_float, c.c_float, c.c_float, c.c_int64, c.c_float]
    lib.kv_apply_momentum.restype = c.c_int64
    lib.kv_apply_momentum.argtypes = [
        c.c_void_p, p(c.c_int64), p(c.c_float), c.c_int64, c.c_float,
        c.c_float]
    lib.kv_apply_ftrl.restype = c.c_int64
    lib.kv_apply_ftrl.argtypes = [
        c.c_void_p, p(c.c_int64), p(c.c_float), c.c_int64, c.c_float,
        c.c_float, c.c_float, c.c_float]
    lib.kv_apply_adabelief.restype = c.c_int64
    lib.kv_apply_adabelief.argtypes = [
        c.c_void_p, p(c.c_int64), p(c.c_float), c.c_int64, c.c_float,
        c.c_float, c.c_float, c.c_float, c.c_int64]
    lib.kv_apply_group_adam.restype = c.c_int64
    lib.kv_apply_group_adam.argtypes = [
        c.c_void_p, p(c.c_int64), p(c.c_float), c.c_int64, c.c_float,
        c.c_float, c.c_float, c.c_float, c.c_int64, c.c_float]
    lib.kv_apply_amsgrad.restype = c.c_int64
    lib.kv_apply_amsgrad.argtypes = [
        c.c_void_p, p(c.c_int64), p(c.c_float), c.c_int64, c.c_float,
        c.c_float, c.c_float, c.c_float, c.c_int64, c.c_float]
    lib.kv_apply_adadelta.restype = c.c_int64
    lib.kv_apply_adadelta.argtypes = [
        c.c_void_p, p(c.c_int64), p(c.c_float), c.c_int64, c.c_float,
        c.c_float, c.c_float]
    lib.kv_apply_lamb.restype = c.c_int64
    lib.kv_apply_lamb.argtypes = [
        c.c_void_p, p(c.c_int64), p(c.c_float), c.c_int64, c.c_float,
        c.c_float, c.c_float, c.c_float, c.c_int64, c.c_float]
    lib.kv_apply_group_adagrad.restype = c.c_int64
    lib.kv_apply_group_adagrad.argtypes = [
        c.c_void_p, p(c.c_int64), p(c.c_float), c.c_int64, c.c_float,
        c.c_float, c.c_float]
    lib.kv_apply_adahessian.restype = c.c_int64
    lib.kv_apply_adahessian.argtypes = [
        c.c_void_p, p(c.c_int64), p(c.c_float), p(c.c_float), c.c_int64,
        c.c_float, c.c_float, c.c_float, c.c_float, c.c_int64, c.c_float]
    lib.kv_apply_lamb_hessian.restype = c.c_int64
    lib.kv_apply_lamb_hessian.argtypes = [
        c.c_void_p, p(c.c_int64), p(c.c_float), p(c.c_float), c.c_int64,
        c.c_float, c.c_float, c.c_float, c.c_float, c.c_int64, c.c_float]
    lib.kv_apply_radam.restype = c.c_int64
    lib.kv_apply_radam.argtypes = [
        c.c_void_p, p(c.c_int64), p(c.c_float), c.c_int64, c.c_float,
        c.c_float, c.c_float, c.c_float, c.c_int64, c.c_float]
    lib.kv_apply_adadqh.restype = c.c_int64
    lib.kv_apply_adadqh.argtypes = [
        c.c_void_p, p(c.c_int64), p(c.c_float), c.c_int64, c.c_float,
        c.c_float, c.c_float, c.c_float, c.c_int64, c.c_float]
    lib.kv_evict.restype = c.c_int64
    lib.kv_evict.argtypes = [c.c_void_p, c.c_uint32, c.c_uint32]
    lib.kv_secondary_open.restype = c.c_int
    lib.kv_secondary_open.argtypes = [c.c_void_p, c.c_char_p]
    lib.kv_spill.restype = c.c_int64
    lib.kv_spill.argtypes = [c.c_void_p, c.c_int64]
    lib.kv_secondary_size.restype = c.c_int64
    lib.kv_secondary_size.argtypes = [c.c_void_p]
    lib.kv_export_count.restype = c.c_int64
    lib.kv_export_count.argtypes = [c.c_void_p, c.c_uint64]
    lib.kv_export.restype = c.c_int64
    lib.kv_export.argtypes = [
        c.c_void_p, c.c_uint64, p(c.c_int64), p(c.c_float), p(c.c_uint32),
        p(c.c_uint32), p(c.c_uint64), c.c_int64]
    lib.kv_import.argtypes = [
        c.c_void_p, p(c.c_int64), p(c.c_float), p(c.c_uint32), p(c.c_uint32),
        p(c.c_uint64), c.c_int64]
    lib.kv_retain_shard.restype = c.c_int64
    lib.kv_retain_shard.argtypes = [c.c_void_p, c.c_uint32, c.c_uint32]


def as_ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def check_toolchain() -> Optional[str]:
    """Returns None when the native path is usable, else a skip reason."""
    try:
        load_library()
        return None
    except (RuntimeError, OSError, FileNotFoundError) as e:
        return str(e)
