"""ElasticJob controller: the reference Go operator's reconciler in
framework-native form.

Parity targets (reference, Go):
- CRD types ``ElasticJob``/``ReplicaSpec``/``ScalePlan``
  (dlrover/go/operator/api/v1alpha1/elasticjob_types.go:29-127,
  scaleplan_types.go:129);
- the reconciler state machine Created -> Pending -> Running ->
  (Scaling) -> Succeeded/Failed
  (pkg/controllers/elasticjob_controller.go:108-156), which launches
  exactly one job master (pkg/controllers/master/master.go) and realizes
  ScalePlans (scaleplan_controller.go:199);
- fault-pod handling in pkg/controllers/training/task.go:545.

TPU-native shape: the controller is platform-agnostic — it drives any
``Scaler``/``NodeWatcher`` pair (k8s PodScaler/PodWatcher in a cluster,
the in-memory scheduler in tests), so the reconcile logic itself is unit
-testable without a kube-apiserver, and on GKE the schedulable unit is a
TPU pod-slice host.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional

from dlrover_tpu.common.constants import NodeStatus, NodeType
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.node import Node, NodeGroupResource, NodeResource
from dlrover_tpu.master.scaler.base import ScalePlan, Scaler
from dlrover_tpu.master.watcher.base import NodeWatcher


class JobPhase:
    """elasticjob_types.go JobPhase values."""

    CREATED = "Created"
    PENDING = "Pending"
    RUNNING = "Running"
    SCALING = "Scaling"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


@dataclasses.dataclass
class ReplicaSpec:
    """elasticjob_types.go ReplicaSpec: how many nodes of one type and
    their per-node resources + restart budget."""

    replicas: int
    resource: NodeResource = dataclasses.field(default_factory=NodeResource)
    restart_count: int = 3
    priority: str = ""


@dataclasses.dataclass
class ElasticJobSpec:
    job_name: str
    replica_specs: Dict[str, ReplicaSpec]
    distribution_strategy: str = "AllreduceStrategy"
    enable_elastic_scheduling: bool = True


@dataclasses.dataclass
class ElasticJobStatus:
    phase: str = JobPhase.CREATED
    scale_generation: int = 0
    start_time: float = 0.0
    completion_time: float = 0.0
    replica_statuses: Dict[str, Dict[str, int]] = dataclasses.field(
        default_factory=dict
    )


@dataclasses.dataclass
class ElasticJob:
    spec: ElasticJobSpec
    status: ElasticJobStatus = dataclasses.field(
        default_factory=ElasticJobStatus
    )


@dataclasses.dataclass
class ScalePlanCR:
    """scaleplan_types.go ScalePlan: a user/brain-submitted resize."""

    replica_resource_specs: Dict[str, ReplicaSpec]
    created_at: float = dataclasses.field(default_factory=time.time)


class ElasticJobController:
    """Reconcile loop over one job (elasticjob_controller.go:108-156).

    Each ``reconcile()`` observes cluster state through the watcher,
    advances the phase machine, and issues ScalePlans through the
    scaler.  Call it periodically (or after watcher events).
    """

    def __init__(self, job: ElasticJob, scaler: Scaler,
                 watcher: NodeWatcher):
        self.job = job
        self._scaler = scaler
        self._watcher = watcher
        self._relaunch_counts: Dict[tuple, int] = {}
        # pod names already relaunched: k8s deletes asynchronously, so a
        # Failed pod lingers in list() — it must not burn budget twice
        self._handled_failures: set = set()

    # -- observation ------------------------------------------------------
    def _observe(self) -> Dict[str, List[Node]]:
        by_type: Dict[str, List[Node]] = {}
        for node in self._watcher.list():
            by_type.setdefault(node.type, []).append(node)
        return by_type

    def _update_replica_statuses(
        self, observed: Dict[str, List[Node]]
    ) -> None:
        statuses: Dict[str, Dict[str, int]] = {}
        for node_type, nodes in observed.items():
            counts: Dict[str, int] = {}
            for n in nodes:
                counts[n.status] = counts.get(n.status, 0) + 1
            statuses[node_type] = counts
        self.job.status.replica_statuses = statuses

    # -- reconcile --------------------------------------------------------
    def reconcile(self) -> str:
        """One reconcile pass; returns the (possibly new) phase."""
        job = self.job
        observed = self._observe()
        self._update_replica_statuses(observed)
        phase = job.status.phase

        if phase == JobPhase.CREATED:
            # launch the full initial replica set (the Go operator first
            # creates the master pod — here the master IS the process
            # hosting this controller, so only workers are scheduled)
            plan = ScalePlan()
            for node_type, spec in job.spec.replica_specs.items():
                plan.node_group_resources[node_type] = NodeGroupResource(
                    count=spec.replicas, node_resource=spec.resource
                )
            self._scaler.scale(plan)
            job.status.phase = JobPhase.PENDING
            job.status.start_time = time.time()

        elif phase in (JobPhase.PENDING, JobPhase.RUNNING,
                       JobPhase.SCALING):
            # terminal checks apply in EVERY live phase: a fast job can
            # finish (or exhaust its budget) before all replicas were
            # ever simultaneously Running
            if self._job_succeeded(observed):
                job.status.phase = JobPhase.SUCCEEDED
                job.status.completion_time = time.time()
            elif self._job_failed(observed):
                job.status.phase = JobPhase.FAILED
                job.status.completion_time = time.time()
            else:
                self._handle_faults(observed)
                if (phase in (JobPhase.PENDING, JobPhase.SCALING)
                        and self._all_running(observed)):
                    job.status.phase = JobPhase.RUNNING

        if job.status.phase != phase:
            logger.info("job %s: %s -> %s", job.spec.job_name, phase,
                        job.status.phase)
        return job.status.phase

    def apply_scale_plan(self, plan: ScalePlanCR) -> None:
        """User/Brain-submitted resize (scaleplan_controller.go:199)."""
        if not self.job.spec.enable_elastic_scheduling:
            logger.warning("elastic scheduling disabled; plan ignored")
            return
        scale = ScalePlan()
        for node_type, spec in plan.replica_resource_specs.items():
            self.job.spec.replica_specs[node_type] = spec
            scale.node_group_resources[node_type] = NodeGroupResource(
                count=spec.replicas, node_resource=spec.resource
            )
        self._scaler.scale(scale)
        self.job.status.phase = JobPhase.SCALING
        self.job.status.scale_generation += 1

    # -- helpers ---------------------------------------------------------
    def _all_running(self, observed: Dict[str, List[Node]]) -> bool:
        for node_type, spec in self.job.spec.replica_specs.items():
            nodes = observed.get(node_type, [])
            running = [n for n in nodes
                       if n.status == NodeStatus.RUNNING]
            if len(running) < spec.replicas:
                return False
        return True

    def _job_succeeded(self, observed: Dict[str, List[Node]]) -> bool:
        workers = observed.get(NodeType.WORKER, [])
        return bool(workers) and all(
            n.status == NodeStatus.SUCCEEDED for n in workers
        )

    def _job_failed(self, observed: Dict[str, List[Node]]) -> bool:
        spec = self.job.spec.replica_specs.get(NodeType.WORKER)
        if spec is None:
            return False
        for n in observed.get(NodeType.WORKER, []):
            # budget is PER RANK (a relaunched pod has a fresh name but
            # inherits the rank's failure history, training/task.go:545)
            key = (NodeType.WORKER, n.rank_index)
            if (n.status == NodeStatus.FAILED
                    and n.name not in self._handled_failures
                    and self._relaunch_counts.get(key, 0)
                    >= spec.restart_count):
                return True
        return False

    def _handle_faults(self, observed: Dict[str, List[Node]]) -> None:
        """Relaunch failed pods within the per-rank budget
        (training/task.go:545)."""
        plan = ScalePlan()
        for node_type, spec in self.job.spec.replica_specs.items():
            for n in observed.get(node_type, []):
                if (n.status != NodeStatus.FAILED
                        or n.name in self._handled_failures):
                    continue
                key = (node_type, n.rank_index)
                used = self._relaunch_counts.get(key, 0)
                if used >= spec.restart_count:
                    continue
                self._handled_failures.add(n.name)
                self._relaunch_counts[key] = used + 1
                replacement = Node(
                    node_type,
                    n.id + 100000 * (used + 1),
                    rank_index=n.rank_index,
                    config_resource=spec.resource,
                    relaunch_count=used + 1,
                )
                plan.remove_nodes.append(n)
                plan.launch_nodes.append(replacement)
        if not plan.empty():
            self._scaler.scale(plan)
