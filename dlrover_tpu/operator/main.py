"""Operator entrypoint: watch ElasticJob CRs, run one master per job.

The deployable half of the operator (deploy/operator.yaml runs this;
deploy/crds/*.yaml define the resources).  Reference counterparts:
- manager main + reconciler registration
  (dlrover/go/operator/main.go, pkg/controllers/elasticjob_controller.go);
- master-pod creation (pkg/controllers/master/master.go:117 — the
  operator schedules ONE job-master pod per ElasticJob; the master then
  owns worker lifecycle through its own Scaler/Watcher).

Architecture note (matches the reference, differs from a classic
all-in-operator controller): this process does NOT manage worker pods.
It reconciles ElasticJob CRs into (master pod + master service), mirrors
the master pod's phase into the CR status, and relaunches a crashed
master.  Worker scheduling, elasticity, and fault handling live in the
master (dlrover_tpu.master.dist_master + scheduler.k8s.PodScaler).

Testable without a cluster: every k8s interaction goes through the small
``OperatorApi`` surface; tests inject a fake (tests/test_k8s_operator.py).
"""

from __future__ import annotations

import argparse
import time
from typing import Any, Dict, List, Optional

from dlrover_tpu.common.log import default_logger as logger

GROUP = "dlrover-tpu.org"
VERSION = "v1alpha1"
PLURAL = "elasticjobs"
DEFAULT_MASTER_PORT = 22222


def build_master_pod_spec(
    job: Dict[str, Any], namespace: str
) -> Dict[str, Any]:
    """The job-master pod (reference master.go:117 NewMasterTemplateToJob):
    runs ``dlrover-tpu-master --platform k8s`` with the job's identity."""
    name = job["metadata"]["name"]
    spec = job.get("spec", {})
    image = spec.get("image", "dlrover-tpu:latest")
    replica_specs = spec.get("replicaSpecs", {})
    # multi-role jobs (chief/evaluator/ps alongside workers) ride the
    # master's --node_groups spec (reference: ElasticJob replicaSpecs →
    # per-role node groups, dist_job_manager.py:259-316)
    known_roles = ("chief", "worker", "evaluator", "ps")
    unknown = sorted(set(replica_specs) - set(known_roles))
    if unknown:
        # the CRD schema allows any key; forwarding an unknown role
        # would crash-loop the master pod on parse_node_groups
        logger.warning(
            "ElasticJob %s: ignoring unknown replicaSpecs roles %s "
            "(known: %s)", name, unknown, list(known_roles),
        )
    # a PRESENT role without a 'replicas' key takes the conventional
    # k8s default of 1; an explicit 0 (suspended role) stays 0
    replicas = {
        role: int(rs.get("replicas", 1) or 0)
        for role, rs in replica_specs.items()
        if role in known_roles
    }
    zeroed = sorted(role for role, n in replicas.items() if not n)
    if zeroed:
        logger.warning(
            "ElasticJob %s: replicaSpecs roles %s have zero replicas "
            "and are dropped from the node groups", name, zeroed,
        )
    active_roles = {role for role, n in replicas.items() if n}
    extra_roles = ",".join(
        f"{role}:{replicas[role]}" for role in sorted(active_roles)
    )
    res = spec.get("masterResource", {}) or {}
    limits = {
        "cpu": str(res.get("cpu", "2")),
        "memory": str(res.get("memory", "4Gi")),
    }
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": f"{name}-master",
            "namespace": namespace,
            "labels": {
                "dlrover-tpu/job-name": name,
                "dlrover-tpu/node-type": "master",
            },
            "ownerReferences": [_owner_ref(job)],
        },
        "spec": {
            "restartPolicy": "Never",
            "containers": [{
                "name": "master",
                "image": image,
                "command": [
                    "dlrover-tpu-master",
                    "--platform", "k8s",
                    "--job_name", name,
                    "--namespace", namespace,
                    "--port", str(DEFAULT_MASTER_PORT),
                    # node_num counts WORKERS only; a chief+ps-only job
                    # must not size rendezvous for a phantom worker (the
                    # 1-default covers only an empty replicaSpecs = the
                    # legacy single-worker shorthand)
                    "--node_num", str(
                        replicas.get("worker", 0)
                        if replica_specs else 1
                    ),
                    "--worker_image", image,
                    # the CR's k8s uid rides into the master so the
                    # PodScaler can set ownerReferences on worker pods
                    # AND their per-rank Services — k8s GC then reclaims
                    # both when the ElasticJob is deleted
                    "--job_uid", str(job["metadata"].get("uid", "")),
                ] + (
                    ["--node_groups", extra_roles]
                    if extra_roles and active_roles != {"worker"}
                    else []
                ),
                "ports": [{"containerPort": DEFAULT_MASTER_PORT}],
                "resources": {"limits": limits, "requests": dict(limits)},
            }],
        },
    }


def build_master_service_spec(
    job: Dict[str, Any], namespace: str
) -> Dict[str, Any]:
    """Stable DNS name workers dial (reference master.go service)."""
    name = job["metadata"]["name"]
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": f"{name}-master",
            "namespace": namespace,
            "ownerReferences": [_owner_ref(job)],
        },
        "spec": {
            "selector": {
                "dlrover-tpu/job-name": name,
                "dlrover-tpu/node-type": "master",
            },
            "ports": [{
                "port": DEFAULT_MASTER_PORT,
                "targetPort": DEFAULT_MASTER_PORT,
            }],
        },
    }


def _owner_ref(job: Dict[str, Any]) -> Dict[str, Any]:
    """Garbage collection: deleting the ElasticJob deletes its pods."""
    return {
        "apiVersion": f"{GROUP}/{VERSION}",
        "kind": "ElasticJob",
        "name": job["metadata"]["name"],
        "uid": job["metadata"].get("uid", ""),
        "controller": True,
        "blockOwnerDeletion": True,
    }


class OperatorApi:
    """The k8s surface the operator needs (real impl wraps the kubernetes
    client; tests inject a fake)."""

    def __init__(self, core_api: Any, custom_api: Any):
        self._core = core_api
        self._custom = custom_api

    def list_elasticjobs(self, namespace: str) -> List[Dict[str, Any]]:
        if namespace:
            out = self._custom.list_namespaced_custom_object(
                GROUP, VERSION, namespace, PLURAL
            )
        else:
            out = self._custom.list_cluster_custom_object(
                GROUP, VERSION, PLURAL
            )
        return out.get("items", [])

    def patch_status(self, namespace: str, name: str,
                     status: Dict[str, Any]) -> None:
        self._custom.patch_namespaced_custom_object_status(
            GROUP, VERSION, namespace, PLURAL, name, {"status": status}
        )

    def get_pod(self, namespace: str, name: str) -> Optional[Any]:
        try:
            return self._core.read_namespaced_pod(name, namespace)
        except Exception:
            return None

    def create_pod(self, namespace: str, manifest: Dict[str, Any]) -> None:
        self._core.create_namespaced_pod(namespace, manifest)

    def delete_pod(self, namespace: str, name: str) -> None:
        self._core.delete_namespaced_pod(name, namespace)

    def create_service(self, namespace: str,
                       manifest: Dict[str, Any]) -> None:
        try:
            self._core.create_namespaced_service(namespace, manifest)
        except Exception as e:  # already exists across reconciles
            logger.debug("service create: %s", e)


def _pod_phase(pod: Any) -> str:
    status = getattr(pod, "status", None) or (
        pod.get("status", {}) if isinstance(pod, dict) else {}
    )
    phase = getattr(status, "phase", None)
    if phase is None and isinstance(status, dict):
        phase = status.get("phase")
    return phase or "Unknown"


class JobReconciler:
    """ElasticJob CR -> (master pod + service) -> CR status mirror."""

    def __init__(self, api: OperatorApi, max_master_relaunch: int = 2):
        self._api = api
        self._max_relaunch = max_master_relaunch
        self._relaunches: Dict[tuple, int] = {}
        self._seen_keys: set = set()

    def prune_budgets(self) -> None:
        """Drop relaunch budgets for jobs no longer being reconciled.

        Called once per watch pass (after every job in the listing went
        through ``reconcile``): any budget key not seen this pass belongs
        to a deleted job — keeping it would grow ``_relaunches``
        unboundedly on churny namespaces.
        """
        stale = [k for k in self._relaunches if k not in self._seen_keys]
        for k in stale:
            del self._relaunches[k]
        self._seen_keys.clear()

    def reconcile(self, job: Dict[str, Any]) -> str:
        meta = job["metadata"]
        name, ns = meta["name"], meta.get("namespace", "default")
        # budget key includes namespace AND uid: same-named jobs in other
        # namespaces, or a deleted-and-recreated job (fresh uid), must
        # not inherit an exhausted relaunch budget
        budget_key = (ns, name, meta.get("uid", ""))
        self._seen_keys.add(budget_key)
        status = job.get("status") or {}
        phase = status.get("phase", "Created")
        if phase in ("Succeeded", "Failed"):
            return phase
        master = self._api.get_pod(ns, f"{name}-master")
        if master is None:
            self._api.create_service(ns, build_master_service_spec(job, ns))
            self._api.create_pod(ns, build_master_pod_spec(job, ns))
            new_phase = "Pending"
        else:
            pod_phase = _pod_phase(master)
            if pod_phase == "Failed":
                used = self._relaunches.get(budget_key, 0)
                if used < self._max_relaunch:
                    # master crash: relaunch (workers keep running; the
                    # new master resyncs from heartbeats/watch)
                    self._relaunches[budget_key] = used + 1
                    self._api.delete_pod(ns, f"{name}-master")
                    logger.warning(
                        "job %s: master failed; relaunch %d/%d",
                        name, used + 1, self._max_relaunch,
                    )
                    new_phase = "Pending"
                else:
                    new_phase = "Failed"
            elif pod_phase == "Succeeded":
                new_phase = "Succeeded"
            elif pod_phase == "Running":
                new_phase = "Running"
            else:
                new_phase = "Pending"
        if new_phase != phase:
            patch: Dict[str, Any] = {"phase": new_phase}
            if new_phase in ("Succeeded", "Failed"):
                patch["completionTime"] = time.strftime(
                    "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
                )
            self._api.patch_status(ns, name, patch)
            logger.info("job %s: %s -> %s", name, phase, new_phase)
        return new_phase


def run(namespace: str = "", interval: float = 5.0,
        api: Optional[OperatorApi] = None,
        max_iterations: Optional[int] = None) -> None:
    """The controller loop (reference manager main)."""
    if api is None:  # pragma: no cover - needs a cluster
        from kubernetes import client, config

        try:
            config.load_incluster_config()
        except Exception:
            config.load_kube_config()
        api = OperatorApi(client.CoreV1Api(), client.CustomObjectsApi())
    reconciler = JobReconciler(api)
    i = 0
    while max_iterations is None or i < max_iterations:
        i += 1
        try:
            jobs = api.list_elasticjobs(namespace)
        except Exception as e:
            logger.warning("listing ElasticJobs failed: %s", e)
            jobs = []
        for job in jobs:
            try:
                reconciler.reconcile(job)
            except Exception:
                logger.exception(
                    "reconcile of %s failed",
                    job.get("metadata", {}).get("name"),
                )
        if jobs:
            # only prune on a non-empty listing: an empty result may be
            # a transient API failure, not mass deletion
            reconciler.prune_budgets()
        if max_iterations is None or i < max_iterations:
            time.sleep(interval)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--namespace", default="",
                   help="watch one namespace ('' = cluster-wide)")
    p.add_argument("--interval", type=float, default=5.0)
    args = p.parse_args(argv)
    run(namespace=args.namespace, interval=args.interval)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
