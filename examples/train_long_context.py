"""Long-context pretraining example: ring attention over the ``cp`` axis.

Demonstrates the beyond-reference context-parallel path (the reference's
sequence parallelism is Ulysses all-to-all only): a sequence too long
for one chip's HBM shards into contiguous chunks over ``cp``; attention
runs as a balanced zigzag ring (ops/ring_attention.py) with K/V rotating
over ICI, composed here with fsdp for the parameters.

Run on a pod slice (or locally on the virtual CPU mesh):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python examples/train_long_context.py

On real hardware drop the env vars and launch under ``dlrover-tpu-run``
for elastic supervision; scale ``SEQ_LEN``/``cp`` to the slice.
"""

import jax
import jax.numpy as jnp

from dlrover_tpu.accel.accelerate import AccelerateConfig, accelerate
from dlrover_tpu.accel.parallel.mesh import MeshSpec
from dlrover_tpu.models.llama import LlamaConfig, LlamaModel

SEQ_LEN = 2048          # per-example context; scale to 128k+ on a pod
CP = 4                  # ring size: attention memory scales by 1/CP
STEPS = 5


def main() -> None:
    n = len(jax.devices())
    cp = next(c for c in range(min(CP, n), 0, -1) if n % c == 0)
    spec = MeshSpec.for_device_count(n, cp=cp)
    cfg = LlamaConfig.tiny(
        num_heads=4,
        num_kv_heads=4,
        max_seq_len=SEQ_LEN,
        scan_layers=True,
        remat=True,
    )
    batch = max(2, 2 * spec.dp * spec.fsdp)
    res = accelerate(
        LlamaModel(cfg),
        config=AccelerateConfig(mesh_spec=spec),
        batch_shape=(batch, SEQ_LEN),
    )
    state = res.init_fn(jax.random.PRNGKey(0))
    rng = jax.random.PRNGKey(1)
    print(
        f"mesh={spec.dims} seq={SEQ_LEN} batch={batch} "
        f"params={cfg.num_params / 1e6:.1f}M"
    )
    for step in range(STEPS):
        rng, k = jax.random.split(rng)
        ids = jax.random.randint(
            k, (batch, SEQ_LEN), 0, cfg.vocab_size
        ).astype(jnp.int32)
        state, metrics = res.train_step(state, {"input_ids": ids})
        print(f"step {step}: loss={float(metrics['loss']):.4f}")


if __name__ == "__main__":
    main()
