"""Sparse recommender training example: KvVariable embeddings + coworker
data loading + eviction + checkpoint.

Launch:

    python examples/train_recsys.py --steps 200

The sparse-path counterpart of examples/train_llama.py (reference
counterpart: the TFPlus KvVariable + estimator recommender path):

- dynamic-vocabulary user/item embeddings in the native C++ store
  (``dlrover_tpu.sparse``) with frequency admission — ids must be seen
  ``--min-frequency`` times before they earn an embedding row;
- the hybrid host/device step: unique ids -> host gather -> bucket-
  padded dense slab -> jitted forward/backward -> native sparse adagrad;
- a coworker process producing batches through shared memory
  (``ShmDataLoader``) so feature generation never blocks the step;
- periodic eviction of stale ids and a full checkpoint (values +
  optimizer slots + frequencies) through CheckpointStorage.
"""

from __future__ import annotations

import argparse
import os
import tempfile

import numpy as np


def make_batches():
    """Runs in the coworker: synthesize (user, item, label) batches with
    a long-tail id distribution (hapax ids exercise admission).
    Module-level (picklable) so the spawned coworker can import it."""
    rng = np.random.RandomState(0)
    for _ in range(10_000):
        users = (rng.zipf(1.5, 256) % 50_000).astype(np.int64)
        items = (rng.zipf(1.3, 256) % 500_000).astype(np.int64)
        labels = (users % 13 == items % 13).astype(np.float32)
        yield {"user": users, "item": items, "label": labels}


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=200)
    parser.add_argument("--dim", type=int, default=32)
    parser.add_argument("--min-frequency", type=int, default=2)
    parser.add_argument("--ckpt-dir", default="")
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp

    from dlrover_tpu.common.storage import PosixDiskStorage
    from dlrover_tpu.sparse import KvOptimizerConfig, KvVariable
    from dlrover_tpu.sparse.embedding import KvEmbedding, SparseTrainStep
    from dlrover_tpu.trainer.data.shm_dataloader import ShmDataLoader

    users = KvEmbedding(KvVariable(
        args.dim, optimizer="adagrad", init_scale=0.05, seed=1,
        min_frequency=args.min_frequency,
        opt_config=KvOptimizerConfig(learning_rate=0.1)), bucket=512)
    items = KvEmbedding(KvVariable(
        args.dim, optimizer="adagrad", init_scale=0.05, seed=2,
        min_frequency=args.min_frequency,
        opt_config=KvOptimizerConfig(learning_rate=0.1)), bucket=1024)

    def loss_fn(dense, embs, batch):
        logit = jnp.sum(embs["user"] * embs["item"], -1) + dense["bias"]
        label = batch["label"]
        return jnp.mean(
            jnp.maximum(logit, 0) - logit * label
            + jnp.log1p(jnp.exp(-jnp.abs(logit)))
        )

    step = SparseTrainStep(
        loss_fn, {"user": users, "item": items},
        lambda p, g: jax.tree.map(lambda a, b: a - 0.05 * b, p, g))
    dense = {"bias": jnp.zeros(())}

    loader = ShmDataLoader(make_batches, num_slots=4)
    losses = []
    try:
        for i, batch in enumerate(loader):
            if i >= args.steps:
                break
            loss, dense = step(
                dense,
                {"user": batch["user"], "item": batch["item"]},
                {"label": jnp.asarray(batch["label"])},
            )
            losses.append(float(loss))
            if (i + 1) % 50 == 0:
                print(
                    f"step {i + 1}: loss={np.mean(losses[-50:]):.4f} "
                    f"users={len(users.var)} items={len(items.var)} "
                    f"item_bytes={items.var.storage_bytes() >> 20}MiB"
                )
                # stale-id eviction keeps the long tail bounded
                evicted = items.var.evict(min_frequency=2)
                if evicted:
                    print(f"  evicted {evicted} cold item rows")
    finally:
        loader.close()

    ckpt_dir = args.ckpt_dir or tempfile.mkdtemp(prefix="recsys_ckpt_")
    storage = PosixDiskStorage()
    users.var.save(storage, os.path.join(ckpt_dir, "users.npz"))
    items.var.save(storage, os.path.join(ckpt_dir, "items.npz"))
    print(f"checkpoint saved to {ckpt_dir}")
    first, last = np.mean(losses[:20]), np.mean(losses[-20:])
    print(f"loss {first:.4f} -> {last:.4f}")
    return 0 if last < first else 1


if __name__ == "__main__":
    raise SystemExit(main())
