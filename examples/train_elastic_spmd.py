"""Elastic multi-process SPMD training worker.

The proof-of-life script for the framework's central promise: REAL
``jax.distributed`` processes under the elastic agent, surviving node
loss (counterpart of the reference's multi-process elastic runs,
reference: dlrover/python/tests/test_elastic_training_agent.py:51-63 +
elastic_agent/torch/training.py:577-728 — there torchelastic worlds,
here one jax.distributed process group whose GSPMD collectives span
processes).

Launch under two agents (two simulated hosts):

    dlrover-tpu-run --nnodes=1:2 --node_rank=0 ... \
        python examples/train_elastic_spmd.py --steps 12 ...

Strategy: dp spans hosts (one DCN replica per host, ``dcn_dp``), fsdp
spans the host's local chips — so each host owns a complete copy of the
fsdp-sharded state and the in-memory flash checkpoint of any SINGLE
surviving host can restore the whole model after a peer host dies.

Determinism: the batch consumed at global step k is a pure function of
k, so a run that is killed and resumed must reproduce the loss
trajectory of an uninterrupted run step for step.
"""

from __future__ import annotations

import argparse
import os


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=12)
    p.add_argument("--global-batch", type=int, default=8)
    p.add_argument("--micro-batch", type=int, default=1)
    p.add_argument("--seq-len", type=int, default=32)
    p.add_argument("--ckpt-dir", default="/tmp/dlrover_tpu_spmd_ckpt")
    p.add_argument("--metrics-file", default="")
    p.add_argument("--step-sleep", type=float, default=0.0,
                   help="host-side pause per step (elasticity tests: "
                        "keeps tiny runs alive long enough to observe "
                        "membership changes)")
    p.add_argument("--crash-at-step", type=int, default=0,
                   help="inject one worker crash after this step (fault-"
                        "tolerance e2e; needs --crash-marker)")
    p.add_argument("--crash-marker", default="",
                   help="file recording that the injected crash fired "
                        "(so the restarted worker does not re-crash)")
    p.add_argument("--slice-unit", type=int, default=0,
                   help="hosts per (emulated) TPU slice: when the world "
                        "holds more than one complete slice, train on a "
                        "hybrid DCN mesh — dp replicas over slices, fsdp "
                        "inside each slice (MeshSpec.hybrid)")
    args = p.parse_args()

    # The test harness emulates hosts with virtual CPU devices; the env
    # var alone loses to an eagerly-registered TPU plugin, so force via
    # config before any backend is initialized.
    if os.environ.get("DLROVER_FORCE_CPU"):
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dlrover_tpu.accel.parallel.mesh import MeshSpec
    from dlrover_tpu.models.llama import LlamaConfig, LlamaModel
    from dlrover_tpu.trainer.elastic.distributed import init_distributed
    from dlrover_tpu.trainer.elastic.trainer import ElasticTrainer

    env = init_distributed()

    def spec_for(devices):
        """dp over hosts/slices (DCN) x fsdp inside (ICI)."""
        procs = len({d.process_index for d in devices})
        unit = args.slice_unit
        if unit and procs % unit == 0:
            # whole slices present: one dp replica per slice, fsdp spans
            # the slice's hosts (the slice-loss e2e re-enters here with
            # fewer slices after the master drops an incomplete one;
            # n_slices == 1 is the single-surviving-slice world)
            n_slices = procs // unit
            return MeshSpec.hybrid(n_slices, len(devices) // n_slices)
        if procs > 1:
            per = len(devices) // procs
            return MeshSpec(dp=procs, fsdp=per, dcn_dp=procs)
        return MeshSpec(fsdp=len(devices))

    # fp32 so the trajectory is comparable across world sizes at tight
    # tolerance (bf16 reduction-order noise would mask a real regression)
    cfg = LlamaConfig.tiny(max_seq_len=args.seq_len, dtype=jnp.float32)
    trainer = ElasticTrainer(
        LlamaModel(cfg),
        global_batch_size=args.global_batch,
        micro_batch_per_shard=args.micro_batch,
        seq_len=args.seq_len,
        checkpoint_dir=args.ckpt_dir,
        mesh_spec_fn=spec_for,
        save_memory_interval=1,
        save_storage_interval=10**9,  # memory tier only: the point here
    )
    trainer.prepare(devices=jax.devices())
    start = trainer.restore_or_init(jax.random.PRNGKey(0))
    paths = dict(trainer._ckpt.engine.restore_path_counts)
    if start > 0:
        # restore-path-taken assertion (VERDICT r4 #5c): a resume must
        # come from a KNOWN tier, and on the CPU test backend the
        # in-memory tier is the copy path BY DESIGN (device_put aliases
        # host memory on CPU; zero-copy is the TPU-backend fast path)
        assert sum(paths.values()) > 0, (
            "resumed without any restore path recorded")
        expect = ("copy", "partial", "storage") \
            if jax.default_backend() == "cpu" else ("zero_copy", "partial")
        assert any(paths[k] for k in expect), (paths, expect)
    print(
        f"[spmd] rank={env.worker_rank}/{env.worker_num} "
        f"devices={jax.device_count()} start_step={start} "
        f"restore_paths={paths}",
        flush=True,
    )

    out = None
    if args.metrics_file:
        out = open(f"{args.metrics_file}.r{env.node_rank}", "a")

    step = start
    while step < args.steps:
        rng = np.random.RandomState(1000 + step)
        batch = rng.randint(
            0, cfg.vocab_size, size=(args.global_batch, args.seq_len)
        ).astype(np.int32)
        metrics = trainer.train_step(batch)
        step = trainer.step
        loss = float(metrics["loss"])
        if out is not None:
            out.write(f"{step} {loss:.6f} {env.worker_num}\n")
            out.flush()
        trainer.maybe_save()
        if (
            args.crash_at_step
            and step == args.crash_at_step
            and args.crash_marker
            and not os.path.exists(args.crash_marker)
        ):
            open(args.crash_marker, "w").close()
            print("[spmd] injected crash", flush=True)
            os._exit(17)
        if args.step_sleep:
            import time

            time.sleep(args.step_sleep)
    print(f"[spmd] done at step {step}", flush=True)
    # Explicit distributed shutdown WHILE ranks are still in collective
    # lockstep (just finished the same step): the shutdown barrier
    # passes immediately.  Leaving it to interpreter atexit lets
    # per-host teardown skew exceed the barrier window on a loaded box
    # — the fast rank then exits, the coordination service declares the
    # job dead, every worker aborts with rc 1, and the agents "recover"
    # a job that already finished.
    from dlrover_tpu.trainer.elastic.distributed import (
        shutdown_distributed,
    )

    shutdown_distributed()
    trainer.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
