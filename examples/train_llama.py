"""End-to-end elastic Llama pretraining example.

Launch (standalone, spawns a local master):

    dlrover-tpu-run --nnodes=1 python examples/train_llama.py \
        --steps 50 --ckpt-dir /tmp/ckpt

Everything the framework offers in one script (the counterpart of the
reference's examples/pytorch/mnist + llama2 examples):

- ``init_distributed()``: env contract -> jax.distributed;
- master-driven data sharding (``IndexShardingClient``): a dead worker's
  unconsumed shards are re-dispatched by the master;
- ``ElasticTrainer``: mesh for the current world, fixed global batch via
  grad accumulation, flash-checkpoint restore on (re)start;
- flash checkpoint cadence: shm every step, async disk persist;
- global-step reports feeding the master's SpeedMonitor.

Chaos knob: ``DLROVER_CRASH_AT_STEP`` makes the worker kill itself once at
that step — the elastic agent restarts it and training resumes from the
in-memory checkpoint (what the reference's chaosblade experiments verify,
reference: docs/tech_report/fault_tolerance_exps.md).
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np


def synth_tokens(index: int, seq_len: int, vocab: int) -> np.ndarray:
    """Deterministic synthetic sample: the data a shard index denotes is
    identical across restarts and world sizes."""
    rng = np.random.RandomState(7 + index)
    return rng.randint(0, vocab, size=(seq_len,)).astype(np.int32)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--global-batch", type=int, default=8)
    p.add_argument("--micro-batch", type=int, default=1)
    p.add_argument("--seq-len", type=int, default=64)
    p.add_argument("--ckpt-dir", default="/tmp/dlrover_tpu_example_ckpt")
    p.add_argument("--out-file", default="")
    p.add_argument("--save-storage-interval", type=int, default=10)
    args = p.parse_args()

    import jax

    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.agent.sharding.client import IndexShardingClient
    from dlrover_tpu.common.constants import NodeEnv
    from dlrover_tpu.models.llama import LlamaConfig, LlamaModel
    from dlrover_tpu.trainer.elastic.distributed import init_distributed
    from dlrover_tpu.trainer.elastic.trainer import ElasticTrainer

    env = init_distributed()
    cfg = LlamaConfig.tiny(max_seq_len=args.seq_len)
    model = LlamaModel(cfg)

    trainer = ElasticTrainer(
        model,
        global_batch_size=args.global_batch,
        micro_batch_per_shard=args.micro_batch,
        seq_len=args.seq_len,
        checkpoint_dir=args.ckpt_dir,
        save_memory_interval=1,
        save_storage_interval=args.save_storage_interval,
    )
    trainer.prepare(devices=jax.devices())
    start_step = trainer.restore_or_init(jax.random.PRNGKey(0))
    print(f"[train] starting from step {start_step}", flush=True)

    master_addr = os.getenv(NodeEnv.MASTER_ADDR, "")
    client = sharding = None
    if master_addr:
        client = MasterClient(
            master_addr, node_id=env.node_rank, node_type="worker"
        )
        dataset_size = args.steps * args.global_batch
        sharding = IndexShardingClient(
            client,
            dataset_name="synth",
            batch_size=args.global_batch,
            num_epochs=1,
            # only the first boot creates the dataset; restarts re-attach
            dataset_size=dataset_size if start_step == 0 else 0,
            num_minibatches_per_shard=1,
        )

    crash_at = int(os.getenv("DLROVER_CRASH_AT_STEP", "0"))
    losses = []
    step = start_step
    while step < args.steps:
        if sharding is not None:
            indices = sharding.fetch_batch_indices(args.global_batch)
            if not indices:
                print("[train] dataset exhausted", flush=True)
                break
        else:
            base = step * args.global_batch
            indices = list(range(base, base + args.global_batch))
        batch = np.stack(
            [synth_tokens(i, args.seq_len, cfg.vocab_size) for i in indices]
        )
        metrics = trainer.train_step(batch)
        step = trainer.step
        loss = float(metrics["loss"])
        losses.append((step, loss))
        # sharded runs BLOCK on the shm commit: the ack below must
        # follow a DURABLE save — with the async double-buffered engine
        # a staged-but-uncommitted save would let a crash resume one
        # step behind the acked shard stream (redoing a step on the
        # NEXT shard's data and finishing a step short)
        trainer.maybe_save(block=sharding is not None)
        if sharding is not None:
            # ack AFTER the step + checkpoint: a crash in between makes
            # the master re-dispatch the shard instead of skipping it
            sharding.report_batch_done(len(indices))
        if client is not None:
            try:
                client.report_global_step(step, time.time())
            except Exception:
                pass  # a local master may exit once the dataset completes
        if crash_at and step == crash_at and start_step == 0:
            print(f"[train] simulated crash at step {step}", flush=True)
            os._exit(23)

    if args.out_file:
        with open(args.out_file, "w") as f:
            json.dump(
                {
                    "start_step": start_step,
                    "final_step": step,
                    "losses": losses,
                },
                f,
            )
    print(f"[train] done at step {step}", flush=True)
    trainer.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
