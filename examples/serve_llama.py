"""Serve a Llama-family model with the continuous-batching engine.

The user-facing half of the serving story (reference counterpart: the
vLLM inference backend the reference's RL stack deploys,
atorch/atorch/rl/inference_backend/vllm_backend.py:11-24): load weights
(HF checkpoint or random init), build an :class:`InferenceEngine`, and
stream concurrent requests through it.

What this demonstrates:
- loading an HF checkpoint into serving layout (``--hf path``), or a
  random-init model for a smoke run;
- ``--int8``: weights pre-quantized ONCE into the Pallas kernel layout —
  decode streams int8 from HBM, prefill runs the MXU's native int8 dot
  (both measured >= bf16 on v5e; PERF.md serving notes);
- continuous batching: requests of different lengths admitted into
  slots as they free up, same-bucket bursts prefilled in one dispatch.

Run::

    python examples/serve_llama.py --requests 16 --int8
"""

from __future__ import annotations

import argparse
import time

import numpy as np


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--hf", default="",
                   help="HF checkpoint path (empty = random tiny model)")
    p.add_argument("--requests", type=int, default=8)
    p.add_argument("--prompt-len", type=int, default=32)
    p.add_argument("--max-new", type=int, default=32)
    p.add_argument("--slots", type=int, default=8)
    p.add_argument("--int8", action="store_true")
    p.add_argument("--temperature", type=float, default=0.8)
    p.add_argument("--speculative", type=int, default=0,
                   help="speculative_k (greedy only; forces temperature 0)")
    p.add_argument("--top-k", type=int, default=40)
    args = p.parse_args()

    import jax

    from dlrover_tpu.serving.engine import InferenceEngine

    if args.hf:
        from dlrover_tpu.models.convert import load_hf_llama

        cfg, params = load_hf_llama(args.hf, scan_layers=False)
        variables = {"params": params}
    else:
        import jax.numpy as jnp

        from dlrover_tpu.models.llama import LlamaConfig, LlamaModel

        cfg = LlamaConfig.tiny(max_seq_len=256, scan_layers=False)
        model = LlamaModel(cfg)
        variables = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
        )

    engine = InferenceEngine(
        cfg, variables,
        max_slots=args.slots,
        int8=args.int8,
        temperature=0.0 if args.speculative else args.temperature,
        top_k=0 if args.speculative else args.top_k,
        speculative_k=args.speculative,
    )
    rng = np.random.RandomState(0)
    rids = [
        engine.add_request(
            rng.randint(1, cfg.vocab_size, size=args.prompt_len),
            args.max_new,
        )
        for _ in range(args.requests)
    ]
    t0 = time.perf_counter()
    outputs = engine.run()
    wall = time.perf_counter() - t0

    stats = engine.stats
    total = sum(len(outputs[r]) for r in rids)
    print(f"requests={len(rids)} generated={total} tokens "
          f"wall={wall:.2f}s ({total / wall:.0f} tok/s)")
    print(f"prefill: {stats.prefill_calls} dispatches "
          f"{stats.prefill_seconds:.2f}s; decode {stats.decode_seconds:.2f}s "
          f"({stats.decode_tokens_per_sec:.0f} tok/s device loop)")
    if args.speculative:
        print(f"speculative: accepted {stats.spec_accepted}/"
              f"{stats.spec_proposed} drafts")
    print("first outputs:", {r: outputs[r][:8].tolist() for r in rids[:2]})


if __name__ == "__main__":
    main()
