"""Supervised fine-tuning (SFT) with prompt-masked loss — stage 1 of the
RLHF pipeline (SFT -> reward model -> PPO).

Parity target: the reference's instruction-tuning entry point (atorch's
HF-Trainer-shaped fine-tuning path, atorch_trainer.py; its RL examples
assume an SFT'd actor).  The stages after this one live in
``dlrover_tpu.rl``: :class:`~dlrover_tpu.rl.reward.RewardModelTrainer`
(preference pairs) and :class:`~dlrover_tpu.rl.ppo_trainer.PPOTrainer`.

What this demonstrates:
- ``loss_mask``: the loss is computed on RESPONSE tokens only — prompt
  positions contribute nothing (the standard SFT recipe; the fused
  chunked loss honors the mask identically, accelerate.py loss path);
- the high-level :class:`~dlrover_tpu.trainer.trainer.Trainer` with a
  warmup+cosine schedule built from ``TrainingArguments``;
- starting from an HF checkpoint: swap ``LlamaConfig.tiny`` +
  random-init for ``models.convert.load_hf_llama`` and pass ``params``.

Run::

    python examples/train_sft.py --steps 30
"""

from __future__ import annotations

import argparse

import numpy as np


def build_example(rng: np.random.RandomState, seq_len: int, vocab: int):
    """One synthetic instruction pair: [prompt || response || pad].

    The 'task' is learnable: the response repeats the prompt's first
    token (so a trained model measurably beats an untrained one).
    Returns (input_ids [T], loss_mask [T]) with mask=1 on response
    positions only.
    """
    if seq_len < 10:
        raise SystemExit("--seq-len must be >= 10 (prompt + response)")
    prompt_len = rng.randint(4, seq_len // 2)
    resp_len = rng.randint(2, seq_len - prompt_len)
    prompt = rng.randint(2, vocab, size=(prompt_len,))
    response = np.full((resp_len,), prompt[0])
    ids = np.zeros((seq_len,), np.int32)
    ids[:prompt_len] = prompt
    ids[prompt_len:prompt_len + resp_len] = response
    mask = np.zeros((seq_len,), np.float32)
    # next-token loss at position t scores token t+1: response tokens
    # t+1 in [prompt_len, prompt_len+resp_len) are scored by positions
    # [prompt_len-1, ...); the Trainer's loss shifts labels internally,
    # so the mask marks the RESPONSE TOKEN positions themselves.
    mask[prompt_len:prompt_len + resp_len] = 1.0
    return ids, mask


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--global-batch", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=64)
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--lora", action="store_true",
                   help="LoRA fine-tuning: frozen base, rank-8 adapters, "
                        "adapter-only optimizer states (reference: atorch "
                        "FSDP+LoRA via peft)")
    p.add_argument("--lora-rank", type=int, default=8)
    args = p.parse_args()

    import jax

    from dlrover_tpu.models.llama import LlamaConfig, LlamaModel
    from dlrover_tpu.trainer.trainer import Trainer, TrainingArguments

    cfg = LlamaConfig.tiny(max_seq_len=args.seq_len,
                           vocab_size=args.vocab)
    rng = np.random.RandomState(0)

    def batches():
        for _ in range(args.steps):
            ids, masks = zip(*[
                build_example(rng, args.seq_len, cfg.vocab_size)
                for _ in range(args.global_batch)
            ])
            yield {
                "input_ids": np.stack(ids),
                "loss_mask": np.stack(masks),
            }

    model = LlamaModel(cfg)
    targs = TrainingArguments(
        max_steps=args.steps,
        logging_steps=max(1, args.steps // 5),
        learning_rate=3e-3,
        warmup_ratio=0.1,
        lr_scheduler_type="cosine",
        weight_decay=0.01,
    )
    extra = {}
    if args.lora:
        from dlrover_tpu.accel.lora import (
            LoRAConfig,
            LoRAModel,
            lora_optimizer,
        )

        lcfg = LoRAConfig(rank=args.lora_rank)
        model = LoRAModel(model, lcfg)
        inner, _ = targs.make_optimizer(args.steps)
        extra["optimizer"] = lora_optimizer(inner)
    trainer = Trainer(
        model,
        targs,
        list(batches()),
        global_batch_size=args.global_batch,
        micro_batch_per_shard=args.global_batch // max(
            1, len(jax.devices())
        ) or 1,
        seq_len=args.seq_len,
        **extra,
    )
    out = trainer.train()
    train_logs = [l for l in trainer.log_history if "loss" in l]
    first, last = train_logs[0]["loss"], train_logs[-1]["loss"]
    mode = "lora" if args.lora else "full"
    print(
        f"[sft:{mode}] loss {first:.3f} -> {last:.3f} over "
        f"{out.global_step} steps (masked to response tokens)"
    )
    if args.lora:
        from dlrover_tpu.accel.lora import adapter_nbytes, base_nbytes

        state = trainer.elastic.state
        print(
            f"[sft:lora] adapters "
            f"{adapter_nbytes(state.params) / 2**20:.2f} MiB vs base "
            f"{base_nbytes(state.params) / 2**20:.2f} MiB; merged "
            f"export via dlrover_tpu.accel.lora.lora_export"
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
