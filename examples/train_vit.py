"""Train a ViT classifier under accelerate() — the vision family on the
same machinery as the LM families.

What this demonstrates:
- ``model_input_key="pixel_values"``: non-token inputs trace init and
  shard per-leaf (leading batch axis) through the same mesh/rule stack;
- a custom classification loss (the default loss is a next-token LM
  loss and is refused for non-token models);
- the reshape-patchify patch embedding keeping the FLOPs on the MXU.

Run::

    python examples/train_vit.py --steps 20
"""

from __future__ import annotations

import argparse

import numpy as np


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=20)  # must be >= 1
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--image-size", type=int, default=64)
    p.add_argument("--classes", type=int, default=10)
    args = p.parse_args()
    if args.steps < 1:
        raise SystemExit("--steps must be >= 1")

    import jax
    import jax.numpy as jnp
    import optax

    from dlrover_tpu.accel.accelerate import AccelerateConfig, accelerate
    from dlrover_tpu.accel.parallel.mesh import MeshSpec
    from dlrover_tpu.models.vit import ViTConfig, ViTModel

    cfg = ViTConfig(
        image_size=args.image_size, patch_size=8, hidden_size=256,
        num_layers=4, num_heads=8, intermediate_size=1024,
        num_classes=args.classes,
    )
    model = ViTModel(cfg)

    def loss_fn(params, batch):
        logits = model.apply({"params": params}, batch["pixel_values"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["labels"]
        ).mean()
        return loss, {"weight": jnp.float32(batch["labels"].shape[0])}

    example = {
        "pixel_values": np.zeros(
            (args.batch, 3, args.image_size, args.image_size), np.float32
        ),
        "labels": np.zeros((args.batch,), np.int32),
    }
    res = accelerate(
        model,
        config=AccelerateConfig(
            mesh_spec=MeshSpec.for_device_count(len(jax.devices()))
        ),
        example_batch=example,
        loss_fn=loss_fn,
        model_input_key="pixel_values",
    )
    state = res.init_fn(jax.random.PRNGKey(0))

    # synthetic labeled images: class k = noise centered at k (learnable)
    rng = np.random.RandomState(0)
    labels = rng.randint(0, args.classes, size=args.batch).astype(np.int32)
    pixels = (
        rng.randn(args.batch, 3, args.image_size, args.image_size)
        + labels[:, None, None, None] / args.classes
    ).astype(np.float32)

    first = last = None
    for step in range(args.steps):
        state, metrics = res.train_step(
            state, {"pixel_values": pixels, "labels": labels}
        )
        loss = float(metrics["loss"])
        first = first if first is not None else loss
        last = loss
        if step % 5 == 0:
            print(f"step {step}: loss {loss:.4f}")
    print(f"[vit] loss {first:.3f} -> {last:.3f} over {args.steps} steps")
    raise SystemExit(0 if last < first else 1)


if __name__ == "__main__":
    main()
