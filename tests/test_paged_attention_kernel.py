"""Parity tests for the fused Pallas paged-attention decode kernel
against a from-scratch numpy oracle (interpret mode on the CPU mesh).
These predate the PR-14 rewrite (multi-page double-buffered DMA,
in-kernel dequant, ``attention_impl`` auto-pick) and deliberately keep
the independent numpy reference; the rewrite's quantized-pool and
engine-integration parity lives in tests/test_paged_kernel.py."""

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.ops.pallas.paged_attention import paged_decode_attention


def _setup(B=3, H=8, KV=2, D=128, bs=16, MB=5, seed=0):
    rng = np.random.RandomState(seed)
    NB = B * MB + 1
    q = jnp.asarray(rng.randn(B, H, D).astype(np.float32) * 0.3)
    k_pool = jnp.asarray(rng.randn(NB, bs, KV, D).astype(np.float32) * 0.3)
    v_pool = jnp.asarray(rng.randn(NB, bs, KV, D).astype(np.float32) * 0.3)
    table = np.zeros((B, MB), np.int32)
    bid = 1
    for b in range(B):
        for j in range(MB):
            table[b, j] = bid
            bid += 1
    return q, k_pool, v_pool, jnp.asarray(table)


def _reference(q, k_pool, v_pool, table, lengths):
    B, H, D = q.shape
    KV = k_pool.shape[2]
    g = H // KV
    outs = []
    for b in range(B):
        kb = np.concatenate(
            [np.asarray(k_pool[int(table[b, j])])
             for j in range(table.shape[1])], 0)
        vb = np.concatenate(
            [np.asarray(v_pool[int(table[b, j])])
             for j in range(table.shape[1])], 0)
        o = np.zeros((H, D), np.float32)
        for h in range(H):
            kvh = h // g
            s = (np.asarray(q[b, h]) @ kb[:, kvh].T) / np.sqrt(D)
            s[int(lengths[b]):] = -1e30
            p = np.exp(s - s.max())
            p /= p.sum()
            o[h] = p @ vb[:, kvh]
        outs.append(o)
    return np.stack(outs)


def test_paged_decode_attention_parity():
    q, k_pool, v_pool, table = _setup()
    lengths = jnp.asarray(np.array([33, 80, 1], np.int32))
    out = paged_decode_attention(
        q, k_pool, v_pool, table, lengths, interpret=True)
    ref = _reference(q, k_pool, v_pool, table, lengths)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5)


def test_paged_decode_attention_mha_and_full_blocks():
    # MHA (KV == H) and lengths on exact block boundaries
    q, k_pool, v_pool, table = _setup(B=2, H=4, KV=4, MB=3, seed=1)
    lengths = jnp.asarray(np.array([48, 16], np.int32))
    out = paged_decode_attention(
        q, k_pool, v_pool, table, lengths, interpret=True)
    ref = _reference(q, k_pool, v_pool, table, lengths)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5)
