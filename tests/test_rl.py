"""PPO/RL tests (reference parity: atorch/atorch/rl/ppo_utils/ppo_util.py
loss/GAE/rewards math, replay_buffer, trainer/ppo_trainer.py loop)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.models.llama import LlamaConfig, LlamaModel
from dlrover_tpu.rl.config import AdaptiveKLController, PPOConfig
from dlrover_tpu.rl.generation import sample_sequences
from dlrover_tpu.rl.ppo_trainer import PPOTrainer, ValueModel
from dlrover_tpu.rl.ppo_utils import (
    gae_advantages,
    logprobs_from_logits,
    ppo_loss,
    shape_rewards,
)
from dlrover_tpu.rl.replay_buffer import Experience, ReplayBuffer


def test_logprobs_from_logits_matches_manual():
    logits = jnp.asarray(np.random.RandomState(0).randn(2, 3, 5))
    labels = jnp.asarray([[1, 2, 0], [4, 4, 3]])
    lp = logprobs_from_logits(logits, labels)
    ref = jax.nn.log_softmax(logits, axis=-1)
    for b in range(2):
        for t in range(3):
            assert lp[b, t] == pytest.approx(
                float(ref[b, t, int(labels[b, t])]), abs=1e-5)


def test_shape_rewards_places_score_on_last_response_token():
    B, T = 2, 6
    lp = jnp.zeros((B, T))
    ref_lp = jnp.zeros((B, T))
    mask = jnp.asarray([[0, 0, 1, 1, 1, 0], [0, 0, 0, 1, 1, 1]])
    scores = jnp.asarray([2.0, -1.0])
    rewards, mean_kl = shape_rewards(scores, lp, ref_lp, mask, kl_coef=0.1)
    assert float(mean_kl) == 0.0
    assert float(rewards[0, 4]) == pytest.approx(2.0)
    assert float(rewards[1, 5]) == pytest.approx(-1.0)
    assert float(jnp.abs(rewards).sum()) == pytest.approx(3.0)


def test_shape_rewards_kl_penalty_sign():
    B, T = 1, 4
    mask = jnp.asarray([[0, 1, 1, 1]])
    lp = jnp.full((B, T), -1.0)
    ref_lp = jnp.full((B, T), -2.0)  # policy MORE confident than ref
    rewards, mean_kl = shape_rewards(
        jnp.zeros(B), lp, ref_lp, mask, kl_coef=0.5)
    assert float(mean_kl) == pytest.approx(1.0)  # (−1) − (−2)
    # positive KL ⇒ negative dense reward on non-terminal tokens
    assert float(rewards[0, 1]) == pytest.approx(-0.5)


def test_gae_matches_numpy_reference():
    rng = np.random.RandomState(3)
    B, T = 2, 8
    values = rng.randn(B, T).astype(np.float32)
    rewards = rng.randn(B, T).astype(np.float32)
    mask = np.zeros((B, T), np.float32)
    mask[0, 3:] = 1.0   # runs to the end of the buffer
    mask[1, 2:6] = 1.0  # EOS-truncated: no bootstrap past position 5
    gamma, lam = 0.99, 0.9
    adv, ret = gae_advantages(
        jnp.asarray(values), jnp.asarray(rewards), jnp.asarray(mask),
        gamma=gamma, lam=lam, whiten=False)

    # plain numpy reverse recursion over the response region
    adv_ref = np.zeros((B, T), np.float32)
    for b in range(B):
        running = 0.0
        for t in reversed(range(T)):
            if mask[b, t] == 0:
                running = 0.0
                continue
            next_v = values[b, t + 1] if t + 1 < T and mask[b, t + 1] else 0.0
            delta = rewards[b, t] + gamma * next_v - values[b, t]
            running = delta + gamma * lam * running
            adv_ref[b, t] = running
    np.testing.assert_allclose(np.asarray(adv), adv_ref, rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(ret), adv_ref + values * mask, rtol=1e-4, atol=1e-5)


def test_ppo_loss_clipping_and_stats():
    B, T = 1, 4
    mask = jnp.ones((B, T))
    old_lp = jnp.zeros((B, T))
    adv = jnp.ones((B, T))
    ret = jnp.zeros((B, T))
    vals = jnp.zeros((B, T))
    # ratio e^1 ≈ 2.7 — far outside the clip window
    loss_big, stats = ppo_loss(
        jnp.ones((B, T)), vals, old_lp, vals, adv, ret, mask,
        clip_ratio=0.2)
    assert stats["clipfrac"] == pytest.approx(1.0)
    # clipped surrogate: positive advantage + clipped ratio => -1.2 * adv
    assert float(stats["policy_loss"]) == pytest.approx(-1.2, abs=1e-5)


def test_adaptive_kl_controller_moves_toward_target():
    ctl = AdaptiveKLController(init_kl_coef=0.2, target=6.0, horizon=100)
    v0 = ctl.value
    ctl.update(current_kl=60.0, n_steps=10)   # way above target -> grow
    assert ctl.value > v0
    ctl2 = AdaptiveKLController(init_kl_coef=0.2, target=6.0, horizon=100)
    ctl2.update(current_kl=0.1, n_steps=10)   # below target -> shrink
    assert ctl2.value < 0.2


def test_replay_buffer_equal_minibatches():
    buf = ReplayBuffer()
    mk = lambda n: Experience(*[np.arange(n * 4).reshape(n, 4).astype(
        np.float32) for _ in range(6)])
    buf.add(mk(5))
    buf.add(mk(5))
    assert len(buf) == 10
    mbs = list(buf.minibatches(4, np.random.RandomState(0)))
    sizes = {len(m["tokens"]) for m in mbs}
    assert sizes == {2}  # equal sizes, remainder dropped
    assert len(mbs) == 5


def test_sample_sequences_greedy_and_shapes():
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    model = LlamaModel(cfg)
    prompts = jnp.asarray(np.full((2, 4), 5, np.int32))
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 12), jnp.int32))
    toks, mask = sample_sequences(
        model.apply, params, prompts, max_new_tokens=8,
        rng=jax.random.PRNGKey(1), temperature=0.0)
    assert toks.shape == (2, 12) and mask.shape == (2, 12)
    np.testing.assert_array_equal(np.asarray(toks[:, :4]), 5)
    np.testing.assert_array_equal(np.asarray(mask[:, :4]), 0)
    np.testing.assert_array_equal(np.asarray(mask[:, 4:]), 1)
    # greedy decode is deterministic
    toks2, _ = sample_sequences(
        model.apply, params, prompts, max_new_tokens=8,
        rng=jax.random.PRNGKey(99), temperature=0.0)
    np.testing.assert_array_equal(np.asarray(toks), np.asarray(toks2))


def test_ppo_increases_reward():
    """E2E: reward = +1 per generated target token; a few PPO iterations
    must raise the mean score (the policy learns to emit the token)."""
    cfg = LlamaConfig.tiny(dtype=jnp.float32, vocab_size=64)
    actor = LlamaModel(cfg)
    critic = ValueModel(trunk=LlamaModel(cfg))
    target = 7

    def reward_fn(tokens, mask):
        hits = ((tokens == target) * mask).sum(axis=1)
        return hits.astype(np.float32) / mask.sum(axis=1).clip(1)

    ppo = PPOTrainer(
        actor, critic,
        PPOConfig(max_new_tokens=8, temperature=1.0, kl_coef=0.01,
                  ppo_epochs=2, minibatches=2, learning_rate=5e-3),
        seed=0,
    )
    prompts = np.full((8, 4), 3, np.int32)
    ppo.init_models(prompts)
    scores = []
    for _ in range(6):
        stats = ppo.step(prompts, reward_fn)
        scores.append(stats["mean_score"])
    early = np.mean(scores[:2])
    late = np.mean(scores[-2:])
    assert late > early + 0.1, scores


def test_cached_decode_matches_full_recompute():
    """KV-cache decode must produce EXACTLY the same greedy tokens as the
    full-recompute sampler (same model, same prompts)."""
    from dlrover_tpu.rl.generation import sample_sequences_cached

    cfg = LlamaConfig.tiny(dtype=jnp.float32, scan_layers=False,
                           remat=False, vocab_size=64)
    model = LlamaModel(cfg)
    prompts = jnp.asarray(np.random.RandomState(0).randint(
        1, 60, (2, 5)).astype(np.int32))
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 16), jnp.int32))
    full, mask_full = sample_sequences(
        model.apply, variables, prompts, max_new_tokens=9,
        rng=jax.random.PRNGKey(7), temperature=0.0)
    cached, mask_cached = sample_sequences_cached(
        model, variables, prompts, max_new_tokens=9,
        rng=jax.random.PRNGKey(7), temperature=0.0)
    np.testing.assert_array_equal(np.asarray(full), np.asarray(cached))
    np.testing.assert_array_equal(np.asarray(mask_full),
                                  np.asarray(mask_cached))


def test_cached_decode_rejects_scan_models():
    from dlrover_tpu.rl.generation import sample_sequences_cached

    cfg = LlamaConfig.tiny(dtype=jnp.float32, scan_layers=True)
    model = LlamaModel(cfg)
    prompts = jnp.zeros((1, 4), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32))
    with pytest.raises(NotImplementedError, match="scan_layers"):
        sample_sequences_cached(model, variables, prompts, 4,
                                jax.random.PRNGKey(0))


def test_ppo_rollout_with_kv_cache():
    cfg = LlamaConfig.tiny(dtype=jnp.float32, vocab_size=64,
                           scan_layers=False, remat=False)
    ppo = PPOTrainer(
        LlamaModel(cfg), ValueModel(trunk=LlamaModel(cfg)),
        PPOConfig(max_new_tokens=6, ppo_epochs=1, minibatches=2,
                  use_kv_cache=True),
        seed=3,
    )
    prompts = np.full((4, 4), 2, np.int32)
    ppo.init_models(prompts)
    stats = ppo.step(prompts, lambda t, m: np.ones(len(t), np.float32))
    assert np.isfinite(stats["loss"])


def test_top_p_sampling_masks_tail():
    """top_p keeps the nucleus: with a peaked distribution and small
    top_p only the argmax can be sampled; top_p=1 can sample others."""
    from dlrover_tpu.rl.generation import select_token

    logits = jnp.asarray([[4.0, 3.9, -8.0, -9.0, -10.0]])
    keys = [jax.random.PRNGKey(i) for i in range(30)]
    picks_narrow = {
        int(select_token(logits, k, 1.0, 0, top_p=0.4)[0]) for k in keys
    }
    assert picks_narrow == {0}, picks_narrow
    picks_wide = {
        int(select_token(logits, k, 1.0, 0, top_p=0.95)[0]) for k in keys
    }
    assert 1 in picks_wide and picks_wide <= {0, 1}, picks_wide


def test_rl_model_engine_per_role_shardings():
    """Actor and critic run under DIFFERENT shardings (reference
    model_engine.py:35 per-model strategies) and sampled (non-greedy)
    rollouts train end to end."""
    from dlrover_tpu.accel.parallel.mesh import MeshSpec
    from dlrover_tpu.rl.model_engine import RLModelEngine

    cfg = LlamaConfig.tiny(dtype=jnp.float32, vocab_size=64)
    actor = LlamaModel(cfg)
    critic = ValueModel(trunk=LlamaModel(cfg))
    engine = RLModelEngine(
        {
            "actor": MeshSpec(dp=2, tp=2, fsdp=2),   # tp-sharded policy
            "critic": MeshSpec(fsdp=8),              # pure-ZeRO critic
            "ref": MeshSpec(dp=8),                   # replicated frozen ref
        },
        devices=jax.devices()[:8],
    )
    trainer = PPOTrainer(
        actor, critic,
        PPOConfig(max_new_tokens=6, temperature=0.8, top_p=0.9,
                  ppo_epochs=1, minibatches=2, learning_rate=1e-3),
        engine=engine,
    )
    prompts = np.tile(np.arange(4, dtype=np.int32), (4, 2))  # [4, 8]
    trainer.init_models(prompts)

    # the roles' leaves really carry different shardings
    actor_leaf = jax.tree_util.tree_leaves(trainer.params["actor"])[1]
    critic_leaf = jax.tree_util.tree_leaves(trainer.params["critic"])[1]
    # actor mesh really tensor-parallel, critic mesh really pure-ZeRO
    assert actor_leaf.sharding.mesh.shape["tp"] == 2
    assert critic_leaf.sharding.mesh.shape["tp"] == 1
    assert critic_leaf.sharding.mesh.shape["fsdp"] == 8
    # and at least one actor param is actually SPLIT over tp while the
    # same-mesh-axis split cannot exist on the critic
    def tp_split(p):
        sh = p.sharding
        return any(
            "tp" in ((e,) if isinstance(e, str) else (e or ()))
            and p.shape[i] > p.sharding.shard_shape(p.shape)[i]
            for i, e in enumerate(sh.spec)
        )
    assert any(
        tp_split(p)
        for p in jax.tree_util.tree_leaves(trainer.params["actor"])
    )
    ref_leaf = jax.tree_util.tree_leaves(trainer.ref_params)[1]
    assert ref_leaf.sharding.mesh.shape["dp"] == 8  # replicated ref

    def reward_fn(tokens, mask):
        # reward emitting token id 3
        return (tokens * (mask > 0)).astype(np.float32).max(1) / 63.0

    stats = trainer.step(prompts, reward_fn)
    assert np.isfinite(stats["loss"])
    # rollouts were sampled, not greedy: two different rngs give
    # different tokens somewhere (smoke check via a second experience)
    m1 = trainer.make_experience(prompts, reward_fn)
    assert np.isfinite(m1["mean_score"])


def test_reward_model_learns_preferences_and_feeds_ppo():
    """RM training (reference reward-model role): Bradley-Terry pairwise
    loss separates chosen from rejected, and the trained RM plugs into
    PPO's reward_fn."""
    from dlrover_tpu.rl.ppo_trainer import ValueModel
    from dlrover_tpu.rl.reward import (
        RewardModelTrainer,
        last_token_reward,
        make_reward_fn,
    )

    # last_token_reward picks the last valid position
    scores = jnp.asarray([[1.0, 2.0, 3.0, 4.0], [5.0, 6.0, 7.0, 8.0]])
    mask = jnp.asarray([[1, 1, 1, 0], [1, 1, 1, 1]])
    np.testing.assert_allclose(
        np.asarray(last_token_reward(scores, mask)), [3.0, 8.0]
    )

    cfg = LlamaConfig.tiny(dtype=jnp.float32, vocab_size=64)
    rm = RewardModelTrainer(ValueModel(trunk=LlamaModel(cfg)),
                            learning_rate=5e-4)
    T = 16
    rm.init(T)

    # synthetic preference: "chosen" = sequences of high token ids
    rng = np.random.RandomState(0)

    def batch():
        chosen = rng.randint(40, 64, size=(8, T)).astype(np.int32)
        rejected = rng.randint(0, 24, size=(8, T)).astype(np.int32)
        mask = np.ones((8, T), np.int32)
        return {"chosen": chosen, "rejected": rejected,
                "chosen_mask": mask, "rejected_mask": mask}

    first = rm.train_step(batch())
    for _ in range(25):
        stats = rm.train_step(batch())
    assert stats["loss"] < first["loss"]
    assert stats["accuracy"] >= 0.9, stats

    # held-out pairs rank correctly
    probe = batch()
    r_chosen = rm.score(probe["chosen"], probe["chosen_mask"])
    r_rejected = rm.score(probe["rejected"], probe["rejected_mask"])
    assert (r_chosen > r_rejected).mean() >= 0.9

    # and the adapter satisfies PPO's reward_fn contract
    fn = make_reward_fn(rm)
    out = fn(probe["chosen"], probe["chosen_mask"])
    assert out.shape == (8,) and np.isfinite(out).all()


def test_ppo_with_serving_backend():
    """Rollouts through the continuous-batching serving engine
    (reference vllm_backend split): one full PPO iteration trains, and
    the engine re-syncs actor weights between iterations."""
    from dlrover_tpu.rl.inference_backend import ServingBackend

    cfg = LlamaConfig.tiny(dtype=jnp.float32, vocab_size=64,
                           scan_layers=False, remat=False)
    backend = ServingBackend(cfg, max_slots=2, chunk=4, temperature=1.0,
                             top_k=8, seed=7)
    ppo = PPOTrainer(
        LlamaModel(cfg), ValueModel(trunk=LlamaModel(cfg)),
        PPOConfig(max_new_tokens=6, ppo_epochs=1, minibatches=2),
        seed=3,
        inference_backend=backend,
    )
    prompts = np.full((4, 4), 2, np.int32)
    ppo.init_models(prompts)
    stats = ppo.step(prompts, lambda t, m: np.ones(len(t), np.float32))
    assert np.isfinite(stats["loss"])
    assert backend.stats.generated_tokens > 0
    # second iteration exercises the weight re-sync path
    stats2 = ppo.step(prompts, lambda t, m: np.ones(len(t), np.float32))
    assert np.isfinite(stats2["loss"])


def test_dpo_learns_preferences_without_reward_model():
    """DPO (beyond-reference: the reference alignment stack is PPO-only)
    raises the chosen sequences' likelihood margin over rejected ones
    against the frozen SFT reference, with rising implicit-reward
    accuracy — no RM in the loop."""
    from dlrover_tpu.rl.dpo import DPOTrainer, dpo_loss, sequence_logprobs

    # unit sanity: the closed-form pieces
    pol_c = jnp.asarray([2.0, 1.0])
    pol_r = jnp.asarray([0.0, 0.5])
    loss, stats = dpo_loss(pol_c, pol_r, pol_c * 0, pol_r * 0, beta=1.0)
    assert float(stats["accuracy"]) == 1.0
    assert float(stats["margin"]) > 0
    # degenerate: policy == reference -> margin 0, loss log 2
    loss0, _ = dpo_loss(pol_c, pol_r, pol_c, pol_r, beta=1.0)
    np.testing.assert_allclose(float(loss0), np.log(2.0), rtol=1e-5)

    # masked sequence logprobs ignore prompt positions
    logits = jnp.zeros((1, 4, 8))
    tokens = jnp.asarray([[1, 2, 3, 4]])
    full = sequence_logprobs(logits, tokens, jnp.ones((1, 4)))
    half = sequence_logprobs(
        logits, tokens, jnp.asarray([[0, 0, 1, 1]])
    )
    np.testing.assert_allclose(float(full[0]), 3 * np.log(1 / 8), rtol=1e-5)
    np.testing.assert_allclose(float(half[0]), 2 * np.log(1 / 8), rtol=1e-5)

    cfg = LlamaConfig.tiny(dtype=jnp.float32, vocab_size=64)
    trainer = DPOTrainer(LlamaModel(cfg), beta=0.5, learning_rate=5e-4)
    T = 16
    trainer.init(T)

    rng = np.random.RandomState(0)

    def batch():
        # preference: continuations of high token ids beat low ones;
        # shared prompt region (first 4 tokens) is masked out
        prompt = rng.randint(0, 64, size=(8, 4)).astype(np.int32)
        chosen = np.concatenate(
            [prompt, rng.randint(40, 64, size=(8, T - 4))], axis=1
        ).astype(np.int32)
        rejected = np.concatenate(
            [prompt, rng.randint(0, 24, size=(8, T - 4))], axis=1
        ).astype(np.int32)
        mask = np.concatenate(
            [np.zeros((8, 4), np.int32), np.ones((8, T - 4), np.int32)],
            axis=1,
        )
        return {"chosen": chosen, "rejected": rejected,
                "chosen_mask": mask, "rejected_mask": mask}

    first = trainer.train_step(batch())
    np.testing.assert_allclose(first["margin"], 0.0, atol=1e-4)  # ref = init
    for _ in range(30):
        stats = trainer.train_step(batch())
    assert stats["loss"] < first["loss"]
    assert stats["accuracy"] >= 0.9, stats
    assert stats["margin"] > 0
    assert stats["chosen_reward"] > stats["rejected_reward"]


def test_dpo_composes_with_lora_adapters():
    """DPO over a LoRAModel: only the adapters move (base frozen), and
    preferences are still learned — the parameter-efficient preference
    stage (LoRA SFT -> LoRA DPO)."""
    import optax

    from dlrover_tpu.accel.lora import LoRAConfig, LoRAModel, lora_optimizer
    from dlrover_tpu.rl.dpo import DPOTrainer

    cfg = LlamaConfig.tiny(dtype=jnp.float32, vocab_size=64,
                           scan_layers=False)
    lora = LoRAModel(LlamaModel(cfg), LoRAConfig(rank=4))
    trainer = DPOTrainer(
        lora, beta=0.5,
        optimizer=lora_optimizer(optax.adam(1e-3)),
    )
    T = 16
    trainer.init(T)
    import flax.linen as nn

    base_before = jax.tree_util.tree_map(
        np.asarray, nn.meta.unbox(trainer.params["params"]["base"])
    )

    rng = np.random.RandomState(0)

    def batch():
        prompt = rng.randint(0, 64, size=(8, 4)).astype(np.int32)
        chosen = np.concatenate(
            [prompt, rng.randint(40, 64, size=(8, T - 4))], axis=1
        ).astype(np.int32)
        rejected = np.concatenate(
            [prompt, rng.randint(0, 24, size=(8, T - 4))], axis=1
        ).astype(np.int32)
        mask = np.concatenate(
            [np.zeros((8, 4), np.int32), np.ones((8, T - 4), np.int32)],
            axis=1,
        )
        return {"chosen": chosen, "rejected": rejected,
                "chosen_mask": mask, "rejected_mask": mask}

    first = trainer.train_step(batch())
    for _ in range(30):
        stats = trainer.train_step(batch())
    assert stats["loss"] < first["loss"]
    assert stats["margin"] > 0

    base_after = nn.meta.unbox(trainer.params["params"]["base"])
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
        base_after, base_before,
    )  # frozen base untouched
