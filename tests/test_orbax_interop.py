"""Orbax checkpoint interop (SURVEY §7 step 5): flash checkpoints open
with ``orbax.checkpoint`` and Orbax checkpoints resume flash training."""

import os
import uuid

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.agent.ckpt_saver import AsyncCheckpointSaver
from dlrover_tpu.trainer.flash_checkpoint import (
    Checkpointer,
    SaverMode,
    StorageType,
)
from dlrover_tpu.trainer.flash_checkpoint.orbax_interop import (
    export_flash_to_orbax,
    export_to_orbax,
    import_from_orbax,
    restore_from_orbax,
)


@pytest.fixture(autouse=True)
def _isolate(monkeypatch):
    job = uuid.uuid4().hex[:8]
    monkeypatch.setenv("DLROVER_JOB_UID", job)
    yield
    AsyncCheckpointSaver.reset()
    for f in os.listdir("/dev/shm"):
        if job in f:
            try:
                os.unlink(os.path.join("/dev/shm", f))
            except OSError:
                pass


def _state():
    return {
        "params": {
            "dense": {"kernel": np.arange(12, dtype=np.float32).reshape(3, 4),
                      "bias": np.ones(4, np.float32)},
        },
        "opt_state": {"mu": np.full((3, 4), 0.5, np.float32)},
        "step": np.int32(7),
    }


def test_flash_to_orbax_roundtrip(tmp_path):
    """A flash checkpoint exported to Orbax loads via orbax.checkpoint
    with identical values."""
    import orbax.checkpoint as ocp

    ckpt = Checkpointer(str(tmp_path / "flash"), saver_mode=SaverMode.LOCAL)
    state = _state()
    assert ckpt.save_checkpoint(5, state, StorageType.DISK)
    ckpt.wait_latest_checkpoint(30)

    orbax_dir = str(tmp_path / "orbax" / "step_5")
    step = export_flash_to_orbax(ckpt.engine, orbax_dir)
    assert step == 5

    with ocp.PyTreeCheckpointer() as c:
        tree = c.restore(orbax_dir)
    np.testing.assert_array_equal(
        tree["params"]["dense"]["kernel"],
        state["params"]["dense"]["kernel"],
    )
    np.testing.assert_array_equal(
        tree["opt_state"]["mu"], state["opt_state"]["mu"]
    )
    assert int(np.asarray(tree["step"])) == 7
    ckpt.close()


def test_orbax_to_flash_restore(tmp_path):
    """A checkpoint written by plain orbax (any JAX framework) restores
    into a sharded target via restore_from_orbax."""
    import orbax.checkpoint as ocp

    state = _state()
    orbax_dir = str(tmp_path / "external" / "step_12")
    with ocp.PyTreeCheckpointer() as c:
        c.save(orbax_dir, state)

    mesh = jax.sharding.Mesh(np.array(jax.devices()[:4]).reshape(4), ("fsdp",))
    sh = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec(None, "fsdp")
    )
    repl = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
    target = jax.tree_util.tree_map(np.zeros_like, state)
    shardings = jax.tree_util.tree_map(lambda _: repl, state)
    shardings["params"]["dense"]["kernel"] = sh

    step, restored = restore_from_orbax(orbax_dir, target, shardings)
    assert step == 12
    np.testing.assert_array_equal(
        np.asarray(restored["params"]["dense"]["kernel"]),
        state["params"]["dense"]["kernel"],
    )
    k = restored["params"]["dense"]["kernel"]
    assert isinstance(k, jax.Array) and k.sharding.spec == sh.spec
    np.testing.assert_array_equal(
        np.asarray(restored["opt_state"]["mu"]), state["opt_state"]["mu"]
    )


def test_export_live_pytree_and_flat(tmp_path):
    """export_to_orbax accepts both live pytrees and the flash engine's
    flat path->array dicts."""
    flat = {"a/b": np.ones(3, np.float32), "a/c": np.zeros(2, np.int32),
            "d": np.float32(2.5)}
    p1 = str(tmp_path / "o1")
    export_to_orbax(p1, flat)
    back = import_from_orbax(p1)
    assert set(back) == {"a/b", "a/c", "d"}
    np.testing.assert_array_equal(back["a/b"], flat["a/b"])

    p2 = str(tmp_path / "o2")
    export_to_orbax(p2, _state())
    nested = import_from_orbax(p2, flat=False)
    assert int(np.asarray(nested["step"])) == 7
