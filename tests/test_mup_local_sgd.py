"""muP and Local SGD tests (reference parity: atorch/atorch/mup/
optim.py MuAdam width-transfer, atorch/atorch/local_sgd reduce methods +
outer optimizer)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.accel.local_sgd import (
    LocalSGD,
    LocalSGDConfig,
    build_local_sgd_step,
    gta_merge,
    linear_merge,
    sparsify_merge,
)
from dlrover_tpu.accel.mup import (
    EMBED,
    HIDDEN,
    OUTPUT,
    VECTOR,
    MupConfig,
    apply_mup_init,
    classify_param,
    label_tree,
    make_mup_model_config,
    mu_adam,
)
from dlrover_tpu.models.llama import LlamaConfig, LlamaModel

# ---------------------------------------------------------------- muP


BASE_WIDTH = 64  # LlamaConfig.tiny()'s hidden size IS the proxy width


def _init_model(width: int):
    cfg = make_mup_model_config(
        LlamaConfig.tiny(dtype=jnp.float32, scan_layers=False),
        width=width, base_width=BASE_WIDTH,
    )
    model = LlamaModel(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    return model, cfg, params


def test_classify_param_roles():
    _, _, params = _init_model(64)
    labels = label_tree(params)
    flat = jax.tree_util.tree_flatten_with_path(labels)[0]
    roles = {"/".join(str(getattr(k, "key", k)) for k in path): v
             for path, v in flat}
    assert any(v == EMBED for k, v in roles.items()
               if "embed_tokens" in k)
    assert any(v == OUTPUT for k, v in roles.items() if "lm_head" in k)
    assert any(v == VECTOR for k, v in roles.items() if "norm" in k)
    assert any(v == HIDDEN for k, v in roles.items()
               if "mlp" in k or "gate" in k or "proj" in k)


def test_mup_config_scaling():
    mup = MupConfig(base_width=32, width=128)
    assert mup.width_mult == 4.0
    assert mup.logit_scale == 0.25
    cfg = make_mup_model_config(
        LlamaConfig.tiny(scan_layers=False), width=128, base_width=64)
    assert cfg.hidden_size == 128
    assert cfg.logit_scale == 1.0  # absorbed convention: no multiplier
    assert cfg.intermediate_size == 256  # scaled by the same ratio


def test_apply_mup_init_rescales_output_only():
    _, _, params = _init_model(64)
    mup = MupConfig(base_width=16, width=64)  # m=4 -> output / sqrt(4)
    scaled = apply_mup_init(params, mup)
    flat_a = jax.tree_util.tree_flatten_with_path(params)[0]
    flat_b = {tuple(p): v for p, v in
              jax.tree_util.tree_flatten_with_path(scaled)[0]}
    for path, before in flat_a:
        after = flat_b[tuple(path)]
        joined = "/".join(str(getattr(k, "key", k)) for k in path)
        if "lm_head" in joined:
            np.testing.assert_allclose(
                np.asarray(after), np.asarray(before) / 2.0, rtol=1e-6)
        else:
            np.testing.assert_array_equal(
                np.asarray(after), np.asarray(before))


def _logit_update_norm(width: int, base_lr: float, use_mup: bool) -> float:
    """Mean |Δlogits| after one adam step — the coordinate-check probe."""
    model, cfg, params = _init_model(width)
    mup = MupConfig(base_width=BASE_WIDTH, width=width)
    if use_mup:
        params = apply_mup_init(params, mup)
        opt = mu_adam(base_lr, mup)
    else:
        import optax

        opt = optax.adam(base_lr)
    batch = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (4, 8)),
        jnp.int32)

    def loss_fn(p):
        logits = model.apply(p, batch)
        onehot = jax.nn.one_hot(batch, cfg.vocab_size)
        return -(jax.nn.log_softmax(logits) * onehot).sum(-1).mean()

    state = opt.init(params)
    grads = jax.grad(loss_fn)(params)
    updates, _ = opt.update(grads, state, params)
    new_params = jax.tree.map(lambda a, b: a + b, params, updates)
    before = model.apply(params, batch)
    after = model.apply(new_params, batch)
    return float(jnp.abs(after - before).mean())


def test_mup_coordinate_check_width_invariance():
    """Under muP the per-step logit movement stays O(1) across widths;
    under standard adam it drifts with width (the motivation for muP
    LR transfer, reference optim.py MuAdam)."""
    lr = 1e-2
    narrow = _logit_update_norm(64, lr, use_mup=True)
    wide = _logit_update_norm(256, lr, use_mup=True)
    ratio_mup = wide / narrow
    narrow_sp = _logit_update_norm(64, lr, use_mup=False)
    wide_sp = _logit_update_norm(256, lr, use_mup=False)
    ratio_sp = wide_sp / narrow_sp
    # muP ratio must stay near 1 and be markedly flatter than standard
    assert 0.3 < ratio_mup < 3.0, (narrow, wide)
    assert ratio_mup < ratio_sp, (ratio_mup, ratio_sp)


# ---------------------------------------------------------- local SGD


def test_linear_merge_weighted():
    deltas = {"w": jnp.asarray([[2.0, 0.0], [0.0, 4.0]])}
    out = linear_merge(deltas)
    np.testing.assert_allclose(np.asarray(out["w"]), [1.0, 2.0])
    out_w = linear_merge(deltas, weights=jnp.asarray([3.0, 1.0]))
    np.testing.assert_allclose(np.asarray(out_w["w"]), [1.5, 1.0])


def test_gta_merge_sign_consensus():
    # element 0: replicas agree (+) -> mean of both; element 1: disagree,
    # elected sign is + (|2| > |-1|) -> only the agreeing replica counts
    deltas = {"w": jnp.asarray([[1.0, 2.0], [3.0, -1.0]])}
    out = gta_merge(deltas)
    np.testing.assert_allclose(np.asarray(out["w"]), [2.0, 2.0])


def test_sparsify_merge_keeps_top_fraction():
    deltas = {"w": jnp.asarray([[1.0, 10.0, 0.1, 0.2],
                                [8.0, 0.3, 0.1, 0.05]])}
    out = sparsify_merge(deltas, density=0.25)  # top-1 of 4 per replica
    np.testing.assert_allclose(np.asarray(out["w"]), [4.0, 5.0, 0.0, 0.0])


def test_local_sgd_converges_on_least_squares():
    """R replicas, H local sgd steps on distinct data shards, outer
    Nesterov sync: the global params must approach the joint solution."""
    rng = np.random.RandomState(0)
    dim, n_per, R = 4, 64, 4
    w_true = rng.randn(dim).astype(np.float32)
    Xs = [rng.randn(n_per, dim).astype(np.float32) for _ in range(R)]
    ys = [x @ w_true for x in Xs]

    # outer_lr=1, momentum=0 == classic parameter averaging: converges
    # tightly; the momentum path is exercised by the mesh test below
    local = LocalSGD(LocalSGDConfig(merge_method="linear", outer_lr=1.0,
                                    outer_momentum=0.0))
    w_global = jnp.zeros(dim)
    state = local.init(w_global)
    inner_lr, H = 0.01, 8
    for _ in range(30):
        replicas = []
        for r in range(R):
            w = state["global"]
            for _ in range(H):
                grad = 2 * Xs[r].T @ (Xs[r] @ w - ys[r]) / n_per
                w = w - inner_lr * grad
            replicas.append(w)
        stacked = jnp.stack(replicas)
        w_global, state = local.sync(state, stacked)
    assert float(jnp.linalg.norm(w_global - w_true)) < 0.05


def test_build_local_sgd_step_on_mesh():
    """shard_map integration: 8 dp replicas each run collective-free
    inner steps on their own params; one sync merges them."""
    from jax.sharding import Mesh

    devices = np.array(jax.devices()[:8])
    mesh = Mesh(devices, ("dp",))
    dim = 4
    target = jnp.arange(dim, dtype=jnp.float32)

    def inner_step(params, batch):
        grad = 2 * (params["w"] - target) + 0.0 * batch.sum()
        return {"w": params["w"] - 0.1 * grad}

    inner_fn, sync_fn, local = build_local_sgd_step(
        mesh, inner_step, LocalSGDConfig(merge_method="linear"))
    R = 8
    replica_params = {"w": jnp.zeros((R, dim))}
    batches = jnp.asarray(np.random.RandomState(0).randn(R, 2),
                          jnp.float32)
    state = local.init({"w": jnp.zeros(dim)})
    for _ in range(12):
        for _ in range(5):  # H inner steps, no dp collective
            replica_params = inner_fn(replica_params, batches)
        new_global, state = sync_fn(state, replica_params)
        replica_params = jax.tree.map(
            lambda g: jnp.broadcast_to(g, (R,) + g.shape), new_global)
    err = float(jnp.linalg.norm(state["global"]["w"] - target))
    # Nesterov (0.7/0.9) rings around the optimum; 12 rounds reach ~0.05
    assert err < 0.1, err


def test_hsdp_local_sgd_over_fsdp_sharded_params():
    """HSDP composition (reference local_sgd/HSDP) through the LIBRARY
    path: build_local_sgd_step with param_spec over ("dp", "fsdp") keeps
    each replica's params sharded over fsdp while Local SGD merges over
    dp.  Replicas train toward DIFFERENT shifted targets whose mean is
    the true target, so convergence is impossible unless the cross-dp
    merge actually averages (an identity sync would fail)."""
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    devices = np.asarray(jax.devices()[:8]).reshape(4, 2)
    mesh = Mesh(devices, ("dp", "fsdp"))
    R, dim = 4, 8
    target = jnp.arange(dim, dtype=jnp.float32)
    # zero-mean per-replica offsets: each replica's own fixed point is
    # target + offset_r; only the dp average recovers `target`
    offsets = jnp.asarray(
        [[4.0], [-4.0], [2.0], [-2.0]]) * jnp.ones((R, dim))

    def inner_step(params, batch):
        # batch carries this replica's shifted target ([1, local_dim]
        # inside shard_map: fsdp-local shard)
        tgt = batch["target"]
        return {"w": params["w"] - 0.1 * 2 * (params["w"] - tgt)}

    inner_fn, sync_fn, local = build_local_sgd_step(
        mesh, inner_step,
        LocalSGDConfig(merge_method="linear", outer_lr=1.0,
                       outer_momentum=0.0),
        param_spec=P("dp", "fsdp"),
        batch_spec=P("dp", "fsdp"),
    )
    spec = NamedSharding(mesh, P("dp", "fsdp"))
    batches = {"target": jax.device_put(
        jnp.broadcast_to(target, (R, dim)) + offsets, spec)}
    w = {"w": jax.device_put(jnp.zeros((R, dim)), spec)}
    state = local.init({"w": jnp.zeros(dim)})
    for _ in range(10):
        for _ in range(20):  # run each replica close to ITS fixed point
            w = inner_fn(w, batches)
        merged, state = sync_fn(state, w)
        w = {"w": jax.device_put(
            jnp.broadcast_to(merged["w"], (R, dim)), spec)}
    # replicas sit at target+offset; only a real average lands on target
    err = float(jnp.linalg.norm(merged["w"] - target))
    assert err < 1e-2, err
    # the per-replica params the library produced stayed sharded over
    # BOTH axes throughout (fsdp shards were never gathered)
    assert not w["w"].sharding.is_fully_replicated
