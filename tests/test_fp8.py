"""FP8 training path (reference amp fp8 via TransformerEngine,
amp_optimization.py:377): fake-quant dot_general with e4m3 forward /
e5m2 gradient quantization and current scaling."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.ops.fp8 import (
    E4M3_MAX,
    fake_quant_fp8,
    fp8_dot_general,
    quantize_dequantize,
)


def test_quantize_roundtrip_error_bounded():
    x = jax.random.normal(jax.random.PRNGKey(0), (128, 64), jnp.float32)
    y = quantize_dequantize(x, jnp.float8_e4m3fn, E4M3_MAX)
    # e4m3 has 3 mantissa bits: relative error ~2^-4 of amax-scaled values
    err = jnp.max(jnp.abs(x - y))
    assert float(err) < float(jnp.max(jnp.abs(x))) * 0.07
    # actually quantized: far fewer distinct values than input
    assert len(np.unique(np.asarray(y))) < len(np.unique(np.asarray(x)))


def test_zero_tensor_safe():
    z = jnp.zeros((8, 8))
    out = quantize_dequantize(z, jnp.float8_e4m3fn, E4M3_MAX)
    assert not np.any(np.isnan(np.asarray(out)))
    g = jax.grad(lambda x: jnp.sum(fake_quant_fp8(x)))(z)
    assert not np.any(np.isnan(np.asarray(g)))


def test_fp8_dot_close_to_exact():
    k1, k2 = jax.random.split(jax.random.PRNGKey(1))
    x = jax.random.normal(k1, (32, 64), jnp.float32)
    w = jax.random.normal(k2, (64, 16), jnp.float32)
    exact = x @ w
    dn = (((1,), (0,)), ((), ()))
    q = fp8_dot_general(x, w, dn)
    rel = jnp.linalg.norm(q - exact) / jnp.linalg.norm(exact)
    assert float(rel) < 0.05, float(rel)


def test_fp8_gradients_flow_and_are_close():
    k1, k2 = jax.random.split(jax.random.PRNGKey(2))
    x = jax.random.normal(k1, (16, 32), jnp.float32)
    w = jax.random.normal(k2, (32, 8), jnp.float32)
    dn = (((1,), (0,)), ((), ()))

    def loss_q(w):
        return jnp.sum(jnp.tanh(fp8_dot_general(x, w, dn)))

    def loss_e(w):
        return jnp.sum(jnp.tanh(jax.lax.dot_general(x, w, dn)))

    gq = jax.grad(loss_q)(w)
    ge = jax.grad(loss_e)(w)
    rel = jnp.linalg.norm(gq - ge) / jnp.linalg.norm(ge)
    assert float(rel) < 0.25, float(rel)


@pytest.mark.parametrize("scan", [False, True], ids=["layers", "scan"])
def test_llama_fp8_trains(scan):
    """LlamaConfig(fp8=True) trains end-to-end; loss stays in the same
    regime as bf16 for the first steps."""
    from dlrover_tpu.accel.accelerate import AccelerateConfig, accelerate
    from dlrover_tpu.accel.parallel.mesh import MeshSpec
    from dlrover_tpu.models.llama import LlamaConfig, LlamaModel

    ids = jax.random.randint(
        jax.random.PRNGKey(1), (8, 64), 0, 256
    ).astype(jnp.int32)
    losses = {}
    for mode in ("fp8", "bf16"):
        cfg = LlamaConfig.tiny(max_seq_len=64, fp8=(mode == "fp8"),
                               scan_layers=scan)
        res = accelerate(
            LlamaModel(cfg),
            config=AccelerateConfig(mesh_spec=MeshSpec.for_device_count(8)),
            batch_shape=(8, 64),
        )
        state = res.init_fn(jax.random.PRNGKey(0))
        for _ in range(3):
            state, metrics = res.train_step(state, {"input_ids": ids})
        losses[mode] = float(metrics["loss"])
    assert np.isfinite(losses["fp8"])
    assert abs(losses["fp8"] - losses["bf16"]) < 0.2, losses


def test_moe_fp8_trains():
    """fp8=True quantizes MoE expert GEMMs too (not a silent no-op)."""
    from dlrover_tpu.accel.accelerate import AccelerateConfig, accelerate
    from dlrover_tpu.accel.parallel.mesh import MeshSpec
    from dlrover_tpu.models.llama import LlamaConfig, LlamaModel

    ids = jax.random.randint(
        jax.random.PRNGKey(1), (8, 64), 0, 256
    ).astype(jnp.int32)
    losses = {}
    for mode in ("fp8", "bf16"):
        cfg = LlamaConfig.tiny(max_seq_len=64, num_experts=4,
                               fp8=(mode == "fp8"))
        res = accelerate(
            LlamaModel(cfg),
            config=AccelerateConfig(
                mesh_spec=MeshSpec.for_device_count(8, ep=2, fsdp=4)
            ),
            batch_shape=(8, 64),
        )
        state = res.init_fn(jax.random.PRNGKey(0))
        for _ in range(2):
            state, metrics = res.train_step(state, {"input_ids": ids})
        losses[mode] = float(metrics["loss"])
    assert np.isfinite(losses["fp8"])
    assert abs(losses["fp8"] - losses["bf16"]) < 0.3, losses
    # fp8 must actually change the numerics (quantization is engaged)
    assert losses["fp8"] != losses["bf16"], losses


def test_gpt2_fp8_trains():
    """GPT2Config(fp8=True) quantizes its projections (family parity)."""
    from dlrover_tpu.accel.accelerate import AccelerateConfig, accelerate
    from dlrover_tpu.accel.parallel.mesh import MeshSpec
    from dlrover_tpu.models.gpt2 import GPT2Config, GPT2Model

    ids = jax.random.randint(
        jax.random.PRNGKey(1), (8, 64), 0, 128
    ).astype(jnp.int32)
    losses = {}
    for mode in ("fp8", "bf16"):
        cfg = GPT2Config.tiny(fp8=(mode == "fp8"))
        res = accelerate(
            GPT2Model(cfg),
            config=AccelerateConfig(mesh_spec=MeshSpec.for_device_count(8)),
            batch_shape=(8, 64),
        )
        state = res.init_fn(jax.random.PRNGKey(0))
        for _ in range(2):
            state, metrics = res.train_step(state, {"input_ids": ids})
        losses[mode] = float(metrics["loss"])
    assert np.isfinite(losses["fp8"])
    assert abs(losses["fp8"] - losses["bf16"]) < 0.3, losses
    assert losses["fp8"] != losses["bf16"]  # quantization engaged
