"""BERT encoder family: logits parity with transformers BertForMaskedLM.

Third model family (reference fast-paths BERT via BertAttentionFA,
layers.py:801-1447); bidirectional attention with padding-as-segments.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from dlrover_tpu.models.bert import BertConfig, BertModel  # noqa: E402


def _tiny_hf():
    cfg = transformers.BertConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
    )
    torch.manual_seed(0)
    return transformers.BertForMaskedLM(cfg)


def test_logits_parity_with_hf():
    from dlrover_tpu.models.convert import load_hf_bert

    hf = _tiny_hf().eval()
    cfg, params = load_hf_bert(
        hf, dtype=jnp.float32, param_dtype=jnp.float32
    )
    ids = np.array([[3, 17, 99, 42, 7, 64, 5, 11]], dtype=np.int64)
    types = np.array([[0, 0, 0, 0, 1, 1, 1, 1]], dtype=np.int64)
    with torch.no_grad():
        ref = hf(
            torch.from_numpy(ids), token_type_ids=torch.from_numpy(types)
        ).logits.numpy()
    out = BertModel(cfg).apply(
        {"params": params},
        jnp.asarray(ids, jnp.int32),
        token_type_ids=jnp.asarray(types, jnp.int32),
    )
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-3, rtol=2e-3)


def test_attention_mask_blocks_padding():
    """Valid tokens must be unaffected by what sits in padded positions."""
    cfg = BertConfig.tiny(dtype=jnp.float32)
    model = BertModel(cfg)
    import flax.linen as nn

    ids = jnp.array([[5, 6, 7, 8, 0, 0, 0, 0]], jnp.int32)
    mask = jnp.array([[1, 1, 1, 1, 0, 0, 0, 0]], jnp.int32)
    params = nn.unbox(model.init(jax.random.PRNGKey(0), ids))["params"]
    out1 = model.apply({"params": params}, ids, attention_mask=mask)
    ids2 = ids.at[:, 4:].set(99)  # change padding content
    out2 = model.apply({"params": params}, ids2, attention_mask=mask)
    np.testing.assert_allclose(
        np.asarray(out1[:, :4]), np.asarray(out2[:, :4]), atol=1e-5
    )


def test_bert_mlm_training_step():
    """MLM loss descends with a plain optax step on the 8-device mesh."""
    import optax
    from dlrover_tpu.accel.parallel.mesh import MeshSpec

    cfg = BertConfig.tiny(dtype=jnp.float32)
    model = BertModel(cfg)
    import flax.linen as nn

    ids = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 3, 128).astype(
        jnp.int32
    )
    masked = ids.at[:, ::4].set(1)  # [MASK]-ish positions
    params = nn.unbox(model.init(jax.random.PRNGKey(0), masked))["params"]
    tx = optax.adam(1e-3)
    opt = tx.init(params)
    mesh = MeshSpec.for_device_count(8).build_mesh()

    def loss_fn(p):
        logits = model.apply({"params": p}, masked)
        lab = jax.nn.one_hot(ids, cfg.vocab_size)
        return -jnp.mean(
            jnp.sum(jax.nn.log_softmax(logits) * lab, axis=-1)
        )

    @jax.jit
    def step(p, o):
        loss, g = jax.value_and_grad(loss_fn)(p)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, loss

    with mesh:
        losses = []
        for _ in range(5):
            params, opt, loss = step(params, opt)
            losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_bert_framework_call_contract():
    """positions/segment_ids kwargs exist (accelerate's default forward)
    and packing segments compose with the padding mask."""
    cfg = BertConfig.tiny(dtype=jnp.float32)
    model = BertModel(cfg)
    import flax.linen as nn

    ids = jnp.array([[5, 6, 7, 8, 9, 10, 0, 0]], jnp.int32)
    params = nn.unbox(model.init(jax.random.PRNGKey(0), ids))["params"]
    mask = jnp.array([[1, 1, 1, 1, 1, 1, 0, 0]], jnp.int32)
    packing = jnp.array([[0, 0, 0, 1, 1, 1, 0, 0]], jnp.int32)
    positions = jnp.array([[0, 1, 2, 0, 1, 2, 0, 0]], jnp.int32)
    out = model.apply(
        {"params": params}, ids, attention_mask=mask,
        segment_ids=packing, positions=positions,
    )
    # tokens in packing segment 0 must ignore segment 1's content
    ids2 = ids.at[:, 3:6].set(99)
    out2 = model.apply(
        {"params": params}, ids2, attention_mask=mask,
        segment_ids=packing, positions=positions,
    )
    np.testing.assert_allclose(
        np.asarray(out[:, :3]), np.asarray(out2[:, :3]), atol=1e-5
    )


def test_bert_rejects_unsupported_variants():
    from dlrover_tpu.models.convert import config_from_hf_bert

    with pytest.raises(ValueError, match="position_embedding_type"):
        config_from_hf_bert(
            transformers.BertConfig(position_embedding_type="relative_key")
        )


def test_bert_shards_under_accelerate():
    """BertModel passes through accelerate() sharding (regression: the
    mlm_transform square kernel must not use duplicate logical axes)."""
    from dlrover_tpu.accel.accelerate import AccelerateConfig, accelerate
    from dlrover_tpu.accel.parallel.mesh import MeshSpec

    cfg = BertConfig.tiny(dtype=jnp.float32)

    def loss_fn(params, batch):
        model = BertModel(cfg)
        logits = model.apply({"params": params}, batch["input_ids"])
        lab = jax.nn.one_hot(batch["input_ids"], cfg.vocab_size)
        loss = -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * lab, axis=-1))
        return loss, {"weight": jnp.float32(batch["input_ids"].size)}

    res = accelerate(
        BertModel(cfg),
        config=AccelerateConfig(mesh_spec=MeshSpec.for_device_count(8, tp=2)),
        batch_shape=(8, 32),
        loss_fn=loss_fn,
    )
    state = res.init_fn(jax.random.PRNGKey(0))
    ids = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 128).astype(
        jnp.int32
    )
    state, metrics = res.train_step(state, {"input_ids": ids})
    assert np.isfinite(float(metrics["loss"]))
