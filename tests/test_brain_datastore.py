"""Brain job-history datastore (reference: the Brain's MySQL job-history
tables, go/brain/pkg/datastore/implementation/utils/mysql.go:339, feeding
resource optimizers and hpsearch)."""

import os
import time

import numpy as np

from dlrover_tpu.brain.datastore import JobHistoryStore, default_history_store
from dlrover_tpu.brain.hpsearch import BayesianOptimizer, Param
from dlrover_tpu.master.resource.local_optimizer import LocalOptimizer
from dlrover_tpu.master.resource.optimizer import SpeedSample


def test_store_roundtrip(tmp_path):
    db = str(tmp_path / "hist.db")
    s = JobHistoryStore(db)
    s.record_job("j1", "llama-pretrain", {"node_num": 4})
    s.record_speed("j1", 2, 10.0)
    s.record_speed("j1", 4, 18.0)
    s.record_speed("j1", 8, 19.0)
    s.record_trial("j1", {"lr": 1e-3, "accum": 2}, 12.5)
    s.finish_job("j1", "Succeeded")
    s.close()

    # persistence: a NEW process/store sees the history
    s2 = JobHistoryStore(db)
    assert s2.speed_history("llama-pretrain") == {2: 10.0, 4: 18.0, 8: 19.0}
    assert s2.best_worker_count("llama-pretrain") == 8
    assert s2.best_worker_count("other-job") is None
    (params, value), = s2.prior_trials("llama-pretrain")
    assert params == {"lr": 1e-3, "accum": 2} and value == 12.5
    assert s2.jobs() == [("j1", "llama-pretrain", "Succeeded")]
    s2.close()


def test_optimizer_cold_start_uses_history(tmp_path):
    s = JobHistoryStore(str(tmp_path / "hist.db"))
    s.record_job("past", "train-x", {})
    for n, v in ((2, 8.0), (4, 15.0), (6, 14.0)):
        s.record_speed("past", n, v)

    opt = LocalOptimizer(max_workers=8, history_store=s, job_name="train-x")
    # no current-job samples yet -> plan jumps to the historical best (4)
    plan = opt.generate_opt_plan([], current_workers=2)
    assert plan.node_group_resources["worker"].count == 4

    # once current samples exist, the live curve drives as before
    samples = [SpeedSample(worker_num=4, speed=15.5)]
    plan2 = opt.generate_opt_plan(samples, current_workers=4)
    assert plan2.node_group_resources["worker"].count == 5  # grow by unit
    s.close()


def test_hpsearch_warm_start(tmp_path):
    s = JobHistoryStore(str(tmp_path / "h.db"))
    s.record_job("past", "tune-y", {})
    rng = np.random.RandomState(0)
    for _ in range(6):
        lr = float(rng.uniform(0, 1))
        s.record_trial("past", {"lr": lr}, -(lr - 0.7) ** 2)
    bo = BayesianOptimizer([Param("lr", 0.0, 1.0)], seed=1, n_init=4)
    adopted = bo.warm_start(s.prior_trials("tune-y"))
    assert adopted == 6
    # with 6 prior observations the GP path is active immediately and
    # proposes near the prior optimum
    prop = bo.suggest()
    assert 0.3 < prop["lr"] < 1.0
    # trials missing a dimension are skipped, not crashed
    assert bo.warm_start([({"other": 1.0}, 0.0)]) == 0
    s.close()


def test_default_store_env(tmp_path, monkeypatch):
    monkeypatch.delenv("DLROVER_HISTORY_DB", raising=False)
    assert default_history_store() is None
    db = str(tmp_path / "env.db")
    monkeypatch.setenv("DLROVER_HISTORY_DB", db)
    s = default_history_store()
    assert s is not None and os.path.exists(db)
    s.close()


def test_dist_master_records_history(tmp_path, monkeypatch):
    """The master records its speed curve into the store for future jobs
    (the reference's job_metrics persistence path)."""
    from dlrover_tpu.common.rpc import find_free_port
    from dlrover_tpu.master.dist_master import DistributedJobMaster
    from dlrover_tpu.scheduler.in_memory import (
        InMemoryCluster,
        InMemoryNodeWatcher,
        InMemoryScaler,
    )

    db = str(tmp_path / "hist.db")
    monkeypatch.setenv("DLROVER_HISTORY_DB", db)
    monkeypatch.setenv("DLROVER_JOB_NAME", "histjob")
    monkeypatch.setenv("DLROVER_JOB_UID", "uid-42")
    cluster = InMemoryCluster()
    master = DistributedJobMaster(
        find_free_port(),
        scaler=InMemoryScaler(cluster),
        watcher=InMemoryNodeWatcher(cluster),
        node_num=1,
    )
    assert master.history_store is not None
    # synthesize observed speed
    master.speed_monitor.add_running_worker("worker", 0)
    master.speed_monitor.sample_global_step(100, time.time() - 10)
    master.speed_monitor.sample_global_step(200, time.time())
    master._record_history_sample()
    hist = JobHistoryStore(db)
    assert hist.speed_history("histjob"), "no speed recorded"
    assert hist.jobs()[0][:2] == ("uid-42", "histjob")
    hist.close()
    master.stop()


def test_tuning_trials_persist_and_warm_start(tmp_path):
    """The auto-tuning loop persists trials and warm-starts from them
    (closes the loop the Brain's trial tables exist for)."""
    from dlrover_tpu.brain.datastore import JobHistoryStore
    from dlrover_tpu.master.hyperparams.strategy_generator import (
        SimpleStrategyGenerator,
    )

    db = str(tmp_path / "t.db")
    store = JobHistoryStore(db)
    store.record_job("run1", "tunejob")
    gen = SimpleStrategyGenerator(seed=3)
    assert gen.attach_history(store, "run1", "tunejob") == 0
    for _ in range(3):
        gen.next_config()
        gen.observe_speed(5.0)
    assert len(store.prior_trials("tunejob")) == 3

    # a later job warm-starts from them
    store.record_job("run2", "tunejob")
    gen2 = SimpleStrategyGenerator(seed=4)
    assert gen2.attach_history(store, "run2", "tunejob") == 3
    store.close()


def test_brain_service_end_to_end(tmp_path):
    """Standalone Brain service over gRPC (reference brain deployment):
    masters record history, query plans, and run warm-started
    hyperparameter sessions."""
    from dlrover_tpu.brain.datastore import JobHistoryStore
    from dlrover_tpu.brain.service import BrainClient, BrainService

    db = str(tmp_path / "brain.db")
    svc = BrainService(JobHistoryStore(db), port=0)
    svc.start()
    try:
        client = BrainClient(f"127.0.0.1:{svc.port}")
        # a past job teaches the fleet
        client.record_job(job_uuid="old", job_name="fleetjob")
        for n, v in ((2, 8.0), (4, 15.0), (8, 15.5)):
            client.record_speed(job_uuid="old", worker_num=n, speed=v)
        client.finish_job(job_uuid="old", status="Succeeded")
        assert client.speed_history("fleetjob") == {2: 8.0, 4: 15.0, 8: 15.5}

        # a cold new job gets the fleet's best size
        assert client.optimize(
            job_name="fleetjob", current_workers=2, max_workers=16,
            samples=[],
        ) == 8

        # hyperparameter session: suggest/observe round trip, trials
        # persisted for future warm starts
        space = [{"name": "lr", "low": 0.0, "high": 1.0}]
        params = client.suggest(job_uuid="new", job_name="fleetjob",
                                space=space)
        assert 0.0 <= params["lr"] <= 1.0
        client.observe(job_uuid="new", job_name="fleetjob", params=params, value=1.23)
        store = JobHistoryStore(db)
        trials = store.prior_trials()
        assert any(abs(v - 1.23) < 1e-9 for _, v in trials)
        # NAMED warm starts see the session's trials too (jobs row
        # ensured by observe)
        named = store.prior_trials("fleetjob")
        assert any(abs(v - 1.23) < 1e-9 for _, v in named)
        store.close()
        client.close()
    finally:
        svc.stop()
