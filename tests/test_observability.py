"""Observability + hang-detection tests (reference parity:
elastic_agent/monitor/resource.py:86-180, monitor/training.py:77-134,
master/stats/job_collector.py, atorch fault_tolerance/
hanging_detector.py:86, xpu_timer Prometheus export)."""

import json
import os
import sys
import threading
import time
import urllib.request

import pytest

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.agent.monitor.hang import HangingDetector
from dlrover_tpu.agent.monitor.resource import (
    ResourceMonitor,
    sample_resource_stats,
)
from dlrover_tpu.agent.monitor.training import (
    TrainingMonitor,
    read_runtime_metrics,
    write_runtime_metrics,
)
from dlrover_tpu.master.stats.job_collector import (
    JobMetricCollector,
    LocalMetricReporter,
)
from dlrover_tpu.utils.profiler import (
    MetricsExporter,
    StepTimer,
    render_prometheus,
)


def test_sample_resource_stats():
    stats = sample_resource_stats(num_chips=4)
    assert stats.memory_mb > 0
    assert stats.tpu_chips == 4


def test_resource_monitor_reports_to_master(local_master, master_client):
    master, _ = local_master
    monitor = ResourceMonitor(master_client, interval=60)
    stats = monitor.report_once()
    assert stats.memory_mb > 0
    usage = master.job_metric_collector.node_usage
    assert "worker-0" in usage
    assert usage["worker-0"]["memory_mb"] == stats.memory_mb


def test_training_monitor_reports_global_step(
    local_master, master_client, tmp_path
):
    master, _ = local_master
    path = str(tmp_path / "metrics.json")
    write_runtime_metrics(7, elapsed_per_step=0.5, path=path)
    assert read_runtime_metrics(path)["step"] == 7

    monitor = TrainingMonitor(master_client, interval=60, path=path)
    before = monitor.last_progress_time
    time.sleep(0.01)
    assert monitor.check_once() == 7
    assert monitor.last_step == 7
    assert monitor.last_progress_time > before
    # the master saw the step (collector + speed monitor)
    assert master.job_metric_collector.steps[-1]["step"] == 7

    # no new step => no progress-time update
    stamp = monitor.last_progress_time
    monitor.check_once()
    assert monitor.last_progress_time == stamp


def test_hang_detector_fires_once():
    det = HangingDetector(
        progress_fn=lambda: 9999.0,
        timeout=10.0,
        grace_period=0.0,
        max_triggers=1,
    )
    assert det.check_once(now=100.0)
    assert not det.check_once(now=200.0)  # max_triggers reached
    det.reset()  # re-arms grace (0.0) and trigger budget
    assert det.check_once(now=time.time() + 300.0)


def test_hang_detector_respects_grace_and_progress():
    det = HangingDetector(
        progress_fn=lambda: 5.0,
        timeout=10.0,
        grace_period=1000.0,
    )
    det.arm()
    assert not det.check_once()  # inside grace
    det._armed_at = 0.0
    assert not det.check_once()  # progress below timeout


def test_training_monitor_reset_counts_resumed_step_as_progress(
    local_master, master_client, tmp_path
):
    """After a restart the trainer resumes BELOW the pre-crash step; the
    reset must drop the high-water mark so that still counts as progress."""
    path = str(tmp_path / "metrics.json")
    monitor = TrainingMonitor(master_client, interval=60, path=path)
    write_runtime_metrics(1000, path=path)
    assert monitor.check_once() == 1000
    monitor.reset_progress_clock()
    assert monitor.last_step == -1
    assert read_runtime_metrics(path) is None  # stale file dropped
    write_runtime_metrics(950, path=path)  # resumed from checkpoint
    before = monitor.last_progress_time
    time.sleep(0.01)
    assert monitor.check_once() == 950
    assert monitor.last_progress_time > before


def test_agent_restarts_on_hang(local_master, tmp_path):
    """E2e: a worker that never reports progress gets restarted, then the
    agent fails after max_restarts (reference relaunch-on-hang protocol)."""
    _, addr = local_master
    client = MasterClient(addr, node_id=0, node_type="worker")
    metrics_path = str(tmp_path / "rt_metrics.json")
    os.environ["DLROVER_RUNTIME_METRICS_PATH"] = metrics_path
    try:
        from dlrover_tpu.agent.elastic_agent import ElasticAgent, WorkerSpec

        spec = WorkerSpec(
            entrypoint=[sys.executable, "-c", "import time; time.sleep(60)"],
            monitor_interval=0.2,
            max_restarts=1,
            hang_timeout=0.5,
            hang_grace_period=0.0,
            monitors=True,
            flash_ckpt=False,
        )
        agent = ElasticAgent(client, 0, spec)
        rc = agent.run()
        assert rc == 1
        assert agent._group.restart_count == 1
    finally:
        os.environ.pop("DLROVER_RUNTIME_METRICS_PATH", None)
        client.close()


def test_job_metric_collector_speed_and_dump(tmp_path):
    path = str(tmp_path / "metrics.jsonl")
    col = JobMetricCollector(LocalMetricReporter(path))
    t0 = 1000.0
    for i in range(5):
        col.report_global_step(i * 10, t0 + i)
    assert col.training_speed() == pytest.approx(10.0)
    col.report_event("node_failed", "worker-1", "exit 9")
    col.collect_job_meta(job="test", nodes=2)
    m = col.get_job_metrics()
    assert m["global_step"] == 40
    assert m["speed_steps_per_sec"] == pytest.approx(10.0)
    assert m["recent_events"][0]["event_type"] == "node_failed"
    lines = [json.loads(x) for x in open(path)]
    kinds = {r["kind"] for r in lines}
    assert kinds == {"global_step", "event"}


def test_goodput_mark_restart_caps_bridging_interval():
    """ISSUE 9 satellite: a fast recovery hiding a kill inside one
    below-3x-median step interval must still be charged as downtime
    once the master saw the failure report (mark_restart); without the
    flag the same interval is credited fully."""
    col = JobMetricCollector(LocalMetricReporter(None))
    t = 1000.0
    for i in range(1, 9):  # steady 1s/step baseline
        col.report_global_step(i, t + i)
    base = col.goodput()["productive_s"]
    assert base == pytest.approx(7.0)
    # a kill + fast recovery: the next report arrives 2.5s later, one
    # step ahead (resume landed exactly on the crash step) — under the
    # 3x-median radar.  With the failure reported, only ~1 median step
    # of it is productive.
    col.mark_restart()
    col.report_global_step(9, t + 8 + 2.5)
    g = col.goodput()
    assert g["restarts_observed"] == 1
    assert g["productive_s"] == pytest.approx(base + 1.0)
    assert g["steady_wall_s"] - g["productive_s"] == pytest.approx(1.5)
    # the flag is consumed: the following clean interval credits fully
    col.report_global_step(10, t + 8 + 3.5)
    assert col.goodput()["productive_s"] == pytest.approx(base + 2.0)


def test_node_failure_report_marks_goodput_restart(local_master):
    """The servicer wires NodeFailure -> mark_restart + a ledger event."""
    master, addr = local_master
    from dlrover_tpu.agent.master_client import MasterClient

    client = MasterClient(addr, node_id=0, node_type="worker")
    try:
        client.report_failure("worker exit 9", level="error", node_rank=0)
        col = master.job_metric_collector
        assert col.restarts_observed == 1
        events = [e["event_type"] for e in col.get_job_metrics()[
            "recent_events"]]
        assert "node_failure" in events
    finally:
        client.close()


def test_step_timer_stats():
    t = StepTimer()
    for v in (0.1, 0.2, 0.3):
        t.observe(v)
    assert t.count == 3
    assert 0.09 < t.percentile(50) < 0.31
    m = t.metrics()
    assert m["dlrover_step_count"] == 3.0
    assert m["dlrover_step_seconds_total"] == pytest.approx(0.6)


def test_step_timer_metrics_snapshot_is_consistent_under_scrape():
    """metrics() takes ONE locked snapshot (the DL011 fix): a scrape
    racing observe() must never pair count from one step with total
    from the next.  With a constant 0.5s sample (exact in binary
    float), any torn snapshot breaks count * 0.5 == total."""
    t = StepTimer(reservoir=16)
    stop = threading.Event()

    def _observe():
        while not stop.is_set():
            t.observe(0.5)

    w = threading.Thread(target=_observe, daemon=True)
    w.start()
    try:
        for _ in range(300):
            m = t.metrics()
            assert m["dlrover_step_count"] * 0.5 == \
                m["dlrover_step_seconds_total"]
    finally:
        stop.set()
        w.join(timeout=5)


def test_metrics_exporter_serves_prometheus():
    timer = StepTimer()
    timer.observe(0.25)
    exporter = MetricsExporter(labels={"rank": "0"})
    exporter.add_source(timer.metrics)
    exporter.start()
    try:
        url = f"http://127.0.0.1:{exporter.port}/metrics"
        body = urllib.request.urlopen(url, timeout=5).read().decode()
        assert 'dlrover_step_count{rank="0"} 1.0' in body
        health = urllib.request.urlopen(
            f"http://127.0.0.1:{exporter.port}/healthz", timeout=5
        ).read()
        assert health == b"ok"
    finally:
        exporter.stop()


def test_render_prometheus_format():
    text = render_prometheus({"a_metric": 1.5}, {"node": "w0"})
    assert text == 'a_metric{node="w0"} 1.5\n'


def test_render_prometheus_escapes_label_values():
    """Exposition-format escaping: an unescaped quote/backslash/newline
    in a label value corrupts every sample after it on the scrape."""
    text = render_prometheus(
        {"a_metric": 1.0},
        {"path": 'C:\\tmp', "msg": 'say "hi"\nbye'},
    )
    assert text == (
        'a_metric{msg="say \\"hi\\"\\nbye",path="C:\\\\tmp"} 1.0\n'
    )
    assert "\n" not in text[:-1].replace("\\n", "")


def test_metrics_exporter_counts_and_logs_failing_sources():
    """A raising source must not vanish silently: it is counted into
    dlrover_metrics_source_errors_total and logged once per source
    (the 'dlrover_tpu' logger is non-propagating, so the once-per-
    source gate is asserted through the exporter's own bookkeeping)."""
    exporter = MetricsExporter()

    def bad_source():
        raise RuntimeError("boom")

    exporter.add_source(bad_source)
    exporter.add_source(lambda: {"dlrover_step_count": 1.0})
    exporter.start()
    try:
        url = f"http://127.0.0.1:{exporter.port}/metrics"
        body1 = urllib.request.urlopen(url, timeout=5).read().decode()
        body2 = urllib.request.urlopen(url, timeout=5).read().decode()
        # the healthy source still renders; the failure is visible
        assert "dlrover_step_count 1.0" in body1
        assert "dlrover_metrics_source_errors_total 1.0" in body1
        assert "dlrover_metrics_source_errors_total 2.0" in body2
        logged = [k for k in exporter._sources_logged if "bad_source" in k]
        assert len(exporter._sources_logged) == 1 and logged, \
            "log once per source, not per scrape"
    finally:
        exporter.stop()


def test_window_gauge_trims_exactly_at_boundary():
    """A sample exactly window_seconds old sits ON the cutoff and must
    be kept (strict <): off-by-one trims silently bias the mean the
    autoscaler keys off."""
    from dlrover_tpu.utils.profiler import WindowGauge

    g = WindowGauge(window_seconds=10.0)
    g.observe(1.0, now=100.0)
    g.observe(3.0, now=105.0)
    # now=110: the t=100 sample is exactly at the cutoff (110-10) -> kept
    assert g.mean(now=110.0) == pytest.approx(2.0)
    # one tick past the window: dropped
    assert g.mean(now=110.0 + 1e-6) == pytest.approx(3.0)
    # far past the window every sample ages out
    assert g.max(now=120.0) == 0.0


def test_window_gauge_empty_window_rates_and_stats_are_zero():
    from dlrover_tpu.utils.profiler import WindowGauge

    g = WindowGauge(window_seconds=5.0)
    assert g.rate() == 0.0
    assert g.mean() == 0.0
    assert g.max() == 0.0
    g.observe(10.0, now=50.0)
    assert g.rate(now=50.0) == pytest.approx(2.0)  # 10 over a 5s window
    # everything aged out: rate decays to exactly zero, not NaN
    assert g.rate(now=100.0) == 0.0
    assert g.mean(now=100.0) == 0.0


# -- native tracer (xpu_timer counterpart) ----------------------------------

def _native_timer_or_skip():
    from dlrover_tpu.utils import native_timer

    reason = native_timer.check_toolchain()
    if reason is not None:  # pragma: no cover
        pytest.skip(f"native toolchain unavailable: {reason}")
    return native_timer


def test_native_tracer_spans_stats_and_exports(tmp_path):
    nt = _native_timer_or_skip()
    tracer = nt.NativeTracer(ring_capacity=256)
    for _ in range(50):
        with tracer.span("train_step"):
            pass
    t0 = tracer.now_ns()
    tracer.record("ckpt_save", t0, t0 + 5_000_000)  # 5ms span
    s = tracer.stats("train_step")
    assert s["count"] == 50
    assert s["p99_s"] >= s["p50_s"] >= 0
    assert tracer.stats("ckpt_save")["max_s"] == pytest.approx(
        0.005, rel=0.01)

    prom = tracer.export_prometheus()
    assert 'xputimer_span_count{name="train_step"} 50' in prom
    path = str(tmp_path / "trace.json")
    trace = json.loads(tracer.export_chrome_trace(path))
    assert len(trace["traceEvents"]) == 51
    assert json.load(open(path))["traceEvents"]


def test_native_tracer_ring_wraps():
    nt = _native_timer_or_skip()
    tracer = nt.NativeTracer(ring_capacity=16)
    for _ in range(40):
        with tracer.span("s"):
            pass
    trace = json.loads(tracer.export_chrome_trace())
    assert len(trace["traceEvents"]) == 16  # ring keeps the newest spans
    assert tracer.stats("s")["count"] == 40  # aggregates keep everything


def test_exporter_serves_native_tracer_text():
    nt = _native_timer_or_skip()
    tracer = nt.NativeTracer(ring_capacity=64)
    with tracer.span("rpc"):
        pass
    exporter = MetricsExporter()
    exporter.add_text_source(tracer.export_prometheus)
    exporter.start()
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{exporter.port}/metrics", timeout=5
        ).read().decode()
        assert 'xputimer_span_count{name="rpc"} 1' in body
    finally:
        exporter.stop()


# -- topology sorter --------------------------------------------------------

def test_slice_topology_sorter_keeps_rank0_group_first():
    from dlrover_tpu.master.elastic_training.net_topology import (
        NodeTopologyMeta,
        SliceTopologySorter,
    )

    nodes = {
        0: NodeTopologyMeta(node_rank=0, slice_id=2, asw="asw-9"),
        1: NodeTopologyMeta(node_rank=1, slice_id=1, asw="asw-1"),
        2: NodeTopologyMeta(node_rank=2, slice_id=2, asw="asw-9"),
        3: NodeTopologyMeta(node_rank=3, slice_id=1, asw="asw-1"),
    }
    ordered = list(SliceTopologySorter().sort(nodes).values())
    # rank 0's (slice 2, asw-9) group leads despite higher slice id
    assert [n.node_rank for n in ordered] == [0, 2, 1, 3]
    # groups are contiguous
    assert [n.slice_id for n in ordered] == [2, 2, 1, 1]


# ---------------------------------------------------------------------------
# xprof auto-profiling (reference xpu_timer: transparent per-kernel /
# per-collective timing -> Prometheus, atorch/dev/xpu_timer/nvidia/hook.cc)
# ---------------------------------------------------------------------------


def test_profile_call_captures_ops():
    import jax
    import jax.numpy as jnp

    from dlrover_tpu.utils.xprof_metrics import profile_call

    f = jax.jit(lambda a, b: (a @ b).sum())
    x = jnp.ones((128, 128))
    f(x, x).block_until_ready()  # compile outside the trace
    result, bd = profile_call(lambda: f(x, x))
    assert float(result) != 0.0
    assert bd["total_device_us"] > 0
    assert bd["top_ops"], bd


def test_profile_call_times_collectives():
    """A psum under shard_map must land in the collectives table —
    the per-collective timing xpu_timer provides."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from dlrover_tpu.utils.xprof_metrics import profile_call

    mesh = Mesh(jax.devices(), ("dp",))

    @jax.jit
    def step(x):
        f = shard_map(lambda v: jax.lax.psum(v @ v, "dp"), mesh,
                      in_specs=P("dp"), out_specs=P())
        return f(x).sum()

    x = jnp.ones((8 * 32, 32))
    step(x).block_until_ready()
    _, bd = profile_call(lambda: step(x))
    assert bd["collectives"], bd["top_ops"]
    assert bd["collective_us"] > 0


def test_auto_profiler_every_n_and_prometheus_text():
    import jax
    import jax.numpy as jnp

    from dlrover_tpu.utils.xprof_metrics import AutoProfiler

    f = jax.jit(lambda a: (a * 2).sum())
    x = jnp.ones((64, 64))
    f(x).block_until_ready()
    prof = AutoProfiler(every_n=3, warmup_steps=1)
    for _ in range(4):  # steps 1(warmup) 2 3 4: capture on step 4
        prof.around_step(lambda: f(x))
    assert prof.profile_count == 1
    assert prof.breakdown is not None
    text = prof.prometheus_text()
    assert "dlrover_xprof_profiles_total 1.0" in text
    assert "dlrover_xprof_device_seconds" in text
    assert "dlrover_xprof_op_seconds{op=" in text


def test_elastic_trainer_xprof_endpoint():
    """Zero-instrumentation wiring: a normal train loop with
    xprof_every_n_steps exposes op timings on /metrics."""
    import urllib.request

    import jax
    import numpy as np

    from dlrover_tpu.models.llama import LlamaConfig, LlamaModel
    from dlrover_tpu.trainer.elastic.trainer import ElasticTrainer

    cfg = LlamaConfig.tiny(max_seq_len=16)
    tr = ElasticTrainer(
        LlamaModel(cfg), global_batch_size=8, micro_batch_per_shard=1,
        seq_len=16, xprof_every_n_steps=2, metrics_port=0,
    )
    try:
        tr.prepare()
        tr.restore_or_init(jax.random.PRNGKey(0))
        batch = np.ones((8, 16), np.int32)
        for _ in range(5):
            tr.train_step(batch)
        assert tr.auto_profiler.profile_count >= 1
        url = f"http://127.0.0.1:{tr.metrics_exporter.port}/metrics"
        body = urllib.request.urlopen(url, timeout=5).read().decode()
        assert "dlrover_step_count" in body
        assert "dlrover_xprof_op_seconds{op=" in body
    finally:
        tr.close()


# -- goodput (reference README.md:54-57: useful-new-step time / wall) -------


def test_goodput_healthy_run_approaches_one():
    c = JobMetricCollector()
    c.mark_job_start(timestamp=100.0)
    # first step lands after 2s of compile (downtime), then 10 steps
    # at 1s each — goodput = 10 / 12
    for i in range(11):
        c.report_global_step(i + 1, 102.0 + i)
    g = c.goodput()
    assert g["wall_s"] == pytest.approx(12.0)
    assert g["productive_s"] == pytest.approx(10.0)
    assert g["goodput"] == pytest.approx(10.0 / 12.0)


def test_goodput_counts_fault_and_rollback_as_downtime():
    """A kill at step 8 that rolls back to a step-5 checkpoint: the gap,
    the recompile, AND the re-run of steps 6-8 all earn nothing — only
    never-before-completed steps are credited."""
    c = JobMetricCollector()
    c.mark_job_start(timestamp=0.0)
    for i in range(1, 9):  # steps 1..8, 1s each, first at t=1
        c.report_global_step(i, float(i))
    # fault: 10s of detection + restart + recompile; resume at step 6
    c.report_global_step(6, 18.0)   # rollback report: no credit
    c.report_global_step(7, 19.0)   # re-done: no credit
    c.report_global_step(8, 20.0)   # re-done: no credit
    c.report_global_step(9, 21.0)   # NEW step: credited
    c.report_global_step(10, 22.0)
    g = c.goodput()
    # productive: steps 2..8 (7s; step 1's interval is from job start,
    # prev=None so uncredited) + steps 9,10 (2s)
    assert g["productive_s"] == pytest.approx(9.0)
    assert g["wall_s"] == pytest.approx(22.0)
    assert g["downtime_s"] == pytest.approx(13.0)
    assert g["goodput"] == pytest.approx(9.0 / 22.0)


def test_goodput_credits_partial_interval_across_rollback_point():
    """A sparse report window straddling the rollback point credits only
    the fraction covering new steps."""
    c = JobMetricCollector()
    c.mark_job_start(timestamp=0.0)
    c.report_global_step(4, 4.0)
    c.report_global_step(8, 8.0)    # steps 5-8 credited (4s)
    c.report_global_step(6, 20.0)   # post-restart resume: no credit
    # one 4s window covering steps 7..10: 8 already credited, so only
    # steps 9,10 count -> half the interval
    c.report_global_step(10, 24.0)
    g = c.goodput()
    assert g["productive_s"] == pytest.approx(4.0 + 2.0)
    assert g["goodput"] == pytest.approx(6.0 / 24.0)


def test_goodput_in_job_metrics_and_detail_rpc(local_master, master_client):
    """The goodput breakdown rides get_job_metrics and the job-detail
    RPC so any client (and the e2e artifact) can read it."""
    master, _ = local_master
    col = master.job_metric_collector
    now = time.time()
    col.report_global_step(1, now - 3.0)
    col.report_global_step(5, now)
    metrics = master_client.query_job_detail().get("metrics", {})
    assert "goodput" in metrics
    assert metrics["goodput"]["productive_s"] == pytest.approx(3.0, abs=0.1)
    assert 0.0 < metrics["goodput"]["goodput"] <= 1.0


def test_goodput_caps_windows_hiding_a_restart():
    """A sparse sampling window that spans a crash+recovery but still
    shows net step progress must not credit the recovery gap: new steps
    are credited at the typical per-step rate instead."""
    c = JobMetricCollector()
    c.mark_job_start(timestamp=0.0)
    for i in range(1, 6):  # steps 1..5, 1s cadence
        c.report_global_step(i, float(i))
    # window 5 -> 6 took 14s: a crash + restart hid inside it
    c.report_global_step(6, 19.0)
    g = c.goodput()
    # steps 2..5 credited fully (4s); step 6 at the 1s median, not 14s
    assert g["productive_s"] == pytest.approx(5.0)
    assert g["downtime_s"] == pytest.approx(19.0 - 5.0)


# -- ISSUE 12: /traces query filtering + the master metrics endpoint ---------


def _traced_router():
    import numpy as np

    from dlrover_tpu.serving.remote.worker import FakeEngine
    from dlrover_tpu.serving.router import (
        ContinuousBatchScheduler,
        RouterMetrics,
        ServingRouter,
    )

    router = ServingRouter(
        scheduler=ContinuousBatchScheduler(block_size=4),
        metrics=RouterMetrics(window_seconds=0.5),
    )
    router.join_replica(
        "r0", FakeEngine(slots=8, tokens_per_step=8, blocks=100000))
    t = time.monotonic()
    for i in range(6):
        router.submit(np.full(8, i % 251, "int32"), 8, now=t)
    # one request that can only expire: deadline already passed
    router.submit(np.full(8, 3, "int32"), 8, timeout=-1.0, now=t)
    router.run_until_idle()
    return router


def test_traces_endpoint_query_filters():
    """/traces and /traces/slowest take ?name= / ?status= / ?limit= —
    mid-incident "the failover traces, newest 20" must be one query,
    not a 4096-entry dump."""
    router = _traced_router()
    exporter = MetricsExporter()
    exporter.attach_router(router)
    exporter.start()
    try:
        base = f"http://127.0.0.1:{exporter.port}"

        def get(path):
            return json.loads(urllib.request.urlopen(
                base + path, timeout=5).read())

        everything = get("/traces")["traces"]
        assert len(everything) == 7
        limited = get("/traces?limit=3")["traces"]
        assert len(limited) == 3
        ok_only = get("/traces?status=ok")["traces"]
        assert len(ok_only) == 6
        assert all(t["status"] == "ok" for t in ok_only)
        timed_out = get("/traces?status=TimedOut")["traces"]
        assert len(timed_out) == 1
        named = get("/traces?name=request&limit=500")["traces"]
        assert len(named) == 7
        assert get("/traces?name=autoscale")["traces"] == []
        slowest = get("/traces/slowest?limit=2&status=ok")["traces"]
        assert len(slowest) == 2
        assert all(t["status"] == "ok" for t in slowest)
        assert slowest[0]["duration_s"] >= slowest[1]["duration_s"]
        # a bad limit degrades to the default instead of erroring
        assert len(get("/traces?limit=bogus")["traces"]) == 7
    finally:
        exporter.stop()


def test_tracer_filters_direct():
    from dlrover_tpu.utils.tracing import Tracer

    tracer = Tracer()
    for i, (name, status) in enumerate(
            [("request", "ok"), ("request", "failover"),
             ("autoscale", "ok")]):
        root = tracer.start_trace(name, rid=i)
        tracer.finish_trace(root, status=status)
    assert len(tracer.finished(name="request")) == 2
    assert len(tracer.finished(status="failover")) == 1
    assert len(tracer.slowest(name="autoscale")) == 1
    assert tracer.finished(name="request", status="ok")[0][
        "status"] == "ok"


def test_master_metrics_endpoint_serves_goodput_ledger(capsys):
    """The ISSUE-12 satellite: the master serves /metrics (port-0 +
    stdout announce) exposing the goodput ledger + rendezvous
    counters with registry help text — scrapeable, not
    JSON-artifact-only."""
    from dlrover_tpu.common.constants import NodeEnv, RendezvousName
    from dlrover_tpu.master.dist_master import DistributedJobMaster
    from dlrover_tpu.scheduler.in_memory import (
        InMemoryCluster,
        InMemoryNodeWatcher,
        InMemoryScaler,
    )

    cluster = InMemoryCluster()
    master = DistributedJobMaster(
        0, scaler=InMemoryScaler(cluster),
        watcher=InMemoryNodeWatcher(cluster), node_num=1)
    col = master.job_metric_collector
    col.mark_job_start(timestamp=time.time() - 10.0)
    col.report_global_step(1, time.time() - 8.0)
    col.report_global_step(5, time.time() - 1.0)
    rdzv = master.rdzv_managers[RendezvousName.ELASTIC_TRAINING]
    rdzv.update_rdzv_params(min_nodes=1, max_nodes=1,
                            waiting_timeout=5, node_unit=1)
    rdzv.join_rendezvous(0, 0, 1)
    rdzv.get_comm_world(0)
    port = master.start_metrics_exporter(0)
    try:
        announced = capsys.readouterr().out
        assert f"{NodeEnv.MASTER_METRICS_ANNOUNCE_PREFIX}{port}" \
            in announced
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
        ).read().decode()
        assert "dlrover_master_goodput " in body
        assert "# HELP dlrover_master_goodput" in body
        assert "dlrover_master_rendezvous_rounds_total 1.0" in body
        assert "dlrover_master_world_size 1.0" in body
        assert "dlrover_master_restarts_observed_total 0.0" in body
        m = master.master_metrics()
        assert 0.0 < m["dlrover_master_goodput"] <= 1.0
        assert m["dlrover_master_downtime_seconds_total"] >= 0.0
    finally:
        master.stop_metrics_exporter()
