"""Fleet-migration chaos suite (ISSUE 11): crash-safe train⇄serve
chip repurposing with lease-fenced exactly-once capacity handoff.

The harness is a full in-thread fleet on a synthetic clock: three
training hosts behind a REAL ElasticTrainingRendezvousManager (driven
fake agents), a REAL JobMetricCollector goodput ledger, a REAL Flash
Checkpoint blocking-save barrier (tiny numpy state through the actual
shm engine), a serving router with a brown-out ladder and a two-replica
base fleet, and the FleetCoordinator under test with a journal on
tmp_path.

The chaos acceptance (CHAOS.md F1-F6): coordinator killed mid-borrow
and mid-return (a NEW incarnation reconstructs every lease from master
+ supervisor ground truth, stale-epoch claims fenced), the borrowed
worker killed mid-boot, the master restarted mid-shrink — and through
all of it: zero lost serving requests, training resuming exactly on
the committed checkpoint step, every lease ending single-owner, every
handoff debt retired exactly once.
"""

import os
import time
import uuid

import numpy as np
import pytest

from dlrover_tpu.common.constants import (  # noqa: E402
    FLEET_HOST_TRANSITIONS,
    FleetOwner,
)
from dlrover_tpu.fleet import (  # noqa: E402
    FleetCoordinator,
    LeaseLedger,
    LeaseTransitionError,
    ServingPlane,
    StaleLeaseError,
    TrainingPlane,
)
from dlrover_tpu.master.elastic_training.rdzv_manager import (  # noqa: E402
    ElasticTrainingRendezvousManager,
)
from dlrover_tpu.master.stats.job_collector import (  # noqa: E402
    JobMetricCollector,
)
from dlrover_tpu.serving.remote.supervisor import (  # noqa: E402
    WorkerRecord,
    WorkerSupervisor,
)
from dlrover_tpu.serving.remote.worker import FakeEngine  # noqa: E402
from dlrover_tpu.serving.router import (  # noqa: E402
    PRIORITY_NORMAL,
    BrownoutPolicy,
    ContinuousBatchScheduler,
    RouterMetrics,
    ServingRouter,
)
from dlrover_tpu.serving.router.replica import (  # noqa: E402
    base_replica_name,
)
from dlrover_tpu.trainer.flash_checkpoint import (  # noqa: E402
    Checkpointer,
    SaverMode,
    StorageType,
)


@pytest.fixture(autouse=True)
def _isolate(monkeypatch):
    """Unique job uid per test so checkpoint shm segments/queues never
    collide across harnesses; reset the saver singleton and sweep the
    job's shm afterwards (same hygiene as test_flash_checkpoint)."""
    from dlrover_tpu.agent.ckpt_saver import AsyncCheckpointSaver

    job = uuid.uuid4().hex[:8]
    monkeypatch.setenv("DLROVER_JOB_UID", job)
    yield
    AsyncCheckpointSaver.reset()
    for fn in os.listdir("/dev/shm"):
        if job in fn:
            try:
                os.unlink(os.path.join("/dev/shm", fn))
            except OSError:
                pass


def _prompt(i, n=8):
    return np.full(n, i % 251, np.int32)


class _StubProc:
    def __init__(self, pid):
        self.pid = pid
        self.returncode = None

    def poll(self):
        return self.returncode


class _StubProxy:
    def close(self, goodbye=True):
        pass


class _FleetStubSupervisor(WorkerSupervisor):
    """spawn() without fork/exec, but WITH a router join: a fleet boot
    becomes a FakeEngine replica, so the borrowed host really takes
    traffic through the router's pump.  ``fail_next`` makes the next N
    spawns die mid-boot (the worker SIGKILLed before its announce —
    exactly what the supervisor's announce timeout surfaces)."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self._pid = 5000
        self.fail_next = 0
        self.boot_failures = 0
        self.spawn_counts = {}

    def spawn(self, name=None, join=True, managed=True):
        if self.fail_next > 0:
            self.fail_next -= 1
            self.boot_failures += 1
            raise RuntimeError(
                "worker killed mid-boot: announce never arrived")
        self._pid += 1
        record = WorkerRecord(
            name, _StubProc(self._pid), "127.0.0.1:0", _StubProxy(),
            managed)
        with self._lock:
            self.workers[name] = record
        if join and self.router is not None:
            self.router.join_replica(
                name, FakeEngine(slots=2, tokens_per_step=2))
        self.spawn_counts[name] = self.spawn_counts.get(name, 0) + 1
        return record


class _Fleet:
    """One fleet under fire, in a box (see module docstring)."""

    def __init__(self, tmp_path, n_hosts=3, min_train_hosts=2,
                 base_replicas=2, journal=True, dwell=0.3):
        # min_train_hosts=2 of 3 hosts -> exactly ONE lendable host
        # (host-2), which keeps every exactly-once count deterministic
        self.t = 1000.0
        self.rdzv = ElasticTrainingRendezvousManager()
        self.collector = JobMetricCollector()
        self.collector.mark_job_start(self.t)
        self.bo = BrownoutPolicy(enter_pressure=2.0,
                                 exit_pressure=0.5,
                                 dwell_seconds=0.2)
        self.router = ServingRouter(
            scheduler=ContinuousBatchScheduler(block_size=4),
            metrics=RouterMetrics(window_seconds=0.5),
            brownout=self.bo,
        )
        for i in range(base_replicas):
            self.router.join_replica(
                f"serving-replica-{i}",
                FakeEngine(slots=2, tokens_per_step=2), now=self.t)
        self.sup = _FleetStubSupervisor(
            router=self.router, respawn=False,
            recorder=self.router.recorder)
        self.hosts = {f"host-{r}": r for r in range(n_hosts)}
        self.ckpt = Checkpointer(
            str(tmp_path / "ckpt"), saver_mode=SaverMode.LOCAL,
            local_rank=0, local_world_size=1, node_rank=0, node_num=1)
        self.ckpt_fail = False
        self.barrier_steps = []      # committed steps per barrier call
        self.plane = TrainingPlane(
            self.rdzv, self.hosts, self._ckpt_barrier,
            collector=self.collector, min_nodes=1,
            recorder=self.router.recorder,
            wall_clock=lambda: self.t)
        self.serving = ServingPlane(self.router, self.sup)
        self.journal_path = str(tmp_path / "leases.json") if journal \
            else None
        self.min_train_hosts = min_train_hosts
        self.coord = FleetCoordinator(
            self.plane, self.serving,
            journal_path=self.journal_path,
            min_train_hosts=min_train_hosts,
            borrow_stage=1, dwell_seconds=dwell, boot_attempts=4,
            now=self.t)
        # simulated trainer.  Restart detection keys on (manager
        # identity, round): a master restart resets round numbering,
        # and a bare round compare can alias across the swap.
        self.step_n = 0
        self._world_key = (id(self.rdzv), self.rdzv.rdzv_round)
        self._restart_lag = 0        # ticks of restore/compile pause
        self.resume_steps = []       # restore step at each restart
        self.requests = []

    # ------------------------------------------------------- trainer sim
    def _ckpt_barrier(self):
        if self.ckpt_fail:
            raise RuntimeError("injected commit failure")
        ok = self.ckpt.save_checkpoint(
            self.step_n, {"w": np.full(8, self.step_n, np.float32)},
            StorageType.MEMORY, block=True)
        if not ok:
            raise RuntimeError("memory save refused")
        self.barrier_steps.append(self.step_n)
        return self.step_n

    def _restore_step(self):
        step, state = self.ckpt.engine.load()
        return int(step) if state is not None else 0

    def _drive_agents(self):
        """Fake per-host agents: join when expected-but-absent, and
        rejoin (the growth restart) when the master says waiting nodes
        could enlarge the world."""
        expected = set(self.plane.expected_hosts())
        for h, r in self.hosts.items():
            if h in expected and not self.rdzv.joined(r):
                self.rdzv.join_rendezvous(r, r, 1)
        if self.rdzv.num_nodes_waiting() > 0:
            for r in self.rdzv.current_world_ranks():
                self.rdzv.join_rendezvous(r, r, 1)
        self.rdzv.get_comm_world(0)  # drives round completion

    def _train_tick(self):
        world = self.rdzv.current_world_ranks()
        if not world or len(world) != self.plane.target_world:
            return
        if (id(self.rdzv), self.rdzv.rdzv_round) != self._world_key:
            # a membership change restarted the trainer: resume from
            # the committed checkpoint generation — THE assertion
            # surface for "training resumes exactly on the committed
            # step"
            self._world_key = (id(self.rdzv), self.rdzv.rdzv_round)
            restored = self._restore_step()
            if restored > 0:
                self.step_n = restored
            self.resume_steps.append(restored)
            # restore + recompile latency: a few ticks of pause, so
            # the bridging interval is a REAL stall (>3x the per-tick
            # median) the goodput radar can see and the planned-
            # elasticity attribution can claim
            self._restart_lag = 4
        if self._restart_lag > 0:
            self._restart_lag -= 1
            return
        self.step_n += 1
        self.collector.report_global_step(self.step_n, self.t)
        # per-step blocking memory save: every step is a committed
        # generation (tiny state; on real hardware this is the async
        # double-buffered path, blocking here makes restores exact)
        self.ckpt.save_checkpoint(
            self.step_n, {"w": np.full(8, self.step_n, np.float32)},
            StorageType.MEMORY, block=True)

    # ---------------------------------------------------------- the tick
    def tick(self, dt=0.05, coordinator=True):
        self.t += dt
        self._drive_agents()
        self._train_tick()
        self.sup.poll(now=self.t)
        self.router.step(now=self.t)
        if coordinator:
            self.coord.poll(now=self.t)
        # a fleet worker whose replica left the router (drain retired
        # or reaped dead) exits: GOODBYE -> rc 0 (the real worker's
        # voluntary-exit contract); the next sup.poll reaps it
        joined = {base_replica_name(n)
                  for n in self.router.replica_names}
        with self.sup._lock:
            records = list(self.sup.workers.values())
        for rec in records:
            if rec.proc.returncode is None and \
                    base_replica_name(rec.name) not in joined:
                rec.proc.returncode = 0

    def run(self, n, dt=0.05, until=None, coordinator=True):
        for _ in range(n):
            self.tick(dt, coordinator=coordinator)
            if until is not None and until():
                return True
        return until is None

    def spike(self, n=40, max_new=32, priority=PRIORITY_NORMAL):
        reqs = [self.router.submit(_prompt(i), max_new,
                                   priority=priority, now=self.t)
                for i in range(n)]
        self.requests.extend(reqs)
        return reqs

    def owners(self):
        return self.coord.ledger.owners()

    def close(self):
        self.ckpt.close()


@pytest.fixture
def fleet(tmp_path):
    f = _Fleet(tmp_path)
    yield f
    f.close()


# ---------------------------------------------------------------- F1/F6

def test_borrow_and_return_full_cycle_zero_lost(fleet):
    """The happy-path acceptance: sustained pressure borrows a host
    (durable ckpt commit -> shrink -> worker boots -> serves), falling
    pressure returns it (zero-lost drain -> regrow -> training resumes
    on the committed step), zero requests lost, every debt retired
    exactly once, every lease single-owner."""
    f = fleet
    # settle: world forms, trainer steps
    f.run(8)
    assert f.plane.world_hosts() == ["host-0", "host-1", "host-2"]
    assert all(o == FleetOwner.TRAINING for o in f.owners().values())

    f.spike(60)
    assert f.run(600, until=lambda: f.coord.borrows_total == 1), \
        f"borrow never completed: {f.coord.migrations} {f.owners()}"
    assert f.owners()["host-2"] == FleetOwner.SERVING
    # the release barrier ran, blocking, BEFORE the shrink
    assert f.barrier_steps, "checkpoint barrier never invoked"
    assert f.plane.last_committed_step == f.barrier_steps[-1]
    # training world shrank and resumed from the committed generation
    assert f.plane.world_hosts() == ["host-0", "host-1"]
    assert f.resume_steps and \
        f.resume_steps[-1] == f.barrier_steps[-1], (
            f.resume_steps, f.barrier_steps)
    # the borrowed host REALLY serves: its replica took placements
    handle = next(
        h for n, h in f.router.manager.replicas.items()
        if base_replica_name(n) == "host-2")
    # ISSUE 12: while on loan, the borrowed replica's origin is the
    # borrow trace, so request attempts landing on host-2 link back
    # to the decision that created it (pruned again at return-drain
    # retirement — a returned host carries no serving origin)
    origin = f.router.replica_origins.get("host-2")
    assert origin is not None and origin["kind"] == "fleet_borrow"
    # drain the spike so pressure falls; the return decision follows
    assert f.run(900, until=lambda: f.coord.returns_total == 1), \
        f"return never completed: {f.coord.migrations} {f.owners()}"
    assert handle.ever_placed, "borrowed replica never took traffic"
    assert f.owners()["host-2"] == FleetOwner.TRAINING
    f.run(10)
    assert f.plane.world_hosts() == ["host-0", "host-1", "host-2"]

    # ZERO lost serving requests: every admitted request completed
    for r in f.requests:
        r.result(timeout=5)
    assert f.router.gateway.poisoned == 0
    assert f.router.metrics.completed == len(f.requests)

    # exactly-once debts: one borrow + one return, each retired once
    assert f.coord.debts_retired_total == 2
    assert f.coord.open_debts() == []
    retired = sorted(
        (d["key"], d["retired_reason"]) for d in
        f.coord.debts.values())
    assert retired == [("borrow:host-2", "serving_joined"),
                       ("return:host-2", "training_joined")]

    # single-owner invariant + handoff latencies recorded
    assert f.coord.verify() == []
    assert f.coord.last_borrow_handoff_s > 0
    assert f.coord.last_return_handoff_s > 0

    # goodput: both windows were PLANNED elasticity, not downtime, and
    # no restart was ever charged
    g = f.collector.goodput()
    assert g["planned_windows"] >= 2, g
    assert g["planned_elasticity_s"] > 0, g
    assert g["restarts_observed"] == 0, g

    # migration traces are always-sampled and closed
    trees = f.router.tracer.traces_named("fleet_migration", limit=50)
    assert len(trees) >= 2
    assert {tr["spans"][0]["attrs"]["direction"] for tr in trees} >= \
        {"borrow", "return"}
    assert {tr["status"] for tr in trees if tr["status"]} <= \
        {"ok", "aborted"}

    # ISSUE 12 span links: the borrow trace references the pressure
    # evidence that pulled the trigger (no autoscaler here, so a
    # minted serving_pressure snapshot of the brown-out stage)
    borrow_tree = next(
        tr for tr in trees
        if tr["spans"][0]["attrs"]["direction"] == "borrow")
    links = borrow_tree["spans"][0].get("links") or []
    assert links, "the borrow root must link to its demand evidence"
    assert links[0]["attrs"]["rel"] == "evidence"
    evidence = f.router.tracer.get_tree(links[0]["trace_id"])
    assert evidence is not None \
        and evidence["name"] == "serving_pressure"
    assert evidence["spans"][0]["attrs"]["stage"] >= 1
    # the origin registered mid-loan (asserted above) was pruned when
    # the returned host's replica retired — no stale decision link
    # survives for a name that left the serving fleet
    assert "host-2" not in f.router.replica_origins


# ------------------------------------------------------------------- F2

def test_coordinator_killed_mid_borrow_recovers_and_finishes(tmp_path):
    """SIGKILL the coordinator between the world shrink and the worker
    boot (the worst instant: the host is in NEITHER world).  A new
    incarnation reconstructs from ground truth + journal intent,
    finishes the boot, and the handoff converges — the host is never
    double-provisioned."""
    f = _Fleet(tmp_path)
    try:
        f.run(8)
        f.spike(60)
        # wedge the boot so the migration parks between shrink and join
        f.sup.fail_next = 10 ** 6
        assert f.run(400, until=lambda: (
            "host-2" in f.coord.migrations
            and f.coord.migrations["host-2"]["phase"] == "boot"
            and f.plane.last_committed_step >= 0))
        assert "host-2" not in f.plane.alive_hosts()
        committed = f.plane.last_committed_step
        old = f.coord

        # the coordinator "process" dies; a new incarnation boots from
        # the journal + ground truth
        f.sup.fail_next = 0
        f.coord = FleetCoordinator(
            f.plane, f.serving, journal_path=f.journal_path,
            min_train_hosts=2, borrow_stage=1, dwell_seconds=0.3,
            boot_attempts=4, now=f.t)
        assert f.coord.ledger.epoch == old.epoch + 1
        # recovery classified the orphan as a mid-borrow host
        assert f.coord.ledger.owner("host-2") == \
            FleetOwner.MIGRATING_OUT
        assert f.run(400, until=lambda: f.coord.borrows_total == 1)
        assert f.coord.ledger.owner("host-2") == FleetOwner.SERVING
        # exactly once: ONE successful boot across both incarnations
        assert f.sup.spawn_counts.get("host-2") == 1
        # training kept running on the shrunk world from the committed
        # step throughout the coordinator outage
        assert f.resume_steps and f.resume_steps[-1] == committed
        assert f.coord.verify() == []
    finally:
        f.close()


def test_zombie_coordinator_is_fenced_after_recovery(tmp_path):
    """The old incarnation is not dead, only presumed dead — when it
    wakes up and tries to finish ITS migration, the lease epoch fences
    every claim (stale-epoch counter proves the fence fired)."""
    f = _Fleet(tmp_path)
    try:
        f.run(8)
        f.spike(60)
        f.sup.fail_next = 10 ** 6
        assert f.run(400, until=lambda: (
            "host-2" in f.coord.migrations
            and f.coord.migrations["host-2"]["phase"] == "boot"))
        zombie = f.coord
        # successor SHARES the ledger object (same journal authority)
        f.coord = FleetCoordinator(
            f.plane, f.serving, ledger=zombie.ledger,
            min_train_hosts=2, borrow_stage=1, dwell_seconds=0.3,
            boot_attempts=4, now=f.t)
        f.sup.fail_next = 0
        # the zombie wakes and tries to drive its stale migration to
        # completion: the first lease write is fenced, the zombie goes
        # inert instead of corrupting single-ownership
        fenced_before = zombie.ledger.stale_claims_fenced
        for _ in range(50):
            f.tick(coordinator=False)
            zombie.poll(now=f.t)
            if zombie.fenced:
                break
        assert zombie.fenced
        assert zombie.ledger.stale_claims_fenced > fenced_before
        # the successor still converges the handoff
        assert f.run(400, until=lambda: f.coord.borrows_total == 1)
        assert f.coord.verify() == []
        assert f.sup.spawn_counts.get("host-2") == 1
    finally:
        f.close()


# ------------------------------------------------------------------- F3

def test_coordinator_killed_mid_return_recovers_and_finishes(tmp_path):
    """Crash between the drain decision and the rendezvous regrow: the
    new incarnation reads the journal intent (MIGRATING_BACK), finishes
    the drain zero-lost, and training regrows to the full world."""
    f = _Fleet(tmp_path)
    try:
        f.run(8)
        f.spike(60)
        assert f.run(600, until=lambda: f.coord.borrows_total == 1)
        # let pressure fall until the return decision fires, then kill
        # the coordinator while the replica is still draining
        assert f.run(900, until=lambda: (
            "host-2" in f.coord.migrations
            and f.coord.migrations["host-2"]["kind"] == "return"))
        old = f.coord
        f.coord = FleetCoordinator(
            f.plane, f.serving, journal_path=f.journal_path,
            min_train_hosts=2, borrow_stage=1, dwell_seconds=0.3,
            boot_attempts=4, now=f.t)
        assert f.coord.ledger.epoch == old.epoch + 1
        assert f.run(900, until=lambda: f.coord.returns_total == 1)
        assert f.coord.ledger.owner("host-2") == FleetOwner.TRAINING
        f.run(10)
        assert f.plane.world_hosts() == \
            ["host-0", "host-1", "host-2"]
        # zero lost through the crash-straddling drain
        for r in f.requests:
            r.result(timeout=5)
        assert f.router.gateway.poisoned == 0
        assert f.coord.verify() == []
    finally:
        f.close()


# ------------------------------------------------------------------- F4

def test_borrowed_worker_killed_mid_boot_is_retried(fleet):
    """The freed host's worker dies before it can announce (SIGKILL
    mid-boot): the coordinator retries within its attempt budget and
    the borrow still lands — one debt, retired once."""
    f = fleet
    f.run(8)
    f.sup.fail_next = 2  # two boots die mid-announce
    f.spike(60)
    assert f.run(600, until=lambda: f.coord.borrows_total == 1)
    assert f.sup.boot_failures == 2
    assert f.sup.spawn_counts.get("host-2") == 1
    assert f.owners()["host-2"] == FleetOwner.SERVING
    retired = [d for d in f.coord.debts.values() if d["retired"]]
    assert [d["key"] for d in retired] == ["borrow:host-2"]
    assert f.coord.verify() == []


def test_boot_budget_exhausted_aborts_borrow_and_returns_host(
        tmp_path):
    """A host that cannot serve (every boot dies) is handed BACK:
    borrow aborted, world regrown, lease back to TRAINING — the fleet
    is never silently smaller."""
    f = _Fleet(tmp_path)
    try:
        f.run(8)
        f.sup.fail_next = 10 ** 6
        f.spike(60)
        assert f.run(900, until=lambda: (
            f.coord.borrow_aborts_total >= 1
            and f.owners().get("host-2") == FleetOwner.TRAINING))
        aborted = f.coord.debts["borrow:host-2"]
        assert aborted["retired"] and \
            aborted["retired_reason"] == "boot_failed"
        # pressure is still high, so the coordinator may try (and
        # abort) the borrow again — each cycle must stay safe.  End
        # the spike and the fleet converges back to the full world.
        for r in f.requests:
            r.cancel()
        assert f.run(600, until=lambda: (
            not f.coord.migrations
            and f.plane.world_hosts() ==
            ["host-0", "host-1", "host-2"]))
        assert f.owners()["host-2"] == FleetOwner.TRAINING
        assert f.coord.verify() == []
    finally:
        f.close()


def test_borrowed_worker_death_mid_serve_reopens_debt(fleet):
    """A borrowed worker dying while ON LOAN is a new capacity loss:
    the debt reopens as a new episode (PR-8 reopen discipline) and the
    host is re-booted — each episode retired exactly once."""
    f = fleet
    f.run(8)
    f.spike(60)
    assert f.run(600, until=lambda: f.coord.borrows_total == 1)
    # SIGKILL the borrowed worker mid-serve
    name = next(n for n in f.router.replica_names
                if base_replica_name(n) == "host-2")
    f.router.fail_replica(name)
    with f.sup._lock:
        rec = next(r for r in f.sup.workers.values()
                   if base_replica_name(r.name) == "host-2")
    rec.proc.returncode = 9
    assert f.run(200, until=lambda:
                 f.coord.debts_reopened_total == 1)
    assert f.run(200, until=lambda:
                 f.coord.debts["borrow:host-2"]["retired"])
    assert f.serving.worker_joined("host-2")
    assert f.sup.spawn_counts.get("host-2") == 2
    debt = f.coord.debts["borrow:host-2"]
    assert debt["retired_reason"] == "serving_joined"
    assert f.coord.verify() == []


# ------------------------------------------------------------------- F5

def test_master_restart_mid_shrink_converges(tmp_path):
    """The master dies and comes back EMPTY mid-shrink (worst case for
    ground truth): agents re-register, the coordinator's recovery keeps
    journal intent for the silent hosts, and the borrow converges with
    training resuming on the committed step."""
    f = _Fleet(tmp_path)
    try:
        f.run(8)
        f.spike(60)
        f.sup.fail_next = 10 ** 6   # park the migration post-shrink
        assert f.run(400, until=lambda: (
            "host-2" in f.coord.migrations
            and f.coord.migrations["host-2"]["phase"] == "boot"))
        committed = f.plane.last_committed_step
        # master restart: a FRESH rendezvous manager with empty state
        fresh = ElasticTrainingRendezvousManager()
        f.rdzv = fresh
        f.plane.adopt_rdzv(fresh)
        # and the coordinator dies with it — full control-plane loss
        f.coord = FleetCoordinator(
            f.plane, f.serving, journal_path=f.journal_path,
            min_train_hosts=2, borrow_stage=1, dwell_seconds=0.3,
            boot_attempts=4, now=f.t)
        # journal intent survives: hosts 0/1 stay TRAINING-owned even
        # though the fresh master knows nobody yet; host-2 resumes its
        # borrow
        assert f.coord.ledger.owner("host-0") == FleetOwner.TRAINING
        assert f.coord.ledger.owner("host-2") == \
            FleetOwner.MIGRATING_OUT
        f.sup.fail_next = 0
        assert f.run(600, until=lambda: f.coord.borrows_total == 1)
        assert f.run(100, until=lambda: len(
            f.plane.world_hosts()) == 2)
        # the survivors re-formed THEIR world and resumed from the
        # committed generation
        assert f.resume_steps and f.resume_steps[-1] >= committed
        assert f.coord.verify() == []
    finally:
        f.close()


# ----------------------------------------------------- guards & ledger

def test_checkpoint_barrier_failure_aborts_borrow(fleet):
    """No commit verdict, no shrink: the release barrier failing rolls
    the lease straight back — the training world never changed."""
    f = fleet
    f.run(8)
    f.ckpt_fail = True
    f.spike(60)
    assert f.run(300, until=lambda: f.coord.borrow_aborts_total >= 1)
    assert f.owners()["host-2"] == FleetOwner.TRAINING
    assert f.plane.world_hosts() == ["host-0", "host-1", "host-2"]
    debt = f.coord.debts["borrow:host-2"]
    assert debt["retired"] and debt["retired_reason"] == "ckpt_failed"
    assert f.coord.verify() == []


def test_starvation_guard_never_borrows_below_min(tmp_path):
    """``min_train_hosts`` is a hard floor: however hard serving
    burns, the coordinator refuses to loan the training world away."""
    f = _Fleet(tmp_path, min_train_hosts=2)
    try:
        f.run(8)
        f.spike(80)
        f.run(400, until=lambda: f.coord.borrows_total == 1)
        # sustained pressure (HIGH: never shed by the brown-out, so
        # admission cannot interfere), but never a second borrow
        from dlrover_tpu.serving.router import PRIORITY_HIGH

        f.spike(80, priority=PRIORITY_HIGH)
        f.run(300)
        training_owned = [h for h, o in f.owners().items()
                          if o == FleetOwner.TRAINING]
        assert len(training_owned) >= 2
        assert f.coord.borrows_total <= 1
    finally:
        f.close()


def test_lease_ledger_contract(tmp_path):
    """Unit contract: undeclared transitions refuse, stale epochs
    fence, the journal round-trips, and a torn journal degrades to
    ground-truth-only recovery instead of crashing."""
    path = str(tmp_path / "leases.json")
    led = LeaseLedger(journal_path=path)
    epoch = led.bump_epoch()
    led.acquire("h0", FleetOwner.TRAINING, epoch, now=1.0)
    # declared edge works
    led.transition("h0", FleetOwner.MIGRATING_OUT, epoch, now=2.0)
    # undeclared edge refuses (TRAINING is not reachable... SERVING
    # direct from MIGRATING_BACK-less state): MIGRATING_OUT ->
    # MIGRATING_BACK is NOT in the spec
    with pytest.raises(LeaseTransitionError):
        led.transition("h0", FleetOwner.MIGRATING_BACK, epoch)
    # stale epoch fences
    with pytest.raises(StaleLeaseError):
        led.transition("h0", FleetOwner.SERVING, epoch - 1)
    assert led.stale_claims_fenced == 1
    # journal round-trip
    led2 = LeaseLedger(journal_path=path)
    assert led2.epoch == epoch
    assert led2.owner("h0") == FleetOwner.MIGRATING_OUT
    # torn journal: unreadable file = start clean, not crash
    with open(path, "w") as fh:
        fh.write('{"epoch": 3, "leases": {tor')
    led3 = LeaseLedger(journal_path=path)
    assert led3.epoch == 0 and led3.owners() == {}
    # the spec itself is total over the enum (mirrors dlint's drift
    # pass at runtime)
    states = {v for k, v in vars(FleetOwner).items()
              if not k.startswith("_")}
    assert set(FLEET_HOST_TRANSITIONS) == states
    for targets in FLEET_HOST_TRANSITIONS.values():
        assert targets, "fleet owner cycle has no terminal states"
        assert set(targets) <= states


def test_fleet_metrics_surface(fleet):
    """Every dlrover_fleet_* gauge is emitted and registered."""
    from dlrover_tpu.utils.metric_registry import METRIC_HELP

    f = fleet
    f.run(8)
    m = f.coord.metrics()
    assert m["dlrover_fleet_hosts_training"] == 3.0
    assert m["dlrover_fleet_lease_epoch"] >= 1.0
    for name in m:
        assert name in METRIC_HELP, f"{name} missing from registry"


# ----------------------------------------------- slow subprocess twin

@pytest.mark.slow
def test_fleet_real_worker_processes_sigkill_mid_serve(tmp_path):
    """Nightly twin with REAL worker subprocesses: the borrow boots an
    actual ``python -m dlrover_tpu.serving.remote.worker`` process on
    the freed host, the process is SIGKILLed while serving (the debt
    reopens, a second real process boots), and the return drains
    zero-lost back to training — driven on the real clock end to end."""
    import signal as _signal

    pytest.importorskip("msgpack", reason="remote fabric frames")
    from dlrover_tpu.master.stats.job_collector import (
        JobMetricCollector,
    )

    rdzv = ElasticTrainingRendezvousManager()
    collector = JobMetricCollector()
    collector.mark_job_start()
    router = ServingRouter(
        scheduler=ContinuousBatchScheduler(block_size=4),
        metrics=RouterMetrics(window_seconds=0.5),
        brownout=BrownoutPolicy(enter_pressure=2.0, exit_pressure=0.5,
                                dwell_seconds=0.2),
    )
    for i in range(2):
        router.join_replica(f"serving-replica-{i}",
                            FakeEngine(slots=2, tokens_per_step=2))
    sup = WorkerSupervisor(router=router, engine="fake",
                           respawn=False, recorder=router.recorder)
    hosts = {f"host-{r}": r for r in range(3)}
    ckpt = Checkpointer(
        str(tmp_path / "ckpt"), saver_mode=SaverMode.LOCAL,
        local_rank=0, local_world_size=1, node_rank=0, node_num=1)
    step_box = {"n": 0}

    def barrier():
        assert ckpt.save_checkpoint(
            step_box["n"], {"w": np.full(64, step_box["n"],
                                         np.float32)},
            StorageType.MEMORY, block=True)
        return step_box["n"]

    plane = TrainingPlane(rdzv, hosts, barrier, collector=collector,
                          min_nodes=1, recorder=router.recorder)
    coord = FleetCoordinator(
        plane, ServingPlane(router, sup),
        journal_path=str(tmp_path / "leases.json"),
        min_train_hosts=2, borrow_stage=1, dwell_seconds=0.3,
        boot_attempts=4)
    last_round = [None]

    def tick():
        expected = set(plane.expected_hosts())
        for h, r in hosts.items():
            if h in expected and not rdzv.joined(r):
                rdzv.join_rendezvous(r, r, 1)
        if rdzv.num_nodes_waiting() > 0:
            for r in rdzv.current_world_ranks():
                rdzv.join_rendezvous(r, r, 1)
        rdzv.get_comm_world(0)
        world = rdzv.current_world_ranks()
        if world and len(world) == plane.target_world:
            if rdzv.rdzv_round != last_round[0]:
                last_round[0] = rdzv.rdzv_round
                restored, st = ckpt.engine.load()
                if st is not None and restored > 0:
                    step_box["n"] = int(restored)
            step_box["n"] += 1
            collector.report_global_step(step_box["n"], time.time())
        sup.poll()
        router.step()
        coord.poll()
        time.sleep(0.005)

    def run_until(cond, budget, what):
        deadline = time.monotonic() + budget
        while not cond():
            assert time.monotonic() < deadline, \
                f"{what}: {coord.migrations} {coord.ledger.owners()}"
            tick()

    try:
        run_until(lambda: rdzv.current_world_ranks(), 30, "world")
        reqs = [router.submit(_prompt(i), 256) for i in range(150)]
        run_until(lambda: coord.borrows_total == 1, 60, "borrow")
        committed = plane.last_committed_step
        # the borrowed host runs a REAL process: SIGKILL it mid-serve
        run_until(lambda: any(
            base_replica_name(n) == "host-2"
            for n in router.replica_names), 30, "join")
        sup.kill("host-2", _signal.SIGKILL)
        run_until(lambda: coord.debts_reopened_total == 1, 60,
                  "debt reopen")
        run_until(lambda: coord.serving.worker_joined("host-2"), 60,
                  "re-boot")
        for r in reqs:
            r.cancel()
        run_until(lambda: coord.returns_total == 1, 90, "return")
        run_until(lambda: len(plane.world_hosts()) == 3, 30, "regrow")
        # invariants: zero lost (every request terminal, none
        # poisoned), committed-step resume, single-owner leases
        assert router.gateway.poisoned == 0
        assert step_box["n"] >= committed
        assert coord.verify() == []
        assert coord.ledger.owners() == {
            h: FleetOwner.TRAINING for h in hosts}
        debt = coord.debts["borrow:host-2"]
        assert debt["retired"]
    finally:
        sup.shutdown()
        ckpt.close()


def test_reboot_budget_exhausted_returns_borrowed_host(fleet):
    """A borrowed host whose worker dies ON LOAN and then refuses every
    re-boot is not serving capacity — the coordinator walks it back to
    training through the declared lease edges (SERVING ->
    MIGRATING_BACK -> TRAINING via the regrow), never jumping them."""
    f = fleet
    f.run(8)
    f.spike(60)
    assert f.run(600, until=lambda: f.coord.borrows_total == 1)
    # kill the borrowed worker and wedge every re-boot
    name = next(n for n in f.router.replica_names
                if base_replica_name(n) == "host-2")
    f.router.fail_replica(name)
    with f.sup._lock:
        rec = next(r for r in f.sup.workers.values()
                   if base_replica_name(r.name) == "host-2")
    rec.proc.returncode = 9
    f.sup.fail_next = 10 ** 6
    assert f.run(400, until=lambda: f.coord.debts_reopened_total == 1)
    assert f.run(600, until=lambda: (
        f.owners().get("host-2") == FleetOwner.TRAINING))
    # the reboot's debt episode retired as boot_failed (read from the
    # recorder NOW — sustained pressure may legitimately re-borrow the
    # host and overwrite the debt entry with a fresh episode)
    assert any(
        e["kind"] == "fleet_debt_retired"
        and e["key"] == "borrow:host-2"
        and e["reason"] == "boot_failed"
        for e in f.router.recorder.events(256))
    f.sup.fail_next = 0
    for r in f.requests:
        r.cancel()
    assert f.run(600, until=lambda: (
        not f.coord.migrations
        and f.plane.world_hosts() == ["host-0", "host-1", "host-2"]))
    debt = f.coord.debts["borrow:host-2"]
    assert debt["retired"]
    assert f.coord.verify() == []


def test_full_control_plane_rebuild_mid_loan(tmp_path):
    """The review scenario: the coordinator PROCESS dies mid-loan and
    the new incarnation rebuilds the TrainingPlane too (a fresh plane
    starts expecting EVERY host).  Recovery must exclude the on-loan
    host from the expected membership — otherwise the strict-world
    rendezvous waits forever for a host that is busy serving and the
    survivors never train."""
    f = _Fleet(tmp_path)
    try:
        f.run(8)
        f.spike(60)
        assert f.run(600, until=lambda: f.coord.borrows_total == 1)
        step_before = f.step_n
        # full restart: new plane (fresh expected set) + new coordinator
        f.plane = TrainingPlane(
            f.rdzv, f.hosts, f._ckpt_barrier,
            collector=f.collector, min_nodes=1,
            recorder=f.router.recorder, wall_clock=lambda: f.t)
        assert f.plane.target_world == 3  # the naive fresh state
        f.coord = FleetCoordinator(
            f.plane, f.serving, journal_path=f.journal_path,
            min_train_hosts=2, borrow_stage=1, dwell_seconds=0.3,
            boot_attempts=4, now=f.t)
        # recovery reconciled the fresh plane with the loan
        assert f.plane.target_world == 2
        assert f.plane.expected_hosts() == ["host-0", "host-1"]
        assert f.coord.ledger.owner("host-2") == FleetOwner.SERVING
        # the survivors keep training (the rendezvous is NOT waiting
        # for the serving host)
        f.run(30)
        assert f.plane.world_hosts() == ["host-0", "host-1"]
        assert f.step_n > step_before
        # and the loan still comes home
        for r in f.requests:
            r.cancel()
        assert f.run(900, until=lambda: f.coord.returns_total == 1)
        f.run(10)
        assert f.plane.world_hosts() == ["host-0", "host-1", "host-2"]
        assert f.coord.verify() == []
    finally:
        f.close()


def test_borrow_refused_when_node_unit_misaligned(tmp_path):
    """Slice alignment: with node_unit=2, borrowing ONE host would
    leave a world size the unit-rounded rendezvous can never form —
    the coordinator must refuse rather than wedge the survivors."""
    f = _Fleet(tmp_path, n_hosts=4, min_train_hosts=1)
    try:
        # the deployment's slice unit, preserved by _apply_params
        f.rdzv.update_rdzv_params(
            min_nodes=4, max_nodes=4, waiting_timeout=0.0,
            node_unit=2)
        f.plane._apply_params()
        assert f.plane.node_unit == 2
        f.run(8)
        assert len(f.plane.world_hosts()) == 4
        f.spike(80)
        f.run(200)
        assert f.coord.borrows_total == 0
        assert all(o == FleetOwner.TRAINING
                   for o in f.owners().values())
        assert len(f.plane.world_hosts()) == 4  # never wedged
    finally:
        f.close()


def test_reboot_counts_apart_from_borrows(fleet):
    """A borrowed worker dying on loan and re-booting is a reopened
    debt episode, NOT a second borrow: borrows_total stays 1 and the
    real decision->join handoff latency is not overwritten by the
    cheap respawn."""
    f = fleet
    f.run(8)
    f.spike(60)
    assert f.run(600, until=lambda: f.coord.borrows_total == 1)
    first_handoff = f.coord.last_borrow_handoff_s
    name = next(n for n in f.router.replica_names
                if base_replica_name(n) == "host-2")
    f.router.fail_replica(name)
    with f.sup._lock:
        rec = next(r for r in f.sup.workers.values()
                   if base_replica_name(r.name) == "host-2")
    rec.proc.returncode = 9
    assert f.run(400, until=lambda:
                 f.coord.worker_reboots_total == 1)
    assert f.coord.borrows_total == 1
    assert f.coord.last_borrow_handoff_s == first_handoff
    assert f.coord.metrics()[
        "dlrover_fleet_worker_reboots_total"] == 1.0


def test_recovery_exclude_does_not_restart_healthy_world(tmp_path):
    """Coordinator bounce with a host on loan: recovery re-excludes
    the serving host, whose rank already left the round at the
    original shrink — the healthy survivors' admitted world must NOT
    be invalidated (no spurious training restart per coordinator
    restart)."""
    f = _Fleet(tmp_path)
    try:
        f.run(8)
        f.spike(60)
        assert f.run(600, until=lambda: f.coord.borrows_total == 1)
        f.run(10)
        round_before = f.rdzv.rdzv_round
        world_before = f.plane.world_hosts()
        assert world_before == ["host-0", "host-1"]
        # clean coordinator restart (plane survives, as in-process)
        f.coord = FleetCoordinator(
            f.plane, f.serving, journal_path=f.journal_path,
            min_train_hosts=2, borrow_stage=1, dwell_seconds=0.3,
            boot_attempts=4, now=f.t)
        # the admitted round survived the recovery untouched
        assert f.rdzv.rdzv_round == round_before
        assert f.plane.world_hosts() == world_before
        f.run(10)
        assert f.rdzv.rdzv_round == round_before, \
            "recovery must not force the survivors to re-rendezvous"
    finally:
        f.close()


def test_recovery_prunes_ghost_journal_leases(tmp_path):
    """A journal naming a decommissioned host must not resurrect it:
    the ghost lease is pruned at recovery, so no phantom return can
    inflate the strict-world target into a size that never forms."""
    path = str(tmp_path / "leases.json")
    led = LeaseLedger(journal_path=path)
    epoch = led.bump_epoch()
    for h in ("host-0", "host-1", "host-2"):
        led.acquire(h, FleetOwner.TRAINING, epoch)
    led.transition("host-2", FleetOwner.MIGRATING_OUT, epoch)
    led.transition("host-2", FleetOwner.SERVING, epoch)
    # host-5: a lease from an inventory that no longer exists
    led.acquire("host-5", FleetOwner.SERVING, epoch)
    f = _Fleet(tmp_path, journal=False)
    try:
        f.journal_path = path
        f.coord = FleetCoordinator(
            f.plane, f.serving, journal_path=path,
            min_train_hosts=2, borrow_stage=1, dwell_seconds=0.3,
            boot_attempts=4, now=f.t)
        assert f.coord.ledger.owner("host-5") is None
        assert set(f.coord.ledger.owners()) <= set(f.hosts)
        f.run(20)
        # the world forms at the real inventory; nothing waits on the
        # ghost, and no phantom return ever targets it
        assert f.plane.world_hosts() == \
            ["host-0", "host-1", "host-2"]
        assert "host-5" not in f.coord.migrations
    finally:
        f.close()
