"""Serving engine: continuous batching, prefill/decode split, int8.

Parity strategy: with fp32 compute the serving engine's greedy decode
must match the training model's full-context greedy decode token for
token (the serving forward is a re-implementation — exact agreement is
the strongest cheap check).  The int8 path is compared against the same
engine serving the DEQUANTIZED weights, isolating the int8 kernel +
activation quantization from the quantization of the weights
themselves.

Reference counterpart: the vLLM backend tests of the reference RL stack
(atorch/atorch/rl/inference_backend/vllm_backend.py).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from dlrover_tpu.models.llama import LlamaConfig, LlamaModel  # noqa: E402
from dlrover_tpu.rl.generation import sample_sequences  # noqa: E402
from dlrover_tpu.serving.engine import InferenceEngine  # noqa: E402
from dlrover_tpu.serving.model import prefill  # noqa: E402
from dlrover_tpu.serving.params import (  # noqa: E402
    serving_params_from_llama,
    serving_params_nbytes,
)


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny(max_seq_len=64, dtype=jnp.float32)
    model = LlamaModel(cfg)
    ids = jax.random.randint(
        jax.random.PRNGKey(1), (3, 8), 0, cfg.vocab_size
    ).astype(jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), ids)
    return cfg, model, variables, ids


def test_greedy_parity_with_training_model(setup):
    cfg, model, variables, ids = setup
    toks_ref, _ = sample_sequences(
        lambda p, t: model.apply(p, t), variables, ids, 10,
        jax.random.PRNGKey(2), temperature=0.0,
    )
    eng = InferenceEngine(cfg, variables, max_slots=2, chunk=4,
                          temperature=0.0)
    toks, mask = eng.generate(np.asarray(ids), 10)
    assert np.array_equal(np.asarray(toks_ref), toks)
    assert mask.shape == toks.shape
    assert (mask[:, :8] == 0).all() and (mask[:, 8:] == 1).all()


def test_prefill_kv_matches_training_cache(setup):
    cfg, model, variables, ids = setup
    sp = serving_params_from_llama(variables, cfg)
    _, ks, vs = prefill(sp, cfg, ids[:1], jnp.int32(8))
    _, cache = model.apply(
        variables, ids[:1], positions=jnp.arange(8), decode=True,
        cache_len=16, mutable=["cache"],
    )
    ck = cache["cache"]["layer_0"]["attn"]["cached_key"][:, :8]
    np.testing.assert_allclose(
        np.asarray(ks[0][:, :8]), np.asarray(ck), atol=1e-6)
    cv = cache["cache"]["layer_1"]["attn"]["cached_value"][:, :8]
    np.testing.assert_allclose(
        np.asarray(vs[1][:, :8]), np.asarray(cv), atol=1e-6)


def test_continuous_batching_matches_solo_runs(setup):
    """More requests than slots, mixed prompt lengths: every request's
    output must equal its single-request (slot-isolated) run — slot
    reuse and batching must not leak state between sequences."""
    cfg, _, variables, _ = setup
    lengths = (3, 8, 5, 12, 7)
    eng = InferenceEngine(cfg, variables, max_slots=2, chunk=4,
                          temperature=0.0)
    rids = [eng.add_request(np.arange(1, n + 1), 8) for n in lengths]
    outs = eng.run()
    assert eng.stats.finished_requests == len(lengths)
    for n, rid in zip(lengths, rids):
        solo = InferenceEngine(cfg, variables, max_slots=1, chunk=4,
                               temperature=0.0)
        srid = solo.add_request(np.arange(1, n + 1), 8)
        assert np.array_equal(solo.run()[srid], outs[rid]), n


def test_eos_stops_generation(setup):
    cfg, model, variables, ids = setup
    # find what greedy generates first, then use THAT token as EOS:
    # generation must stop right after producing it
    eng = InferenceEngine(cfg, variables, max_slots=1, chunk=4,
                          temperature=0.0)
    rid = eng.add_request(np.asarray(ids[0]), 8)
    first = int(eng.run()[rid][0])
    eng2 = InferenceEngine(cfg, variables, max_slots=1, chunk=4,
                           temperature=0.0, eos_token=first)
    rid2 = eng2.add_request(np.asarray(ids[0]), 8)
    out = eng2.run()[rid2]
    assert out[0] == first and out.size == 1


def test_slot_reuse_after_eos_admits_queue(setup):
    cfg, _, variables, _ = setup
    eng = InferenceEngine(cfg, variables, max_slots=1, chunk=4,
                          temperature=0.0)
    r1 = eng.add_request(np.arange(1, 4), 6)
    r2 = eng.add_request(np.arange(4, 10), 6)
    outs = eng.run()
    assert outs[r1].size == 6 and outs[r2].size == 6


def test_int8_prequant_agrees_with_dequantized_weights(setup):
    """int8 engine vs the same engine over explicitly dequantized
    weights: isolates kernel+activation-quant error from weight-quant
    error.  High (not perfect) greedy agreement expected."""
    cfg, _, variables, ids = setup
    eng8 = InferenceEngine(cfg, variables, max_slots=3, chunk=4,
                           temperature=0.0, int8=True)

    def deq(tree):
        if isinstance(tree, dict):
            if set(tree) == {"q", "scale"}:
                return tree["q"].astype(jnp.float32) * tree["scale"]
            return {k: deq(v) for k, v in tree.items()}
        return tree

    # fp engine carrying the int8 engine's own weight-quantization error
    eng_ref = InferenceEngine(cfg, variables, max_slots=3, chunk=4,
                              temperature=0.0)
    eng_ref.params = deq(eng8.params)
    toks8, _ = eng8.generate(np.asarray(ids), 8)
    toksr, _ = eng_ref.generate(np.asarray(ids), 8)
    agree = (toks8[:, 8:] == toksr[:, 8:]).mean()
    assert agree >= 0.7, agree


def test_int8_params_are_smaller(setup):
    cfg, _, variables, _ = setup
    sp = serving_params_from_llama(variables, cfg)
    sp8 = serving_params_from_llama(variables, cfg, int8=True)
    assert serving_params_nbytes(sp8) < 0.45 * serving_params_nbytes(sp)


def test_generate_api_shapes(setup):
    cfg, _, variables, ids = setup
    eng = InferenceEngine(cfg, variables, max_slots=2, chunk=4,
                          temperature=0.7, top_k=20, top_p=0.9, seed=3)
    toks, mask = eng.generate(np.asarray(ids), 5)
    assert toks.shape == (3, 13) and mask.shape == (3, 13)
    assert (toks[:, :8] == np.asarray(ids)).all()
    assert (toks >= 0).all() and (toks < cfg.vocab_size).all()


def test_same_bucket_burst_prefills_in_one_dispatch(setup):
    """A burst of same-bucket prompts admitted together must prefill as
    ONE batched dispatch (vLLM-style batched prefill), and outputs must
    still match single-request runs."""
    cfg, _, variables, _ = setup
    eng = InferenceEngine(cfg, variables, max_slots=4, chunk=4,
                          temperature=0.0)
    lengths = (5, 6, 5, 6)  # all land in the same bucket
    rids = [eng.add_request(np.arange(2, n + 2), 6) for n in lengths]
    outs = eng.run()
    assert eng.stats.finished_requests == 4
    assert eng.stats.prefill_calls == 1, eng.stats
    for n, rid in zip(lengths, rids):
        solo = InferenceEngine(cfg, variables, max_slots=1, chunk=4,
                               temperature=0.0)
        srid = solo.add_request(np.arange(2, n + 2), 6)
        assert np.array_equal(solo.run()[srid], outs[rid]), n


def test_prompt_lookup_draft_finder():
    from dlrover_tpu.serving.speculative import find_draft

    ctx = np.array([5, 6, 7, 8, 9, 5, 6, 7], dtype=np.int32)
    d = find_draft(ctx, 3)
    # tail trigram [5,6,7] occurred at 0; continuation is [8,9,5]... only
    # up to k: [8, 9, 5]
    assert d is not None and d.tolist() == [8, 9, 5]
    assert find_draft(np.array([1, 2, 3, 4]), 3) is None  # no repeat
    assert find_draft(np.array([7]), 3) is None           # too short


def test_speculative_greedy_matches_plain_engine(setup):
    """Speculative decode must commit EXACTLY the plain greedy output —
    greedy verification preserves the distribution; drafts only change
    how many dispatches it takes."""
    cfg, _, variables, ids = setup
    # repetitive prompt => real acceptances
    prompt = np.tile(np.array([3, 5, 7, 9], np.int32), 6)
    plain = InferenceEngine(cfg, variables, max_slots=2, chunk=4,
                            temperature=0.0)
    r0 = plain.add_request(prompt, 12)
    want = plain.run()[r0]

    spec = InferenceEngine(cfg, variables, max_slots=2,
                           temperature=0.0, speculative_k=4)
    r1 = spec.add_request(prompt, 12)
    got = spec.run()[r1]
    assert np.array_equal(want, got), (want, got)
    assert spec.stats.spec_proposed > 0
    # the speculative win: strictly fewer verify dispatches (model
    # forwards) than decode-committed tokens (this case is fully
    # deterministic: greedy, fixed prompt/seed); exact accounting is
    # spec_calls = tokens - accepted up to end-of-budget truncation
    assert spec.stats.spec_accepted > 0
    assert spec.stats.spec_calls < spec.stats.generated_tokens


def test_speculative_sampling_runs_and_commits():
    """Speculation composes with temperature/top-k sampling via exact
    rejection sampling: the engine produces the requested token counts
    and the committed tokens come from a live distribution (spec rounds
    really happened)."""
    cfg = LlamaConfig.tiny(max_seq_len=64, dtype=jnp.float32)
    model = LlamaModel(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32))
    # repetitive prompt: the n-gram lookup finds drafts
    prompt = np.array([5, 6, 7, 5, 6, 7, 5, 6, 7], np.int32)
    eng = InferenceEngine(cfg, variables, max_slots=2,
                          temperature=0.8, top_k=20, speculative_k=4,
                          seed=3)
    rid = eng.add_request(prompt, 16)
    out = eng.run()[rid]
    assert out.size == 16
    assert (out >= 0).all() and (out < cfg.vocab_size).all()
    assert eng.stats.spec_calls > 0
    assert eng.stats.spec_proposed > 0
    assert eng.stats.tokens_per_forward > 0


def test_rejection_commit_preserves_target_distribution():
    """Monte Carlo check of the Leviathan/Chen guarantee with a
    point-mass draft: accept-prob p(d), resample from the zeroed
    residual — the committed token at the drafted position must be an
    EXACT sample from the target distribution."""
    from dlrover_tpu.serving.speculative import rejection_commit

    vocab = 8
    # fixed non-trivial target distribution at every position
    base = np.array([0.30, 0.05, 0.20, 0.02, 0.18, 0.10, 0.05, 0.10])
    logits = jnp.log(jnp.asarray(base, jnp.float32))[None, None, :]
    logits = jnp.tile(logits, (1, 2, 1))  # [B=1, K=2, V]
    drafts = jnp.array([[2]], jnp.int32)  # always draft token 2
    draft_len = jnp.array([1], jnp.int32)

    @jax.jit
    def one(key):
        out, n = rejection_commit(
            logits, drafts, draft_len, key,
            temperature=1.0, top_k=0, top_p=1.0,
        )
        return out[0, 0]

    trials = 4000
    keys = jax.random.split(jax.random.PRNGKey(0), trials)
    samples = np.asarray(jax.vmap(one)(keys))
    freq = np.bincount(samples, minlength=vocab) / trials
    # multinomial std per bin ~ sqrt(p(1-p)/n) <= 0.008; 4 sigma
    np.testing.assert_allclose(freq, base, atol=0.032)


def test_rejection_commit_greedy_matches_argmax_path():
    from dlrover_tpu.serving.speculative import rejection_commit

    logits = jnp.asarray(
        np.random.RandomState(0).randn(2, 4, 16), jnp.float32
    )
    greedy = np.asarray(jnp.argmax(logits, axis=-1))
    # drafts: slot 0 matches the greedy chain for 2 tokens then breaks;
    # slot 1 misses immediately
    drafts = np.zeros((2, 3), np.int32)
    drafts[0, 0] = greedy[0, 0]
    drafts[0, 1] = greedy[0, 1]
    drafts[0, 2] = (greedy[0, 2] + 1) % 16
    drafts[1, 0] = (greedy[1, 0] + 1) % 16
    out, n = rejection_commit(
        jnp.asarray(logits), jnp.asarray(drafts),
        jnp.array([3, 3], jnp.int32), jax.random.PRNGKey(0),
        temperature=0.0, top_k=0, top_p=1.0,
    )
    out, n = np.asarray(out), np.asarray(n)
    assert n[0] == 3 and n[1] == 1
    assert out[0, :3].tolist() == [
        greedy[0, 0], greedy[0, 1], greedy[0, 2]]
    assert out[1, 0] == greedy[1, 0]


def test_speculative_auto_enables_on_repetitive_context():
    """speculative_k='auto' starts in chunk decode, watches the free
    draft hit rate, and switches speculation on for repetitive text."""
    cfg = LlamaConfig.tiny(max_seq_len=128, dtype=jnp.float32)
    model = LlamaModel(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32))
    eng = InferenceEngine(cfg, variables, max_slots=1, chunk=4,
                          temperature=0.0, speculative_k="auto")
    assert eng._spec_state == "watching"
    prompt = np.tile(np.array([3, 9, 4], np.int32), 10)
    rid = eng.add_request(prompt, 48)
    out = eng.run()[rid]
    assert out.size == 48
    assert eng.stats.spec_calls > 0, "auto mode never engaged"
    assert eng.stats.tokens_per_forward > 1.0


def test_full_length_prompt_with_zero_new_tokens(setup):
    """A prompt that fills max_len with max_new_tokens=0 must admit,
    emit its single prefill token, and finish (regression: the
    speculative context buffer write at index max_len)."""
    cfg, _, variables, _ = setup
    eng = InferenceEngine(cfg, variables, max_slots=1, chunk=4,
                          temperature=0.0, speculative_k=4)
    prompt = np.arange(1, cfg.max_seq_len + 1, dtype=np.int32)
    rid = eng.add_request(prompt, 0)
    out = eng.run()[rid]
    assert out.size == 1


def test_serving_greedy_parity_with_attention_bias():
    """Qwen2-style qkv biases flow through the serving layout (fused
    bqkv) — greedy decode must still match the training model exactly."""
    from dlrover_tpu.rl.generation import sample_sequences

    cfg = LlamaConfig.tiny(max_seq_len=64, dtype=jnp.float32,
                           attention_bias=True)
    model = LlamaModel(cfg)
    ids = jax.random.randint(
        jax.random.PRNGKey(5), (2, 8), 0, cfg.vocab_size
    ).astype(jnp.int32)
    variables = model.init(jax.random.PRNGKey(4), ids)
    # perturb biases so the test cannot pass with biases dropped
    import flax

    variables = flax.core.unfreeze(variables)
    import flax.linen as nn

    params = nn.meta.unbox(variables)["params"]
    for lname in ("layer_0", "layer_1"):
        for proj in ("q_proj", "k_proj", "v_proj"):
            params[lname]["attn"][proj]["bias"] = (
                params[lname]["attn"][proj]["bias"] + 0.3
            )
    variables = {"params": params}
    toks_ref, _ = sample_sequences(
        lambda p, t: model.apply(p, t), variables, ids, 8,
        jax.random.PRNGKey(2), temperature=0.0,
    )
    eng = InferenceEngine(cfg, variables, max_slots=2, chunk=4,
                          temperature=0.0)
    toks, _ = eng.generate(np.asarray(ids), 8)
    assert np.array_equal(np.asarray(toks_ref), toks)
