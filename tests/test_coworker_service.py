"""Remote coworker data service (reference coworker_data_service.py /
coworker_dataset.py): CPU-side preprocessing served over gRPC, pulled by
workers with prefetch, failover, and dynamic discovery."""

import numpy as np
import pytest

from dlrover_tpu.trainer.data.coworker_service import (
    CoworkerDataService,
    RemoteBatchIterator,
    discover_coworkers,
)


def _batches(n, base=0):
    for i in range(n):
        yield {"x": np.full((2, 3), base + i, np.float32),
               "i": np.array([base + i])}


def test_single_coworker_round_trip():
    svc = CoworkerDataService(_batches(5), get_timeout_s=2.0)
    svc.start()
    try:
        it = RemoteBatchIterator([f"127.0.0.1:{svc.port}"], prefetch=2)
        got = sorted(int(b["i"][0]) for b in it)
        assert got == [0, 1, 2, 3, 4]
        it.close()
    finally:
        svc.stop()


def test_two_coworkers_merge_streams():
    a = CoworkerDataService(_batches(3, base=0))
    b = CoworkerDataService(_batches(3, base=100))
    a.start(); b.start()
    try:
        it = RemoteBatchIterator(
            [f"127.0.0.1:{a.port}", f"127.0.0.1:{b.port}"]
        )
        got = sorted(int(x["i"][0]) for x in it)
        assert got == [0, 1, 2, 100, 101, 102]
        it.close()
    finally:
        a.stop(); b.stop()


def test_dead_coworker_excluded():
    """A dead address doesn't block the stream; live coworkers carry it."""
    live = CoworkerDataService(_batches(4))
    live.start()
    dead = CoworkerDataService(_batches(1))  # never started
    try:
        it = RemoteBatchIterator(
            [f"127.0.0.1:{dead.port}", f"127.0.0.1:{live.port}"],
            rpc_timeout_s=1.0, max_failures=2,
        )
        got = []
        # dead coworker never reports END; pull the live stream's items
        for _ in range(4):
            got.append(int(next(it)["i"][0]))
        assert sorted(got) == [0, 1, 2, 3]
        it.close()
    finally:
        live.stop()


def test_discovery_via_master_kv(local_master):
    from dlrover_tpu.agent.master_client import MasterClient

    _, addr = local_master
    client = MasterClient(addr, node_id=0, node_type="worker")
    svc = CoworkerDataService(_batches(2))
    svc.start()
    try:
        svc.register(client, "cw0")
        addrs = discover_coworkers(client, ["cw0", "missing"])
        assert len(addrs) == 1 and addrs[0].endswith(f":{svc.port}")
        # worker consumes via discovery-refresh only (no static addrs)
        it = RemoteBatchIterator(
            [], refresh_fn=lambda: [f"127.0.0.1:{svc.port}"],
            refresh_interval_s=0.1,
        )
        vals = sorted(int(b["i"][0]) for b in it)
        assert vals == [0, 1]
        it.close()
    finally:
        svc.stop()


def test_all_dead_terminates_without_refresh():
    """Every coworker excluded + no refresh_fn => clean StopIteration,
    not a hang."""
    dead = CoworkerDataService(_batches(1))  # never started
    it = RemoteBatchIterator(
        [f"127.0.0.1:{dead.port}"], rpc_timeout_s=0.5, max_failures=1,
    )
    with pytest.raises(StopIteration):
        next(it)
    it.close()


def test_producer_error_raises_not_clean_end():
    """A broken input pipeline surfaces as RuntimeError on the worker,
    not as a silently short epoch."""

    def bad_iter():
        yield {"x": np.zeros(2, np.float32)}
        raise IOError("bad shard")

    svc = CoworkerDataService(bad_iter(), get_timeout_s=1.0)
    svc.start()
    try:
        it = RemoteBatchIterator([f"127.0.0.1:{svc.port}"])
        next(it)  # the good batch
        with pytest.raises(RuntimeError, match="pipeline failed"):
            while True:
                next(it)
    finally:
        it.close()
        svc.stop()


def test_excluded_coworker_rejoins_after_refresh():
    """A restarted coworker at a previously-excluded address serves again
    once the refresh re-announces it."""
    svc = CoworkerDataService(_batches(2))
    addr = f"127.0.0.1:{svc.port}"
    # not started yet: first contacts fail and exclude the address
    it = RemoteBatchIterator(
        [addr], rpc_timeout_s=0.5, max_failures=1,
        refresh_fn=lambda: [addr], refresh_interval_s=0.2,
    )
    import time as _t
    _t.sleep(1.0)  # let it fail + exclude
    svc.start()    # "restart" the coworker
    got = sorted(int(b["i"][0]) for b in it)
    assert got == [0, 1]
    it.close()
    svc.stop()
