"""High-level Trainer loop tests (reference parity:
atorch/atorch/trainer/atorch_trainer.py — HF-shaped train/eval/log/
callback/resume loop over the accelerated step)."""

import os
import uuid

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.agent.ckpt_saver import AsyncCheckpointSaver
from dlrover_tpu.models.llama import LlamaConfig, LlamaModel
from dlrover_tpu.trainer.trainer import (
    IntervalStrategy,
    Trainer,
    TrainerCallback,
    TrainingArguments,
)


@pytest.fixture(autouse=True)
def _isolate(monkeypatch):
    job = uuid.uuid4().hex[:8]
    monkeypatch.setenv("DLROVER_JOB_UID", job)
    yield
    AsyncCheckpointSaver.reset()
    for f in os.listdir("/dev/shm"):
        if job in f:
            try:
                os.unlink(os.path.join("/dev/shm", f))
            except OSError:
                pass


GB, SEQ = 8, 16


def _loader(n_batches, vocab, seed=0, batch=GB):
    rng = np.random.RandomState(seed)
    return [
        rng.randint(0, vocab, size=(batch, SEQ)).astype(np.int32)
        for _ in range(n_batches)
    ]


def _make_trainer(tmp_path=None, callbacks=None, **arg_overrides):
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    model = LlamaModel(cfg)
    args = TrainingArguments(
        max_steps=arg_overrides.pop("max_steps", 6),
        num_train_epochs=arg_overrides.pop("num_train_epochs", 10),
        logging_steps=2,
        **arg_overrides,
    )
    return Trainer(
        model,
        args,
        train_dataloader=_loader(4, cfg.vocab_size),
        eval_dataloader=_loader(2, cfg.vocab_size, seed=9),
        callbacks=callbacks,
        global_batch_size=GB,
        micro_batch_per_shard=1,
        seq_len=SEQ,
        checkpoint_dir=str(tmp_path / "ckpt") if tmp_path else None,
        save_storage_interval=4,
    ), cfg


class Recorder(TrainerCallback):
    def __init__(self):
        self.events = []

    def on_train_begin(self, trainer):
        self.events.append("begin")

    def on_step_end(self, trainer, metrics):
        self.events.append(("step", trainer.global_step, metrics["loss"]))

    def on_log(self, trainer, logs):
        self.events.append(("log", logs["step"]))

    def on_evaluate(self, trainer, metrics):
        self.events.append(("eval", metrics["eval_loss"]))

    def on_train_end(self, trainer):
        self.events.append("end")


def test_train_runs_to_max_steps_with_callbacks_and_logs():
    rec = Recorder()
    trainer, _ = _make_trainer(callbacks=[rec])
    out = trainer.train()
    assert out.global_step == 6
    assert out.training_loss > 0
    assert rec.events[0] == "begin" and rec.events[-1] == "end"
    steps = [e[1] for e in rec.events if isinstance(e, tuple)
             and e[0] == "step"]
    assert steps == [1, 2, 3, 4, 5, 6]  # wraps the 4-batch loader
    logged = [e[1] for e in rec.events if isinstance(e, tuple)
              and e[0] == "log"]
    assert logged == [2, 4, 6]
    assert any(h.get("steps_per_sec", 0) > 0 for h in trainer.log_history)


def test_eval_strategy_steps():
    rec = Recorder()
    trainer, _ = _make_trainer(
        callbacks=[rec], eval_strategy=IntervalStrategy.STEPS, eval_steps=3)
    trainer.train()
    evals = [e for e in rec.events if isinstance(e, tuple)
             and e[0] == "eval"]
    assert len(evals) == 2  # steps 3 and 6
    assert all(v > 0 for _, v in evals)


def test_training_loss_decreases_on_repeated_batch():
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    model = LlamaModel(cfg)
    batch = _loader(1, cfg.vocab_size)[0]
    trainer = Trainer(
        model,
        TrainingArguments(max_steps=12, num_train_epochs=100,
                          logging_steps=0),
        train_dataloader=[batch],
        global_batch_size=GB,
        micro_batch_per_shard=1,
        seq_len=SEQ,
    )
    trainer.train()
    out = trainer.elastic.result.eval_step(
        trainer.elastic.state, trainer.elastic._shape_batch(batch))
    final_loss = float(jax.device_get(out["loss"]))
    init_loss = np.log(cfg.vocab_size)  # ~uniform at init
    assert final_loss < init_loss * 0.9


def test_resume_from_checkpoint(tmp_path):
    trainer, cfg = _make_trainer(tmp_path)
    trainer.train()
    assert trainer.global_step == 6

    # a fresh Trainer over the same dir resumes at step 6 and continues
    trainer2, _ = _make_trainer(tmp_path, max_steps=8)
    out = trainer2.train()
    assert out.global_step == 8


def test_lr_schedule_shapes():
    """warmup + cosine/linear/constant schedules (reference
    atorch_trainer.py create_scheduler surface)."""
    from dlrover_tpu.trainer.trainer import TrainingArguments

    args = TrainingArguments(
        learning_rate=1e-3, warmup_steps=10, lr_scheduler_type="cosine",
        min_lr_ratio=0.1,
    )
    sched = args.make_schedule(100)
    assert float(sched(0)) == 0.0
    assert np.isclose(float(sched(10)), 1e-3)
    # cosine decays monotonically to the floor
    assert float(sched(55)) < 1e-3
    assert np.isclose(float(sched(100)), 1e-4, rtol=1e-2)

    lin = TrainingArguments(
        learning_rate=2e-4, warmup_ratio=0.1, lr_scheduler_type="linear"
    ).make_schedule(100)
    assert np.isclose(float(lin(10)), 2e-4)
    assert np.isclose(float(lin(100)), 0.0, atol=1e-9)

    const = TrainingArguments(
        learning_rate=5e-4, lr_scheduler_type="constant"
    ).make_schedule(100)
    assert np.isclose(float(const(77)), 5e-4)

    opt, sched2 = TrainingArguments(learning_rate=1e-3).make_optimizer(50)
    assert hasattr(opt, "update") and sched2 is not None


def test_sft_example_masked_loss_learns():
    """examples/train_sft.py end to end: the prompt-masked SFT loss
    drops substantially on the learnable copy task."""
    import io
    import runpy
    import sys
    from contextlib import redirect_stdout

    argv = sys.argv
    sys.argv = ["train_sft.py", "--steps", "20", "--global-batch", "8",
                "--seq-len", "32", "--vocab", "64"]
    buf = io.StringIO()
    try:
        with redirect_stdout(buf):
            runpy.run_path(
                os.path.join(os.path.dirname(__file__), "..",
                             "examples/train_sft.py"),
                run_name="__main__",
            )
    except SystemExit as e:
        assert e.code == 0
    finally:
        sys.argv = argv
    line = [
        l for l in buf.getvalue().splitlines()
        if "[sft:" in l and "loss" in l
    ][-1]
    # "[sft:full] loss A -> B over N steps ..."
    parts = line.split()
    first, last = float(parts[2]), float(parts[4])
    assert last < first * 0.6, line
