"""Chaos matrix: faults beyond plain node kill (VERDICT r3 item 9).

Scenarios (results table in CHAOS.md; reference:
docs/tech_report/fault_tolerance_exps.md:1-100 — the reference's
fault-injection experiment suite):

1. master restart mid-run      -> agents reconnect, run finishes
2. disk full during persist    -> save degrades, training continues,
                                  memory tier stays restorable
3. shm corruption at restore   -> detected, falls back to storage
4. agent killed during commit  -> partial stage dir never visible;
                                  restart restores last COMMITTED step
"""

import os
import signal
import subprocess
import sys
import time
import uuid

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fresh_job(name):
    os.environ["DLROVER_JOB_UID"] = f"{name}{uuid.uuid4().hex[:6]}"


def _cleanup_shm():
    job = os.environ.get("DLROVER_JOB_UID", "")
    for f in os.listdir("/dev/shm"):
        if job and job in f:
            try:
                os.unlink(os.path.join("/dev/shm", f))
            except OSError:
                pass


# ---------------------------------------------------------------------------
# 1. master restart mid-run
# ---------------------------------------------------------------------------


def test_master_restart_mid_run(tmp_path):
    """Kill the master while an agent trains; a fresh master on the same
    port takes over; the agent's heartbeats/polls recover and the run
    finishes cleanly (reference: the master HA half of its fault
    matrix)."""
    from dlrover_tpu.common.rpc import find_free_port

    work = str(tmp_path)
    port = find_free_port()

    def start_master():
        return subprocess.Popen(
            [sys.executable, "-m", "dlrover_tpu.master.main",
             "--platform", "local", "--port", str(port),
             "--node_num", "1"],
            stdout=open(os.path.join(work, "master.log"), "a"),
            stderr=subprocess.STDOUT,
        )

    master = start_master()
    env = dict(os.environ)
    env.update(
        DLROVER_FORCE_CPU="1",
        XLA_FLAGS="--xla_force_host_platform_device_count=2",
        DLROVER_JOB_UID=f"chaosM{uuid.uuid4().hex[:6]}",
        JAX_PLATFORMS="cpu",
    )
    agent = None
    try:
        time.sleep(2)
        agent = subprocess.Popen(
            [sys.executable, "-m", "dlrover_tpu.agent.launcher",
             "--nnodes=1", "--node_rank=0",
             f"--master-addr=127.0.0.1:{port}",
             "--max-restarts=1", "--monitor-interval=1",
             sys.executable,
             os.path.join(REPO, "examples/train_elastic_spmd.py"),
             "--steps", "8", "--global-batch", "4", "--seq-len", "32",
             "--ckpt-dir", os.path.join(work, "ckpt"),
             "--metrics-file", os.path.join(work, "metrics"),
             "--step-sleep", "1.0"],
            env=env, cwd=REPO,
            stdout=open(os.path.join(work, "agent.log"), "w"),
            stderr=subprocess.STDOUT,
            preexec_fn=os.setsid,
        )
        # wait for training to start
        m0 = os.path.join(work, "metrics.r0")
        deadline = time.time() + 300
        while time.time() < deadline:
            if os.path.exists(m0) and os.path.getsize(m0) > 0:
                break
            assert agent.poll() is None, "agent died before training"
            time.sleep(1)
        else:
            pytest.fail("training never started")

        master.kill()
        master.wait(10)
        time.sleep(3)          # agent sees poll failures meanwhile
        master = start_master()

        rc = agent.wait(300)
        assert rc == 0, f"agent exited {rc} after master restart"
        with open(m0) as f:
            last_step = int(f.read().strip().splitlines()[-1].split()[0])
        assert last_step == 8
    finally:
        if agent is not None and agent.poll() is None:
            try:
                os.killpg(os.getpgid(agent.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
        master.kill()


# ---------------------------------------------------------------------------
# 2. disk full during async persist
# ---------------------------------------------------------------------------


class _DiskFullStorage:
    """Delegating storage whose writes fail with ENOSPC after arming."""

    def __init__(self, inner):
        self._inner = inner
        self.full = False
        self.failed_writes = 0

    def write(self, content, path):
        if self.full:
            self.failed_writes += 1
            raise OSError(28, "No space left on device", path)
        return self._inner.write(content, path)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def test_disk_full_persist_degrades_but_training_continues(tmp_path):
    _fresh_job("chaosDisk")
    from dlrover_tpu.agent.ckpt_saver import AsyncCheckpointSaver
    from dlrover_tpu.common.storage import PosixDiskStorage
    from dlrover_tpu.trainer.flash_checkpoint import (
        Checkpointer,
        SaverMode,
        StorageType,
    )

    storage = _DiskFullStorage(PosixDiskStorage())
    ckpt = Checkpointer(
        str(tmp_path / "ckpt"), storage=storage,
        saver_mode=SaverMode.LOCAL, local_rank=0, local_world_size=1,
        node_rank=0, node_num=1,
    )
    state = {"w": np.arange(64, dtype=np.float32)}
    try:
        assert ckpt.save_checkpoint(1, state, StorageType.DISK)
        ckpt.wait_latest_checkpoint(60)
        storage.full = True           # the disk fills up mid-run
        state2 = {"w": 2.0 * np.arange(64, dtype=np.float32)}
        # persist fails under the hood; the TRAINING-side call must not
        # raise, and the memory tier keeps accepting saves
        ckpt.save_checkpoint(2, state2, StorageType.DISK)
        time.sleep(1.0)               # async persist attempts + fails
        assert storage.failed_writes > 0
        assert ckpt.save_checkpoint(3, state2, StorageType.MEMORY)
        step, loaded = ckpt.load_checkpoint(
            {"w": np.zeros(64, np.float32)})
        assert step == 3              # memory tier still restorable
        np.testing.assert_array_equal(
            np.asarray(loaded["w"]), state2["w"])
        # the disk recovers: persistence works again
        storage.full = False
        assert ckpt.save_checkpoint(4, state2, StorageType.DISK)
        ckpt.wait_latest_checkpoint(60)
    finally:
        ckpt.close()
        AsyncCheckpointSaver.reset()
        _cleanup_shm()


# ---------------------------------------------------------------------------
# 3. shm corruption detected at restore
# ---------------------------------------------------------------------------


def test_shm_corruption_falls_back_to_storage(tmp_path):
    _fresh_job("chaosShm")
    from dlrover_tpu.agent.ckpt_saver import AsyncCheckpointSaver
    from dlrover_tpu.trainer.flash_checkpoint import (
        Checkpointer,
        SaverMode,
        StorageType,
    )

    ckpt = Checkpointer(
        str(tmp_path / "ckpt"), saver_mode=SaverMode.LOCAL,
        local_rank=0, local_world_size=1, node_rank=0, node_num=1,
    )
    state = {"w": np.arange(256, dtype=np.float32)}
    try:
        assert ckpt.save_checkpoint(5, state, StorageType.DISK)
        ckpt.wait_latest_checkpoint(60)
        # corrupt the shm metadata: shard claims more bytes than the
        # segment holds (torn write / bit rot on the metadata channel)
        handler = ckpt.engine._shm_handler
        meta = handler._meta.get()
        for leaf in meta["leaves"].values():
            for shard in leaf["shards"]:
                shard["nbytes"] = shard["nbytes"] * 1000
        handler._meta.set(meta)
        step, loaded = ckpt.load_checkpoint(
            {"w": np.zeros(256, np.float32)})
        assert step == 5              # restored from DISK, not shm
        np.testing.assert_array_equal(np.asarray(loaded["w"]), state["w"])
    finally:
        ckpt.close()
        AsyncCheckpointSaver.reset()
        _cleanup_shm()


# ---------------------------------------------------------------------------
# 4. agent killed during commit
# ---------------------------------------------------------------------------


def test_kill_during_commit_keeps_last_committed_step(tmp_path):
    """A persist that never commits (saver killed between shard write
    and rename) must stay INVISIBLE: restart restores the previous
    committed step; the stale stage dir is tolerated."""
    _fresh_job("chaosCommit")
    from dlrover_tpu.agent.ckpt_saver import AsyncCheckpointSaver
    from dlrover_tpu.trainer.flash_checkpoint import (
        Checkpointer,
        SaverMode,
        StorageType,
    )

    ckpt_dir = str(tmp_path / "ckpt")
    ckpt = Checkpointer(
        ckpt_dir, saver_mode=SaverMode.LOCAL, local_rank=0,
        local_world_size=1, node_rank=0, node_num=1,
    )
    state5 = {"w": 5.0 * np.ones(64, np.float32)}
    state6 = {"w": 6.0 * np.ones(64, np.float32)}
    try:
        assert ckpt.save_checkpoint(5, state5, StorageType.DISK)
        ckpt.wait_latest_checkpoint(60)
        # step 6: shard data lands in the stage dir but the saver dies
        # before commit — emulated by suppressing the commit call
        saver = AsyncCheckpointSaver.get_ckpt_saver()
        real_commit = saver.commit_checkpoint
        saver.commit_checkpoint = lambda *a, **k: None
        ckpt.save_checkpoint(6, state6, StorageType.DISK)
        time.sleep(1.0)
        saver.commit_checkpoint = real_commit
    finally:
        ckpt.close()
        AsyncCheckpointSaver.reset()
        _cleanup_shm()

    # "restart": fresh checkpointer over the same dir, no shm
    _fresh_job("chaosCommit2")
    ckpt2 = Checkpointer(
        ckpt_dir, saver_mode=SaverMode.LOCAL, local_rank=0,
        local_world_size=1, node_rank=0, node_num=1,
    )
    try:
        step, loaded = ckpt2.load_checkpoint(
            {"w": np.zeros(64, np.float32)})
        assert step == 5, f"uncommitted step leaked: {step}"
        np.testing.assert_array_equal(
            np.asarray(loaded["w"]), state5["w"])
    finally:
        ckpt2.close()
        AsyncCheckpointSaver.reset()
        _cleanup_shm()
