"""Compute-path tests on a virtual 8-device CPU mesh (conftest sets
XLA_FLAGS=--xla_force_host_platform_device_count=8).

Mirrors the reference's testing trick of running distributed behavior in
tiny worlds on CPU (reference: atorch/atorch/tests/common_tests/
distributed_test.py — multiprocessing.spawn gloo worlds; here a single
process with a multi-device CPU mesh, the JAX-native equivalent).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.accel.accelerate import AccelerateConfig, accelerate
from dlrover_tpu.accel.parallel.mesh import (
    DEFAULT_LOGICAL_RULES,
    MeshSpec,
    logical_to_spec,
)
from dlrover_tpu.models.llama import LlamaConfig, LlamaModel


def test_mesh_spec_validation():
    spec = MeshSpec(dp=2, fsdp=2, tp=2)
    assert spec.size == 8
    mesh = spec.build_mesh()
    assert mesh.shape["dp"] == 2 and mesh.shape["tp"] == 2
    with pytest.raises(ValueError):
        MeshSpec(dp=3).build_mesh()  # 3 != 8 devices
    assert MeshSpec.for_device_count(8, tp=2).fsdp == 4


def test_logical_to_spec_rules():
    spec = logical_to_spec(("batch", "seq", "act_embed"))
    assert spec == jax.sharding.PartitionSpec(("dp", "fsdp"), ("cp", "sp"))
    # conflicting mesh axis: second user falls back to replication
    spec = logical_to_spec(("heads", "vocab"))
    assert spec == jax.sharding.PartitionSpec("tp")


def test_model_forward_unjitted():
    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    ids = jnp.zeros((2, 16), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), ids)
    import flax.linen as nn

    logits = model.apply(nn.unbox(variables), ids)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert jnp.isfinite(logits.astype(jnp.float32)).all()


def test_scan_layers_matches_loop():
    """scan-over-layers and the python loop build the same computation shape."""
    ids = jnp.zeros((2, 16), jnp.int32)
    for scan in (False, True):
        cfg = LlamaConfig.tiny(scan_layers=scan)
        model = LlamaModel(cfg)
        variables = model.init(jax.random.PRNGKey(0), ids)
        import flax.linen as nn

        logits = model.apply(nn.unbox(variables), ids)
        assert logits.shape == (2, 16, cfg.vocab_size)


def _make_batch(rng, batch, seq, vocab, accum=None):
    shape = (batch, seq) if accum is None else (accum, batch, seq)
    ids = jax.random.randint(rng, shape, 0, vocab).astype(jnp.int32)
    return {"input_ids": ids}


@pytest.mark.parametrize(
    "mesh_spec",
    [
        MeshSpec(dp=8),
        MeshSpec(fsdp=8),
        MeshSpec(dp=2, fsdp=2, tp=2),
        MeshSpec(fsdp=4, tp=2),
    ],
    ids=["dp8", "fsdp8", "dp2fsdp2tp2", "fsdp4tp2"],
)
def test_train_step_shards_and_learns(mesh_spec):
    cfg = LlamaConfig.tiny(scan_layers=True, remat=True)
    model = LlamaModel(cfg)
    res = accelerate(
        model,
        config=AccelerateConfig(mesh_spec=mesh_spec),
        batch_shape=(8, 32),
    )
    state = res.init_fn(jax.random.PRNGKey(0))
    batch = _make_batch(jax.random.PRNGKey(1), 8, 32, cfg.vocab_size)
    losses = []
    for _ in range(3):
        state, metrics = res.train_step(state, batch)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses))
    # same batch repeated => loss must drop
    assert losses[-1] < losses[0]
    assert int(state.step) == 3

    # param sharding actually applied: under tp, mlp kernels are split
    if mesh_spec.tp > 1:
        gate = state.params["layers"]["layer"]["mlp"]["gate_proj"]["kernel"]
        specs = gate.sharding.spec
        assert "tp" in str(specs)


def test_grad_accumulation_fixed_global_batch():
    """accum=2 over half-microbatches ~ one full batch (ElasticTrainer
    fixed-global-batch parity, reference trainer.py:307-327)."""
    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    spec = MeshSpec(dp=8)

    res1 = accelerate(
        model, config=AccelerateConfig(mesh_spec=spec), batch_shape=(16, 32)
    )
    res2 = accelerate(
        model,
        config=AccelerateConfig(mesh_spec=spec, grad_accum_steps=2),
        batch_shape=(8, 32),
    )
    state1 = res1.init_fn(jax.random.PRNGKey(0))
    state2 = res2.init_fn(jax.random.PRNGKey(0))

    full = _make_batch(jax.random.PRNGKey(1), 16, 32, cfg.vocab_size)
    micro = {"input_ids": full["input_ids"].reshape(2, 8, 32)}

    state1, m1 = res1.train_step(state1, full)
    state2, m2 = res2.train_step(state2, micro)
    assert np.isclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-4)
    p1 = state1.params["final_norm"]["scale"]
    p2 = state2.params["final_norm"]["scale"]
    np.testing.assert_allclose(np.asarray(p1), np.asarray(p2), rtol=1e-4)


def test_grad_accum_with_uneven_loss_mask():
    """Token-count weighting: accumulation must match the full-batch step
    even when mask density differs across microbatches."""
    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    spec = MeshSpec(dp=8)
    res1 = accelerate(
        model, config=AccelerateConfig(mesh_spec=spec), batch_shape=(16, 32)
    )
    res2 = accelerate(
        model,
        config=AccelerateConfig(mesh_spec=spec, grad_accum_steps=2),
        batch_shape=(8, 32),
    )
    state1 = res1.init_fn(jax.random.PRNGKey(0))
    state2 = res2.init_fn(jax.random.PRNGKey(0))

    ids = jax.random.randint(jax.random.PRNGKey(1), (16, 32), 0, 256).astype(jnp.int32)
    mask = jnp.zeros((16, 32), jnp.float32)
    # first half: only 2 valid tokens per row; second half: all valid
    mask = mask.at[:8, :2].set(1.0).at[8:, :].set(1.0)
    full = {"input_ids": ids, "loss_mask": mask}
    micro = {
        "input_ids": ids.reshape(2, 8, 32),
        "loss_mask": mask.reshape(2, 8, 32),
    }
    state1, m1 = res1.train_step(state1, full)
    state2, m2 = res2.train_step(state2, micro)
    assert np.isclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-4)
    p1 = state1.params["final_norm"]["scale"]
    p2 = state2.params["final_norm"]["scale"]
    np.testing.assert_allclose(np.asarray(p1), np.asarray(p2), rtol=1e-4)


def test_per_example_positions():
    """2-D positions (packed sequences) must work through RoPE."""
    import flax.linen as nn

    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    ids = jnp.zeros((2, 16), jnp.int32)
    variables = nn.unbox(model.init(jax.random.PRNGKey(0), ids))
    positions = jnp.tile(jnp.arange(8), (2, 2))  # two packed segments
    segs = jnp.repeat(jnp.array([[0, 1]]), 8, axis=1)
    logits = model.apply(variables, ids, positions=positions, segment_ids=segs)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert jnp.isfinite(logits.astype(jnp.float32)).all()


def test_eval_step():
    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    res = accelerate(
        model, config=AccelerateConfig(mesh_spec=MeshSpec(dp=8)), batch_shape=(8, 32)
    )
    state = res.init_fn(jax.random.PRNGKey(0))
    out = res.eval_step(state, _make_batch(jax.random.PRNGKey(1), 8, 32, 256))
    assert np.isfinite(float(out["loss"]))


def test_chunked_loss_matches_plain():
    """fused_lm_head_loss (chunked, never materializes logits) must match
    the plain logits loss in value and gradients."""
    import jax
    import jax.numpy as jnp
    import flax.linen as nn

    from dlrover_tpu.accel.accelerate import default_loss_fn
    from dlrover_tpu.models.llama import LlamaConfig, LlamaModel

    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    ids = jax.random.randint(
        jax.random.PRNGKey(1), (2, 32), 0, cfg.vocab_size
    ).astype(jnp.int32)
    params = nn.unbox(model.init(jax.random.PRNGKey(0), ids))["params"]
    batch = {"input_ids": ids}
    plain = default_loss_fn(model)
    chunked = default_loss_fn(model, loss_chunk_size=8)
    l1, a1 = plain(params, batch)
    l2, a2 = chunked(params, batch)
    assert float(a1["weight"]) == float(a2["weight"])
    assert abs(float(l1) - float(l2)) < 2e-3
    g1 = jax.grad(lambda p: plain(p, batch)[0])(params)
    g2 = jax.grad(lambda p: chunked(p, batch)[0])(params)
    diffs = jax.tree_util.tree_map(
        lambda a, b: float(jnp.max(jnp.abs(a - b))), g1, g2
    )
    assert max(jax.tree_util.tree_leaves(diffs)) < 2e-2


def test_chunked_loss_mask_shift_matches_plain():
    """A user loss_mask must select the same target tokens in both paths
    (the chunked path shifts it to label positions internally)."""
    import jax
    import jax.numpy as jnp
    import flax.linen as nn

    from dlrover_tpu.accel.accelerate import default_loss_fn
    from dlrover_tpu.models.llama import LlamaConfig, LlamaModel

    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    ids = jax.random.randint(
        jax.random.PRNGKey(3), (2, 32), 0, cfg.vocab_size
    ).astype(jnp.int32)
    mask = (jax.random.uniform(jax.random.PRNGKey(4), (2, 32)) > 0.4).astype(
        jnp.float32
    )
    params = nn.unbox(model.init(jax.random.PRNGKey(0), ids))["params"]
    batch = {"input_ids": ids, "loss_mask": mask}
    l1, a1 = default_loss_fn(model)(params, batch)
    l2, a2 = default_loss_fn(model, loss_chunk_size=8)(params, batch)
    assert float(a1["weight"]) == float(a2["weight"])
    assert abs(float(l1) - float(l2)) < 2e-3


def test_ulysses_attention_numerics():
    """Explicit seq<->heads all-to-all path must match plain attention
    exactly (reference _SeqAllToAll, atorch distributed.py:474-501)."""
    from dlrover_tpu.ops.attention import (
        _xla_attention,
        ulysses_attention,
    )

    mesh = MeshSpec(dp=2, sp=2, tp=2).build_mesh()
    b, s, hq, hkv, d = 4, 32, 8, 4, 16
    q = jax.random.normal(jax.random.PRNGKey(0), (b, s, hq, d), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, s, hkv, d), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, s, hkv, d), jnp.float32)
    seg = jnp.concatenate(
        [jnp.zeros((b, s // 2), jnp.int32), jnp.ones((b, s // 2), jnp.int32)],
        axis=1,
    )
    ref = _xla_attention(q, k, v, causal=True, segment_ids=seg, scale=None)

    @jax.jit
    def run(q, k, v, seg):
        return ulysses_attention(
            q, k, v, mesh=mesh, causal=True, segment_ids=seg
        )

    with mesh:
        out = run(q, k, v, seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    # no segment ids path
    ref2 = _xla_attention(q, k, v, causal=True, segment_ids=None, scale=None)

    @jax.jit
    def run2(q, k, v):
        return ulysses_attention(q, k, v, mesh=mesh, causal=True)

    with mesh:
        out2 = run2(q, k, v)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref2), atol=1e-5)


def test_train_step_sp_ulysses_parity():
    """sp=2 (Ulysses all-to-all engaged via mesh dispatch) must match the
    sp=1 loss trajectory on identical data."""
    cfg = LlamaConfig.tiny(num_heads=8, num_kv_heads=4)
    model = LlamaModel(cfg)
    res_sp = accelerate(
        model,
        config=AccelerateConfig(mesh_spec=MeshSpec(dp=2, sp=2, tp=2)),
        batch_shape=(8, 32),
    )
    res_base = accelerate(
        model,
        config=AccelerateConfig(mesh_spec=MeshSpec(dp=8)),
        batch_shape=(8, 32),
    )
    state_sp = res_sp.init_fn(jax.random.PRNGKey(0))
    state_base = res_base.init_fn(jax.random.PRNGKey(0))
    batch = _make_batch(jax.random.PRNGKey(1), 8, 32, cfg.vocab_size)

    # The Ulysses path must actually engage — a silent fallback to GSPMD
    # would also pass the loss-parity assertion below.
    import dlrover_tpu.ops.attention as attn_mod

    calls = {"n": 0}
    real = attn_mod.ulysses_attention

    def spy(*a, **kw):
        calls["n"] += 1
        return real(*a, **kw)

    attn_mod.ulysses_attention = spy
    try:
        state_sp, _ = res_sp.train_step(state_sp, batch)
    finally:
        attn_mod.ulysses_attention = real
    assert calls["n"] > 0, "Ulysses dispatch did not engage under sp=2"
    state_base, _ = res_base.train_step(state_base, batch)

    for _ in range(2):
        state_sp, m_sp = res_sp.train_step(state_sp, batch)
        state_base, m_base = res_base.train_step(state_base, batch)
        assert np.isclose(
            float(m_sp["loss"]), float(m_base["loss"]), rtol=2e-3
        ), (float(m_sp["loss"]), float(m_base["loss"]))


def test_offload_optimizer_states_to_host():
    """opt states live in pinned host memory; params stay on device; the
    train step streams them through the update (adam_offload parity).

    The CPU SPMD partitioner in this XLA build rejects memory-kind
    placement annotations ("Side-effect ops cannot be replicated"), so
    on the CPU mesh this skips — the path is validated on real TPU
    (single-chip run: pinned_host states, loss descends, states stay
    host-resident after steps).
    """
    from dlrover_tpu.accel.accelerate import AccelerateConfig, accelerate

    cfg = LlamaConfig.tiny(max_seq_len=64)
    res = accelerate(
        LlamaModel(cfg),
        config=AccelerateConfig(
            mesh_spec=MeshSpec.for_device_count(8),
            offload_optimizer_states=True,
        ),
        batch_shape=(8, 64),
    )
    try:
        state = res.init_fn(jax.random.PRNGKey(0))
    except Exception as e:  # jax.errors.JaxRuntimeError on CPU SPMD
        if "annotate_device_placement" in str(e) or "Side-effect" in str(e):
            pytest.skip("backend does not support memory-kind SPMD")
        raise
    kinds = {
        leaf.sharding.memory_kind
        for leaf in jax.tree_util.tree_leaves(state.opt_state)
        if leaf.ndim >= 1
    }
    assert kinds == {"pinned_host"}, kinds
    param_kinds = {
        leaf.sharding.memory_kind
        for leaf in jax.tree_util.tree_leaves(state.params)
    }
    assert "pinned_host" not in param_kinds
    ids = jax.random.randint(
        jax.random.PRNGKey(1), (8, 64), 0, cfg.vocab_size
    ).astype(jnp.int32)
    state, metrics = res.train_step(state, {"input_ids": ids})
    assert float(metrics["loss"]) > 0
    # states remain host-resident after the step (no silent migration)
    kinds = {
        leaf.sharding.memory_kind
        for leaf in jax.tree_util.tree_leaves(state.opt_state)
        if leaf.ndim >= 1
    }
    assert kinds == {"pinned_host"}, kinds


def test_offload_streaming_roundtrip_logic():
    """Backend-independent check of _offload_streaming: the wrapped
    update must hand the inner tx a device-kind state and return a
    pinned_host-kind state, leaving scalar / unsharded leaves untouched
    (covers the wrapper even where memory kinds are unsupported)."""
    import optax

    from dlrover_tpu.accel import accelerate as accel_mod
    from dlrover_tpu.accel.accelerate import _offload_streaming

    mesh = jax.sharding.Mesh(np.array(jax.devices()[:1]), ("dp",))
    sh = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())

    moved = []
    real_device_put = jax.device_put

    def fake_device_put(x, dst):
        moved.append((getattr(x, "_tag", "?"), dst.memory_kind))
        y = np.asarray(x).view(np.ndarray).copy()
        out = _Tagged(y, dst.memory_kind)
        return out

    class _Tagged(np.ndarray):
        def __new__(cls, arr, tag):
            obj = np.asarray(arr).view(cls)
            obj._tag = tag
            return obj

    seen = {}

    def inner_update(grads, state, params=None):
        seen["state"] = state
        return grads, state

    tx = optax.GradientTransformation(lambda p: None, inner_update)
    cell = {"tree": {"mu": sh, "count": sh}}
    wrapped = _offload_streaming(tx, cell)

    state = {"mu": _Tagged(np.ones((4,)), "host"), "count": np.int32(3)}
    grads = {"mu": np.ones((4,)), "count": np.int32(0)}
    jax.device_put = fake_device_put
    try:
        _, new_state = wrapped.update(grads, state, None)
    finally:
        jax.device_put = real_device_put
    # inner tx saw the device-kind copy of the vector state
    assert seen["state"]["mu"]._tag == "device"
    # scalar (ndim 0) leaf passed through both directions untouched
    assert seen["state"]["count"] == 3
    assert int(new_state["count"]) == 3
    # returned vector state went back to pinned_host
    assert new_state["mu"]._tag == "pinned_host"
    kinds = [k for _, k in moved]
    assert kinds == ["device", "pinned_host"], kinds


def test_chunked_loss_under_tensor_parallel_vocab():
    """Vocab-parallel cross entropy (reference distributed_modules/
    cross_entropy.py): the chunked fused loss must agree with the plain
    loss when the lm_head vocab dim is tp-sharded."""
    from dlrover_tpu.accel.accelerate import AccelerateConfig, accelerate

    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    ids = jax.random.randint(
        jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size
    ).astype(jnp.int32)
    losses = {}
    for chunk in (None, 8):
        res = accelerate(
            LlamaModel(cfg),
            config=AccelerateConfig(
                mesh_spec=MeshSpec.for_device_count(8, tp=2),
                loss_chunk_size=chunk,
            ),
            batch_shape=(8, 32),
        )
        state = res.init_fn(jax.random.PRNGKey(0))
        _, metrics = res.train_step(state, {"input_ids": ids})
        losses[chunk] = float(metrics["loss"])
    np.testing.assert_allclose(losses[8], losses[None], rtol=1e-5)


def test_offload_remat_policies_resolve():
    """Selective activation offloading policies (reference
    selective_offloading_checkpoint.py:252) resolve to callables; the
    execution path needs a real TPU (XLA host memory spaces), covered
    by benchmarks/offload_probe.py."""
    from dlrover_tpu.models.llama import resolve_remat_policy

    assert callable(resolve_remat_policy("offload_dots"))
    assert callable(resolve_remat_policy("offload_names:mlp_out,attn_out"))
    assert callable(resolve_remat_policy("names:qkv_proj"))
    assert callable(
        resolve_remat_policy("dots_with_no_batch_dims_saveable"))
