"""ElasticTrainer: fixed global batch across world sizes + flash-ckpt
resume (reference behavior: dlrover/trainer/torch/elastic/trainer.py
:307-327 grad-accum adjustment; tests mirror
dlrover/trainer/tests/torch/elastic_test.py)."""

import os
import uuid

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.accel.parallel.mesh import MeshSpec
from dlrover_tpu.agent.ckpt_saver import AsyncCheckpointSaver
from dlrover_tpu.models.llama import LlamaConfig, LlamaModel
from dlrover_tpu.trainer.elastic.trainer import (
    ElasticTrainer,
    plan_global_batch,
)
from dlrover_tpu.trainer.flash_checkpoint import SaverMode, StorageType


@pytest.fixture(autouse=True)
def _isolate(monkeypatch):
    job = uuid.uuid4().hex[:8]
    monkeypatch.setenv("DLROVER_JOB_UID", job)
    yield
    AsyncCheckpointSaver.reset()
    for f in os.listdir("/dev/shm"):
        if job in f:
            try:
                os.unlink(os.path.join("/dev/shm", f))
            except OSError:
                pass


def test_plan_global_batch_adjusts_accum():
    spec8 = MeshSpec(fsdp=8)
    spec4 = MeshSpec(fsdp=4)
    spec2 = MeshSpec(dp=2)
    p8 = plan_global_batch(32, spec8, micro_batch_per_shard=2)
    p4 = plan_global_batch(32, spec4, micro_batch_per_shard=2)
    p2 = plan_global_batch(32, spec2, micro_batch_per_shard=2)
    assert (p8.grad_accum_steps, p4.grad_accum_steps, p2.grad_accum_steps) == (2, 4, 8)
    for p in (p8, p4, p2):
        assert p.micro_batch_global * p.grad_accum_steps == 32
    with pytest.raises(ValueError):
        plan_global_batch(30, spec8, micro_batch_per_shard=2)


def _model():
    # fp32 end to end for a tight trajectory comparison
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    return LlamaModel(cfg), cfg


def _batch(step: int, global_batch: int, seq: int, vocab: int) -> np.ndarray:
    rng = np.random.RandomState(1000 + step)
    return rng.randint(0, vocab, size=(global_batch, seq)).astype(np.int32)


def test_scale_up_resumes_with_identical_trajectory(tmp_path):
    """3 steps on a 4-device world, save, restart on 8 devices, 3 more
    steps: the loss trajectory must match an uninterrupted 8-device run
    (same fixed global batch, resharded restored state)."""
    devices = jax.devices()
    assert len(devices) >= 8
    seq, gb = 32, 16
    ckpt_dir = str(tmp_path / "ckpt")

    # --- uninterrupted reference run: 8 devices, 6 steps ---
    model, cfg = _model()
    ref = ElasticTrainer(
        model, global_batch_size=gb, micro_batch_per_shard=2, seq_len=seq,
    )
    ref.prepare(devices=devices[:8])
    assert ref.plan.grad_accum_steps == 1
    ref.restore_or_init(jax.random.PRNGKey(0))
    ref_losses = []
    for s in range(6):
        m = ref.train_step(_batch(s, gb, seq, cfg.vocab_size))
        ref_losses.append(float(m["loss"]))

    # --- elastic run, phase A: 4 devices (accum 2) ---
    model2, _ = _model()
    tr = ElasticTrainer(
        model2, global_batch_size=gb, micro_batch_per_shard=2, seq_len=seq,
        checkpoint_dir=ckpt_dir, saver_mode=SaverMode.LOCAL,
    )
    tr.prepare(devices=devices[:4])
    assert tr.plan.grad_accum_steps == 2
    assert tr.restore_or_init(jax.random.PRNGKey(0)) == 0
    a_losses = [
        float(tr.train_step(_batch(s, gb, seq, cfg.vocab_size))["loss"])
        for s in range(3)
    ]
    assert tr.save(StorageType.MEMORY)
    pre_restart_step = tr.step
    tr.close()

    # --- phase B: "restarted" onto 8 devices, restore + continue ---
    model3, _ = _model()
    tr2 = ElasticTrainer(
        model3, global_batch_size=gb, micro_batch_per_shard=2, seq_len=seq,
        checkpoint_dir=ckpt_dir, saver_mode=SaverMode.LOCAL,
    )
    tr2.prepare(devices=devices[:8])
    assert tr2.plan.grad_accum_steps == 1
    restored = tr2.restore_or_init(jax.random.PRNGKey(42))
    assert restored == pre_restart_step == 3
    b_losses = [
        float(tr2.train_step(_batch(s, gb, seq, cfg.vocab_size))["loss"])
        for s in range(3, 6)
    ]
    tr2.close()

    # accum-2 on 4 devices must equal full-batch on 8 devices ...
    np.testing.assert_allclose(a_losses, ref_losses[:3], rtol=2e-4, atol=2e-4)
    # ... and the restarted world continues the exact trajectory
    np.testing.assert_allclose(b_losses, ref_losses[3:], rtol=2e-4, atol=2e-4)


def test_persistent_compile_cache_dir(tmp_path):
    """prepare() wires the JAX persistent compilation cache so elastic
    restarts (fresh processes) reuse compiled executables from disk."""
    import jax

    from dlrover_tpu.trainer.elastic.trainer import ElasticTrainer
    from dlrover_tpu.models.llama import LlamaConfig, LlamaModel

    cache = tmp_path / "xla_cache"
    trainer = ElasticTrainer(
        LlamaModel(LlamaConfig.tiny(max_seq_len=32)),
        global_batch_size=8,
        micro_batch_per_shard=1,
        seq_len=32,
        compile_cache_dir=str(cache),
        compile_cache_min_secs=0.0,  # persist even sub-second compiles
    )
    prev_dir = jax.config.jax_compilation_cache_dir
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    try:
        trainer.prepare()
        assert jax.config.jax_compilation_cache_dir == str(cache)
        trainer.restore_or_init(jax.random.PRNGKey(0))
        import numpy as np
        import jax.numpy as jnp

        shape = (trainer.plan.micro_batch_global, 32)
        if trainer.plan.grad_accum_steps > 1:
            shape = (trainer.plan.grad_accum_steps,) + shape
        ids = jnp.zeros(shape, jnp.int32)
        metrics = trainer.train_step(ids)
        assert np.isfinite(float(metrics["loss"]))
        # the executable landed in the on-disk cache
        assert cache.exists() and any(cache.iterdir())
    finally:
        # restore global jax config for the rest of the suite
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", prev_min
        )
