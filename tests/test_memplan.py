"""Memory planner tests (VERDICT r4 #8): the 7B plan derives from the
real sharding rules and gates admission with an offload suggestion.
Reference counterpart: atorch/examples/llama2/README.md:395-411."""

import pytest

from dlrover_tpu.accel.memplan import hbm_budget, plan_memory
from dlrover_tpu.accel.parallel.mesh import MeshSpec
from dlrover_tpu.models.llama import LlamaConfig, LlamaModel


@pytest.fixture(scope="module")
def llama7b():
    return LlamaModel(LlamaConfig.llama2_7b())


def test_7b_admitted_on_16_device_v5p(llama7b):
    plan = plan_memory(
        llama7b, MeshSpec(fsdp=16), (16, 4096),
        hbm_budget_bytes=hbm_budget("v5p"),
    )
    assert plan.fits is True
    # fp32 master params: 7B x 4 bytes / 16 devices ~ 1.6 GiB
    assert 1.3 < plan.params_bytes / 1024**3 < 1.9
    # adam m+v doubles it
    assert abs(plan.opt_device_bytes - 2 * plan.params_bytes) \
        < 0.05 * plan.opt_device_bytes


def test_7b_rejected_on_v5e8_with_actionable_suggestion(llama7b):
    """Rejection carries the cheapest fix that fits: int8 moments when
    they suffice (seq 4k), offload when only the bigger hammer does
    (seq 8k — activations grew past what int8 moments can buy back)."""
    plan = plan_memory(
        llama7b, MeshSpec(fsdp=8), (8, 4096),
        hbm_budget_bytes=hbm_budget("v5e"),
    )
    assert plan.fits is False
    assert "quantized_adamw" in plan.suggestion

    plan8k = plan_memory(
        llama7b, MeshSpec(fsdp=8), (8, 8192),
        hbm_budget_bytes=hbm_budget("v5e"),
    )
    assert plan8k.fits is False
    assert "offload_optimizer_states" in plan8k.suggestion
    # and the suggested offload variant indeed fits
    offloaded = plan_memory(
        llama7b, MeshSpec(fsdp=8), (8, 8192),
        offload_optimizer=True,
        hbm_budget_bytes=hbm_budget("v5e"),
    )
    assert offloaded.fits is True
    assert offloaded.opt_device_bytes == 0
    assert offloaded.opt_host_bytes > 0


def test_int8_moments_shrink_optimizer_state(llama7b):
    base = plan_memory(llama7b, MeshSpec(fsdp=16), (16, 4096))
    q = plan_memory(
        llama7b, MeshSpec(fsdp=16), (16, 4096),
        optimizer="quantized_adamw",
    )
    # int8 m+v + block scales ~ 2.06/8 of fp32 m+v
    ratio = q.opt_device_bytes / base.opt_device_bytes
    assert 0.24 < ratio < 0.28, ratio


def test_tp_and_pp_shard_the_plan(llama7b):
    fsdp = plan_memory(llama7b, MeshSpec(fsdp=16), (16, 4096))
    tp = plan_memory(llama7b, MeshSpec(fsdp=8, tp=2), (16, 4096))
    # same device count -> same order of param bytes (different axes)
    assert abs(tp.params_bytes - fsdp.params_bytes) \
        < 0.25 * fsdp.params_bytes
    pp = plan_memory(llama7b, MeshSpec(fsdp=8, pp=2), (16, 4096))
    assert abs(pp.params_bytes - fsdp.params_bytes) \
        < 0.25 * fsdp.params_bytes


def test_seq32k_offload_variant_matches_perf_table(llama7b):
    """The PERF.md offload result (seq-32k trainable on 16 GB with
    selective offload) must be consistent with the planner's verdicts:
    plain adamw at seq 32k overflows v5e, offload fits."""
    base = plan_memory(
        llama7b, MeshSpec(fsdp=16), (16, 32768),
        hbm_budget_bytes=hbm_budget("v5e"),
    )
    offload = plan_memory(
        llama7b, MeshSpec(fsdp=16), (16, 32768),
        offload_optimizer=True,
        hbm_budget_bytes=hbm_budget("v5e"),
    )
    assert base.total_device_bytes > offload.total_device_bytes


def test_spec_tree_mismatch_falls_back_to_replicated():
    """ADVICE r5: a spec/param tree length mismatch must NOT zip
    misaligned lists (sharded byte counts attributed to the wrong
    leaves) — every leaf is treated as replicated, so the estimate is a
    conservative upper bound."""
    from jax.sharding import PartitionSpec

    from dlrover_tpu.accel.memplan import _align_specs

    specs = [PartitionSpec("fsdp"), None]
    assert _align_specs(specs, 2) is specs  # aligned: untouched
    assert _align_specs(specs, 5) == [None] * 5  # short: all replicated
    assert _align_specs(specs, 1) == [None]      # long: all replicated
