"""Distributed master node lifecycle tests over the in-memory scheduler
(the reference's pattern: mocked cluster + real managers, reference:
dlrover/python/tests/test_job_manager.py)."""

import time

import pytest

from dlrover_tpu.common.constants import (
    NodeEventType,
    NodeStatus,
    NodeType,
    RendezvousName,
)
from dlrover_tpu.common.node import Node
from dlrover_tpu.master.elastic_training.rdzv_manager import (
    ElasticTrainingRendezvousManager,
)
from dlrover_tpu.master.node.event_callback import (
    RendezvousMembershipCallback,
    TaskRescheduleCallback,
)
from dlrover_tpu.master.node.job_manager import JobManager
from dlrover_tpu.master.node.status_flow import get_node_state_flow
from dlrover_tpu.master.monitor.speed_monitor import SpeedMonitor
from dlrover_tpu.master.shard.task_manager import TaskManager
from dlrover_tpu.scheduler.in_memory import (
    InMemoryCluster,
    InMemoryNodeWatcher,
    InMemoryScaler,
)


def _wait(cond, timeout=10.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


@pytest.fixture()
def manager():
    cluster = InMemoryCluster()
    jm = JobManager(
        scaler=InMemoryScaler(cluster),
        watcher=InMemoryNodeWatcher(cluster),
        worker_num=2,
        heartbeat_timeout=30.0,
        max_relaunch_count=2,
    )
    yield jm, cluster
    jm.stop()


def test_status_flow_table():
    flow = get_node_state_flow(
        NodeStatus.PENDING, NodeEventType.MODIFIED, NodeStatus.RUNNING
    )
    assert flow and not flow.should_relaunch
    flow = get_node_state_flow(
        NodeStatus.RUNNING, NodeEventType.MODIFIED, NodeStatus.FAILED
    )
    assert flow and flow.should_relaunch
    flow = get_node_state_flow(
        NodeStatus.RUNNING, NodeEventType.DELETED, NodeStatus.DELETED
    )
    assert flow and flow.should_relaunch
    # terminal: a succeeded node never relaunches
    flow = get_node_state_flow(
        NodeStatus.SUCCEEDED, NodeEventType.DELETED, NodeStatus.DELETED
    )
    assert flow and not flow.should_relaunch
    assert (
        get_node_state_flow(
            NodeStatus.RUNNING, NodeEventType.MODIFIED, NodeStatus.RUNNING
        )
        is None
    )


def test_start_creates_and_tracks_workers(manager):
    jm, cluster = manager
    jm.start()
    assert _wait(
        lambda: sum(
            n.status == NodeStatus.RUNNING
            for n in jm.job_nodes[NodeType.WORKER].values()
        )
        == 2
    ), jm.get_job_detail()
    ranks = sorted(
        n.rank_index for n in jm.job_nodes[NodeType.WORKER].values()
    )
    assert ranks == [0, 1]


def test_node_failure_event_triggers_relaunch(manager):
    jm, cluster = manager
    jm.start()
    assert _wait(lambda: len(cluster.nodes) == 2)
    victim = sorted(cluster.nodes)[0]
    cluster.fail_node(victim)
    # a replacement (same rank) must be launched and reach RUNNING
    assert _wait(
        lambda: sum(
            n.status == NodeStatus.RUNNING
            for n in jm.job_nodes[NodeType.WORKER].values()
        )
        == 2
        and any(
            n.relaunch_count == 1
            for n in jm.job_nodes[NodeType.WORKER].values()
        )
    ), jm.get_job_detail()


def test_heartbeat_timeout_synthesizes_failure_and_recovers(manager):
    """Silent node => dead-node event => data shards recovered, rendezvous
    membership shrinks, replacement launched (VERDICT item 4 'done')."""
    jm, cluster = manager
    task_manager = TaskManager(0, SpeedMonitor())
    task_manager.new_dataset(
        batch_size=2, dataset_size=8, dataset_name="ds",
        num_minibatches_per_shard=1,
    )
    rdzv = ElasticTrainingRendezvousManager()
    rdzv.update_rdzv_params(2, 2, 10, 1)
    jm.add_node_event_callback(TaskRescheduleCallback(task_manager))
    jm.add_node_event_callback(
        RendezvousMembershipCallback(
            {RendezvousName.ELASTIC_TRAINING: rdzv}
        )
    )
    jm.start()
    assert _wait(lambda: len(jm.job_nodes[NodeType.WORKER]) >= 2)

    # both agents heartbeat (by rank); rank 0 takes a data shard
    now = time.time()
    jm.collect_node_heart_beat(NodeType.WORKER, 0, now)
    jm.collect_node_heart_beat(NodeType.WORKER, 1, now)
    rdzv.join_rendezvous(0, 0, 1)
    rdzv.join_rendezvous(1, 1, 1)
    task = task_manager.get_dataset_task(0, "ds")
    assert task.task_id >= 0
    dataset = task_manager.get_dataset("ds")
    assert len(dataset.doing) == 1

    # rank 0 goes silent: check at now+60 (timeout 30)
    node0 = next(
        n for n in jm.job_nodes[NodeType.WORKER].values()
        if n.rank_index == 0
    )
    node1 = next(
        n for n in jm.job_nodes[NodeType.WORKER].values()
        if n.rank_index == 1
    )
    node1.update_heartbeat(now + 55)  # rank 1 stays alive
    dead = jm.check_heart_beats(now=now + 60)
    assert [n.rank_index for n in dead] == [0]
    assert node0.status == NodeStatus.DELETED
    # shard recovered for re-dispatch
    assert len(dataset.doing) == 0
    assert not dataset.completed()
    # replacement for rank 0 launched by the scaler
    assert _wait(
        lambda: any(
            n.rank_index == 0 and n.status == NodeStatus.RUNNING
            and n.relaunch_count == 1
            for n in jm.job_nodes[NodeType.WORKER].values()
        )
    ), jm.get_job_detail()


def test_relaunch_budget_exhaustion_fails_job(manager):
    jm, cluster = manager
    jm.start()
    assert _wait(lambda: len(cluster.nodes) == 2)
    for _ in range(4):
        running = [
            name for name, n in cluster.nodes.items()
            if n.rank_index == 0 and not n.is_exited()
        ]
        if not running:
            break
        cluster.fail_node(running[0])
        _wait(
            lambda: any(
                n.rank_index == 0 and n.status == NodeStatus.RUNNING
                for n in cluster.nodes.values()
            )
            or jm.any_worker_failed_fatally(),
            timeout=5,
        )
    assert _wait(lambda: jm.any_worker_failed_fatally(), timeout=5)


def test_distributed_master_end_to_end_rpc():
    """Boot the DistributedJobMaster on a real port; agent heartbeats and
    status reports flow through the servicer into the JobManager (round-1
    gap: heartbeats previously landed in job_manager=None)."""
    import threading

    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.common.rpc import find_free_port
    from dlrover_tpu.master.dist_master import DistributedJobMaster

    cluster = InMemoryCluster()
    port = find_free_port()
    master = DistributedJobMaster(
        port,
        scaler=InMemoryScaler(cluster),
        watcher=InMemoryNodeWatcher(cluster),
        node_num=2,
        heartbeat_timeout=30.0,
    )
    master.prepare()
    try:
        clients = [
            MasterClient(f"127.0.0.1:{port}", node_id=r, node_type="worker")
            for r in range(2)
        ]
        for r, c in enumerate(clients):
            c.report_heart_beat(time.time())
        assert _wait(
            lambda: all(
                d.get("heartbeat_age") is not None
                for d in master.job_manager.get_job_detail()["worker"].values()
            )
        ), master.job_manager.get_job_detail()

        # both workers succeed -> master run loop exits 0
        for r, c in enumerate(clients):
            c.report_node_status(r, NodeStatus.SUCCEEDED)
        rc = {}
        t = threading.Thread(
            target=lambda: rc.setdefault("rc", master.run(poll_interval=0.2))
        )
        t.start()
        t.join(15)
        assert rc.get("rc") == 0
        for c in clients:
            c.close()
    finally:
        master.stop()


# -- multi-role jobs (chief / evaluator / PS) ----------------------------


def _role_manager(max_relaunch_count=2, critical_worker_index=None):
    from dlrover_tpu.common.node import NodeGroupResource

    cluster = InMemoryCluster()
    jm = JobManager(
        scaler=InMemoryScaler(cluster),
        watcher=InMemoryNodeWatcher(cluster),
        heartbeat_timeout=30.0,
        max_relaunch_count=max_relaunch_count,
        node_groups={
            NodeType.CHIEF: NodeGroupResource(1),
            NodeType.WORKER: NodeGroupResource(2),
            NodeType.EVALUATOR: NodeGroupResource(1),
            NodeType.PS: NodeGroupResource(2),
        },
        critical_worker_index=critical_worker_index,
    )
    return jm, cluster


def test_multi_role_groups_scheduled_with_criticality():
    """chief/evaluator/ps groups are launched alongside workers and carry
    the reference's criticality policy (training_node.py set_critical_node:
    chief+evaluator always critical, PS per flag, workers per index)."""
    jm, cluster = _role_manager(critical_worker_index={0: 1})
    jm.start()
    try:
        assert _wait(
            lambda: all(
                sum(
                    n.status == NodeStatus.RUNNING
                    for n in jm.job_nodes.get(t, {}).values()
                )
                == c
                for t, c in {
                    NodeType.CHIEF: 1,
                    NodeType.WORKER: 2,
                    NodeType.EVALUATOR: 1,
                    NodeType.PS: 2,
                }.items()
            )
        ), jm.get_job_detail()
        chief = next(iter(jm.job_nodes[NodeType.CHIEF].values()))
        evaluator = next(iter(jm.job_nodes[NodeType.EVALUATOR].values()))
        assert chief.critical and evaluator.critical
        assert all(n.critical for n in jm.job_nodes[NodeType.PS].values())
        workers = {
            n.rank_index: n for n in jm.job_nodes[NodeType.WORKER].values()
        }
        assert workers[0].critical and workers[0].max_relaunch_count == 1
        assert not workers[1].critical
    finally:
        jm.stop()


def test_ps_query_and_training_completion_ignores_live_ps():
    """query_ps_nodes reports the rank-ordered live PS set; the job
    completes when chief+workers+evaluator exit even though PS stays up
    (reference: dist_job_manager.py:655-662)."""
    jm, cluster = _role_manager()
    jm.start()
    try:
        assert _wait(
            lambda: sum(
                n.status == NodeStatus.RUNNING
                for nodes in jm.job_nodes.values()
                for n in nodes.values()
            )
            == 6
        ), jm.get_job_detail()
        metas, ready, failure = jm.query_ps_nodes()
        assert ready and not failure
        assert [m.node_rank for m in metas] == [0, 1]
        assert all(m.node_type == NodeType.PS for m in metas)

        assert not jm.all_workers_exited()
        # every training-role node succeeds; PS nodes keep running
        for t in (NodeType.CHIEF, NodeType.WORKER, NodeType.EVALUATOR):
            for n in list(jm.job_nodes[t].values()):
                jm.update_node_reported_status(t, n.rank_index, NodeStatus.SUCCEEDED)
        assert _wait(jm.all_workers_exited), jm.get_job_detail()
        assert not jm.job_failed()
    finally:
        jm.stop()


def test_critical_ps_failure_beyond_budget_fails_job():
    jm, cluster = _role_manager(max_relaunch_count=0)
    jm.start()
    try:
        assert _wait(
            lambda: sum(
                n.status == NodeStatus.RUNNING
                for n in jm.job_nodes.get(NodeType.PS, {}).values()
            )
            == 2
        ), jm.get_job_detail()
        victim = next(
            name for name, n in cluster.nodes.items() if n.type == NodeType.PS
        )
        cluster.fail_node(victim)
        assert _wait(jm.job_failed, timeout=5), jm.get_job_detail()
        _, _, failure = jm.query_ps_nodes()
        assert failure
    finally:
        jm.stop()


def test_rendezvous_membership_excludes_evaluator_and_ps():
    """Chief/evaluator/PS nodes never enter the SPMD comm world: the
    rendezvous membership callback tracks workers only (ranks are
    per-role, so other roles would alias worker ranks)."""
    jm, cluster = _role_manager()
    rdzv = ElasticTrainingRendezvousManager()
    rdzv.update_rdzv_params(2, 2, 10, 1)
    jm.add_node_event_callback(
        RendezvousMembershipCallback({RendezvousName.ELASTIC_TRAINING: rdzv})
    )
    jm.start()
    try:
        assert _wait(
            lambda: sum(
                n.status == NodeStatus.RUNNING
                for nodes in jm.job_nodes.values()
                for n in nodes.values()
            )
            == 6
        ), jm.get_job_detail()
        # the 2 workers joined; chief + evaluator + 2 ps did not
        assert len(rdzv._alive_nodes) == 2
    finally:
        jm.stop()


def test_noncritical_ps_budget_exhaustion_does_not_fail_job():
    from dlrover_tpu.common.node import NodeGroupResource

    cluster = InMemoryCluster()
    jm = JobManager(
        scaler=InMemoryScaler(cluster),
        watcher=InMemoryNodeWatcher(cluster),
        heartbeat_timeout=30.0,
        max_relaunch_count=0,
        node_groups={
            NodeType.WORKER: NodeGroupResource(1),
            NodeType.PS: NodeGroupResource(1),
        },
        ps_is_critical=False,
    )
    jm.start()
    try:
        assert _wait(
            lambda: sum(
                n.status == NodeStatus.RUNNING
                for n in jm.job_nodes.get(NodeType.PS, {}).values()
            )
            == 1
        )
        victim = next(
            name for name, n in cluster.nodes.items() if n.type == NodeType.PS
        )
        cluster.fail_node(victim)
        assert _wait(
            lambda: any(
                n.status == NodeStatus.FAILED
                for n in jm.job_nodes[NodeType.PS].values()
            )
        )
        # the operator said PS loss is survivable: the job must not die
        assert not jm.job_failed()
        assert not jm.any_worker_failed_fatally()
        # the shrunken set becomes adoptable: target lowered, abandoned
        # node released, so failover clients can re-reach ready
        assert jm.node_group_target(NodeType.PS) == 0
        _, ready, failure = jm.query_ps_nodes()
        assert ready and not failure
    finally:
        jm.stop()


def test_ps_version_bumps_once_per_loss_and_on_scaleup_join():
    """One PS loss emits FAILED then DELETED for the same node — the
    version must bump once; a scale-up join after a master restart (nodes
    adopted, no started events) must still bump."""
    from dlrover_tpu.master.elastic_training.elastic_ps import ElasticPsService
    from dlrover_tpu.master.node.event_callback import PSClusterVersionCallback

    jm, cluster = _role_manager()
    svc = ElasticPsService()
    cb = PSClusterVersionCallback(svc, jm)
    jm.add_node_event_callback(cb)
    jm.start()
    try:
        assert _wait(
            lambda: len(jm.running_nodes(NodeType.PS)) == 2
        )
        node = jm.running_nodes(NodeType.PS)[0]
        cb.on_node_failed(node)
        cb.on_node_deleted(node)  # watcher reports the removal too
        assert svc.get_global_cluster_version() == 1

        # master-restart scale-up: adopted cluster, fresh callback
        svc2 = ElasticPsService()
        cb2 = PSClusterVersionCallback(svc2, jm)
        for n in jm.running_nodes(NodeType.PS):
            n.adopted_at_start = True
        joiner = Node(NodeType.PS, 999, rank_index=2, status=NodeStatus.RUNNING)
        jm.job_nodes[NodeType.PS][999] = joiner
        cb2.on_node_started(joiner)
        assert svc2.get_global_cluster_version() == 1
    finally:
        jm.stop()


def test_ps_loss_during_initial_formation_does_not_bump():
    """A PS dying before the cluster ever fully formed must not move the
    version: workers still hold version 0 and a reshard round would
    restore from a checkpoint that never existed."""
    from dlrover_tpu.master.elastic_training.elastic_ps import ElasticPsService
    from dlrover_tpu.master.node.event_callback import PSClusterVersionCallback

    jm, cluster = _role_manager()
    svc = ElasticPsService()
    cb = PSClusterVersionCallback(svc, jm)
    ghost = Node(NodeType.PS, 1, rank_index=0, status=NodeStatus.FAILED)
    cb.on_node_failed(ghost)
    assert svc.get_global_cluster_version() == 0
    jm.stop()


def test_relaunch_replacement_join_does_not_double_bump():
    from dlrover_tpu.master.elastic_training.elastic_ps import ElasticPsService
    from dlrover_tpu.master.node.event_callback import PSClusterVersionCallback

    jm, cluster = _role_manager()
    svc = ElasticPsService()
    cb = PSClusterVersionCallback(svc, jm)
    jm.add_node_event_callback(cb)
    jm.start()
    try:
        assert _wait(lambda: len(jm.running_nodes(NodeType.PS)) == 2)
        victim = next(
            name for name, n in cluster.nodes.items() if n.type == NodeType.PS
        )
        cluster.fail_node(victim)
        # loss bumps once; the replacement (relaunch_count=1) reaching
        # RUNNING must NOT bump again
        assert _wait(lambda: len(jm.running_nodes(NodeType.PS)) == 2)
        time.sleep(0.2)  # let any (wrong) second bump land
        assert svc.get_global_cluster_version() == 1
    finally:
        jm.stop()


def test_loss_after_replacement_completed_formation_still_bumps():
    """A relaunched replacement that COMPLETES initial formation must
    still mark the cluster as formed, so a later genuine loss bumps."""
    from dlrover_tpu.master.elastic_training.elastic_ps import ElasticPsService
    from dlrover_tpu.master.node.event_callback import PSClusterVersionCallback

    jm, cluster = _role_manager()
    svc = ElasticPsService()
    cb = PSClusterVersionCallback(svc, jm)
    jm.add_node_event_callback(cb)
    jm.start()
    try:
        assert _wait(lambda: len(jm.running_nodes(NodeType.PS)) == 2)
        # simulate: formation finished by a relaunched node (the live set
        # is ready; the finishing event carries relaunch_count=1)
        cb._ever_ready = False
        finisher = jm.running_nodes(NodeType.PS)[1]
        finisher.relaunch_count = 1
        cb.on_node_started(finisher)
        assert svc.get_global_cluster_version() == 0  # no formation bump
        # a genuine loss afterwards must bump
        cb.on_node_failed(jm.running_nodes(NodeType.PS)[0])
        assert svc.get_global_cluster_version() == 1
    finally:
        jm.stop()
