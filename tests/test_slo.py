"""SLO burn-rate engine (ISSUE 12): window math, band isolation,
budget exhaustion, and the acceptance — ``serving_slo_burn_rate``
drives a scale-up in a scenario where queue depth alone would not.

Everything runs on a synthetic clock: the engine takes ``now``
everywhere, so window expiry and burn arithmetic are asserted exactly,
not raced.
"""

import numpy as np

from dlrover_tpu.brain.serving import ServingScalePolicy, ServingSignal
from dlrover_tpu.serving.remote.worker import FakeEngine
from dlrover_tpu.serving.router import (
    PRIORITY_BATCH,
    PRIORITY_HIGH,
    PRIORITY_NORMAL,
    ContinuousBatchScheduler,
    RouterMetrics,
    ServingAutoScaler,
    ServingRouter,
    SloEngine,
    SloObjective,
)


def _engine(fast=10.0, slow=40.0, target=0.9):
    return SloEngine(
        objectives=(
            SloObjective(PRIORITY_HIGH, ttft_target_s=0.1,
                         e2e_target_s=1.0, target=target),
            SloObjective(PRIORITY_NORMAL, ttft_target_s=0.2,
                         e2e_target_s=2.0, target=target),
            SloObjective(PRIORITY_BATCH, ttft_target_s=1.0,
                         e2e_target_s=10.0, target=target),
        ),
        fast_window_s=fast, slow_window_s=slow,
    )


# -- window math -------------------------------------------------------------


def test_compliance_and_burn_rate_math():
    slo = _engine(target=0.9)  # error budget = 0.1
    t = 1000.0
    # 8 good + 2 bad NORMAL completions -> 80% compliance
    for i in range(10):
        bad = i < 2
        slo.observe(PRIORITY_NORMAL,
                    ttft_s=(0.5 if bad else 0.01),
                    e2e_s=0.5, now=t + i * 0.1)
    t += 1.0
    assert abs(slo.compliance(PRIORITY_NORMAL, t, "fast") - 0.8) < 1e-9
    # bad fraction 0.2 over budget 0.1 -> burning at 2x
    assert abs(slo.burn_rate(PRIORITY_NORMAL, t, "fast") - 2.0) < 1e-9
    # slow window holds the same events right now
    assert abs(slo.burn_rate(PRIORITY_NORMAL, t, "slow") - 2.0) < 1e-9
    # idle band: perfect compliance, zero burn
    assert slo.compliance(PRIORITY_HIGH, t, "fast") == 1.0
    assert slo.burn_rate(PRIORITY_HIGH, t, "fast") == 0.0


def test_ttft_violation_alone_is_a_violation():
    slo = _engine()
    t = 50.0
    # e2e comfortably inside, TTFT blown: the user WAITED even though
    # the answer eventually streamed fast
    slo.observe(PRIORITY_HIGH, ttft_s=5.0, e2e_s=0.5, now=t)
    assert slo.compliance(PRIORITY_HIGH, t + 0.1, "fast") == 0.0
    # a missing TTFT (legacy non-streaming path) judges on e2e alone
    slo.observe(PRIORITY_HIGH, ttft_s=None, e2e_s=0.5, now=t + 0.2)
    assert abs(slo.compliance(PRIORITY_HIGH, t + 0.3, "fast") - 0.5) \
        < 1e-9


def test_fast_window_forgets_but_slow_window_remembers():
    slo = _engine(fast=10.0, slow=40.0)
    t = 100.0
    for i in range(5):
        slo.observe_violation(PRIORITY_NORMAL, now=t + i * 0.1)
    # inside both windows
    assert slo.burn_rate(PRIORITY_NORMAL, t + 1, "fast") > 0
    assert slo.burn_rate(PRIORITY_NORMAL, t + 1, "slow") > 0
    # 20s later: past the 10s fast window, inside the 40s slow one
    assert slo.burn_rate(PRIORITY_NORMAL, t + 20, "fast") == 0.0
    assert slo.burn_rate(PRIORITY_NORMAL, t + 20, "slow") > 0
    # 60s later: everything aged out; budget replenished
    assert slo.burn_rate(PRIORITY_NORMAL, t + 60, "slow") == 0.0
    assert slo.budget_remaining(PRIORITY_NORMAL, t + 60) == 1.0


def test_band_isolation():
    slo = _engine()
    t = 10.0
    for i in range(20):
        slo.observe_violation(PRIORITY_BATCH, now=t + i * 0.05)
        slo.observe(PRIORITY_HIGH, ttft_s=0.01, e2e_s=0.1,
                    now=t + i * 0.05)
    t += 2.0
    # BATCH is on fire; HIGH and NORMAL are untouched by it
    assert slo.burn_rate(PRIORITY_BATCH, t, "fast") > 1.0
    assert slo.burn_rate(PRIORITY_HIGH, t, "fast") == 0.0
    assert slo.compliance(PRIORITY_HIGH, t, "fast") == 1.0
    assert slo.burn_rate(PRIORITY_NORMAL, t, "fast") == 0.0
    assert slo.budget_remaining(PRIORITY_HIGH, t) == 1.0


def test_budget_exhaustion_clamps_and_pressure_needs_both_windows():
    slo = _engine(fast=10.0, slow=40.0, target=0.9)
    t = 200.0
    # 50% bad >> the 10% budget: remaining pins to 0, never negative
    for i in range(20):
        slo.observe(PRIORITY_NORMAL,
                    ttft_s=(9.9 if i % 2 else 0.01), e2e_s=0.1,
                    now=t + i * 0.1)
    t += 3.0
    assert slo.budget_remaining(PRIORITY_NORMAL, t) == 0.0
    # pressure = min(fast, slow) burn, max over bands
    assert slo.pressure(t) > 1.0
    # 15s later the fast window is clean -> the multi-window rule
    # stands down even though the slow window still remembers
    assert slo.burn_rate(PRIORITY_NORMAL, t + 15, "slow") > 0
    assert slo.pressure(t + 15) == 0.0


def test_summary_and_render_and_otlp_metrics():
    slo = _engine()
    t = 5.0
    slo.observe(PRIORITY_NORMAL, ttft_s=0.01, e2e_s=0.1, now=t)
    slo.observe_violation(PRIORITY_NORMAL, now=t)
    summary = slo.summary(t + 0.5)
    assert summary["NORMAL"]["observed"] == 2
    assert summary["NORMAL"]["violations"] == 1
    assert summary["NORMAL"]["met"] is False
    assert summary["HIGH"]["met"] is True
    text = slo.render()
    assert 'serving_slo_burn_rate{band="NORMAL",window="fast"}' in text
    assert "# HELP serving_slo_compliance" in text
    rows = slo.otlp_metrics(t + 0.5)
    names = {name for name, _, _ in rows}
    assert names == {"serving_slo_compliance", "serving_slo_burn_rate",
                     "serving_slo_budget_remaining",
                     "serving_slo_class_burn_rate"}
    bands = {attrs["band"] for _, attrs, _ in rows if "band" in attrs}
    assert bands == {"HIGH", "NORMAL", "BATCH"}
    classes = {attrs["tenant_class"] for _, attrs, _ in rows
               if "tenant_class" in attrs}
    assert classes == {"premium", "standard", "background"}


# -- the policy signal -------------------------------------------------------


def test_policy_scales_up_on_burn_where_queue_would_not():
    policy = ServingScalePolicy(
        min_replicas=1, max_replicas=8, queue_high=4.0,
        slo_burn_high=2.0)
    # depth 2 over 2 replicas = 1.0 per replica: inside the [queue_low,
    # queue_high) dead band — the queue alone moves nothing
    shallow_queue = [ServingSignal(queue_depth=2.0)] * 3
    assert policy.decide(shallow_queue, 2) == 2
    # same shallow queue, but the SLO budget is burning at 5x
    burning = [ServingSignal(queue_depth=2.0, slo_pressure=5.0)] * 3
    assert policy.decide(burning, 2) == 3
    # burn below the threshold: still no move
    mild = [ServingSignal(queue_depth=2.0, slo_pressure=1.5)] * 3
    assert policy.decide(mild, 2) == 2
    # slo_burn_high=None disables the signal entirely
    off = ServingScalePolicy(queue_high=4.0, slo_burn_high=None)
    assert off.decide(burning, 2) == 2
    # and burn holds off the scale-DOWN an empty queue would take
    assert policy.decide(burning, 3) == 4  # up, not down


def test_signal_dict_roundtrip_keeps_slo_pressure():
    s = ServingSignal(queue_depth=1.0, slo_pressure=3.5)
    assert ServingSignal.from_dict(s.to_dict()).slo_pressure == 3.5
    # a pre-SLO producer's dict (Brain RPC path) defaults to 0.0
    legacy = {"queue_depth": 1.0, "ttft_seconds": 0.1,
              "tokens_per_sec": 5.0}
    assert ServingSignal.from_dict(legacy).slo_pressure == 0.0


# -- the acceptance: burn-driven scale-up end to end -------------------------


class _PlanScaler:
    """Scaler stub recording executed plans."""

    def __init__(self):
        self.plans = []

    def scale(self, plan):
        self.plans.append(plan)


def _router_with_slow_engine(slo):
    router = ServingRouter(
        scheduler=ContinuousBatchScheduler(block_size=4),
        metrics=RouterMetrics(window_seconds=5.0),
        slo=slo,
    )
    # plenty of slots: the queue never builds, but generation takes
    # long enough (driven by the synthetic clock below) to blow TTFT
    router.join_replica("r0", FakeEngine(slots=64, tokens_per_step=1,
                                         blocks=100000))
    return router


def _drive_slow_requests(router, auto, t0, rounds=30):
    """Submit one request per round and step with the clock jumping
    past the TTFT target each time: per-replica queue depth stays ~1
    (far below queue_high) while EVERY completion violates."""
    t = t0
    for i in range(rounds):
        router.submit(np.full(8, i % 251, np.int32), 1,
                      priority=PRIORITY_NORMAL, now=t)
        # the engine finishes in one step, but that step lands 0.5s
        # after submission — TTFT 0.5s against a 0.2s target
        t += 0.5
        router.step(now=t)
        t += 0.1
    return t


def test_burn_rate_drives_scale_up_where_queue_depth_would_not():
    slo = _engine(fast=10.0, slow=40.0, target=0.9)
    router = _router_with_slow_engine(slo)
    scaler = _PlanScaler()
    auto = ServingAutoScaler(
        router, scaler,
        policy=ServingScalePolicy(
            min_replicas=1, max_replicas=4, queue_high=50.0,
            queue_low=0.0, slo_burn_high=2.0),
        decide_interval=0.5, cooldown=2.0, min_samples=2)
    t = _drive_slow_requests(router, auto, t0=1000.0)

    # the queue never came close to the scale-up bar...
    assert all(s.queue_depth < 5.0 for s in auto._samples or [])
    # ...but the burn did, and a scale-up plan was executed
    assert slo.pressure(t) > 2.0
    up_plans = [p for p in auto.plans if p.node_group_resources]
    assert up_plans, "SLO burn must have driven a scale-up"
    count = sum(g.count for g in
                up_plans[0].node_group_resources.values())
    assert count >= 2
    # the autoscale trace recorded the decision (always-sampled)
    autoscale = router.tracer.traces_named("autoscale")
    assert autoscale, "the burn-driven decision must be traced"

    # CONTROL: identical drive with the SLO signal disabled — queue
    # depth alone never scales (proving the burn was the cause)
    slo2 = _engine(fast=10.0, slow=40.0, target=0.9)
    router2 = _router_with_slow_engine(slo2)
    scaler2 = _PlanScaler()
    auto2 = ServingAutoScaler(
        router2, scaler2,
        policy=ServingScalePolicy(
            min_replicas=1, max_replicas=4, queue_high=50.0,
            queue_low=0.0, slo_burn_high=None),
        decide_interval=0.5, cooldown=2.0, min_samples=2)
    _drive_slow_requests(router2, auto2, t0=1000.0)
    assert not [p for p in auto2.plans if p.node_group_resources], \
        "without the SLO signal the shallow queue must not scale"


def test_router_feeds_poisoning_as_violation():
    """A poisoned request (burned every failover replay) never
    answered its caller — the SLO engine must see it, or a
    crash-looping replica reads as perfect compliance."""
    from dlrover_tpu.serving.router import RequestGateway

    slo = _engine(fast=10.0, slow=40.0)
    router = ServingRouter(
        gateway=RequestGateway(max_requeues=0),
        scheduler=ContinuousBatchScheduler(block_size=4),
        metrics=RouterMetrics(window_seconds=5.0),
        slo=slo,
    )
    router.join_replica("r0", FakeEngine(slots=4, tokens_per_step=1,
                                         blocks=100000))
    t = 700.0
    req = router.submit(np.full(8, 1, np.int32), 8,
                        priority=PRIORITY_NORMAL, now=t)
    router.step(now=t)           # placed on r0
    router.fail_replica("r0")
    router.step(now=t + 0.1)     # reap -> requeue cap 0 -> poisoned
    assert router.metrics.metrics()[
        "serving_requests_poisoned_total"] == 1.0
    assert req.state == "Poisoned"
    assert slo.burn_rate(PRIORITY_NORMAL, t + 0.2, "fast") > 0


def test_router_feeds_expiry_as_violation():
    slo = _engine(fast=10.0, slow=40.0)
    router = ServingRouter(
        scheduler=ContinuousBatchScheduler(block_size=4),
        metrics=RouterMetrics(window_seconds=5.0),
        slo=slo,
    )
    t = 500.0
    # no replicas: the request can only age out — an SLO violation
    router.submit(np.full(8, 1, np.int32), 4, timeout=0.5, now=t)
    router.manager.replicas.clear()
    router.step(now=t + 1.0)
    assert slo.burn_rate(PRIORITY_NORMAL, t + 1.1, "fast") > 0
    m = router.metrics.metrics()
    assert m["serving_requests_timed_out_total"] == 1.0
