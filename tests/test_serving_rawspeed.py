"""Decode raw-speed push (ISSUE 13): chunked prefill, speculative
accept into paged KV, and int8 paged KV blocks.

Covers the three engine optimizations and their contracts:

- **chunked prefill** (``prefill_chunk``): greedy parity with the
  monolithic path, the ordering invariant (no slot ever emits a token
  out of order; every decode slot keeps its cadence while a long
  prompt prefills), partial-prefill cursor state across dispatches,
  and cancel-mid-prefill reclaiming the slot + its KV blocks (the PR 5
  reclamation contract extended to half-prefilled slots);
- **int8 paged KV** (``kv_dtype="int8"``): quantization round-trip
  bound, logit drift bounded vs the native pool on a seeded small
  model, greedy token agreement, and the >=1.9x block-budget
  multiplier feeding the engine pool and the router's placement
  ledger;
- **speculative accept into paged KV**: books balance after a drain
  (blocks allocated == blocks freed) with acceptance actually
  happening, and the ``serving_spec_accept_ratio`` /
  ``serving_kv_quant_blocks`` / ``serving_prefill_chunk_seconds``
  metric plumbing from EngineStats through the adapter to the
  router's /metrics dict.

The nightly soak at the bottom (``-m slow``) drives Pareto heavy-tail
prompt lengths (serving/router/loadgen's distribution) with seeded
mid-flight cancels and asserts the stall bound + books under chaos.
"""

import json
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.models.llama import LlamaConfig, LlamaModel
from dlrover_tpu.serving.engine import InferenceEngine


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny(max_seq_len=96, dtype=jnp.float32)
    model = LlamaModel(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32))
    return cfg, variables


def _prompts(cfg, n, size, seed=0):
    rng = np.random.RandomState(seed)
    return rng.randint(0, cfg.vocab_size, (n, size)).astype(np.int32)


def _engine(setup, **kw):
    cfg, variables = setup
    kw.setdefault("max_slots", 2)
    kw.setdefault("chunk", 4)
    kw.setdefault("temperature", 0.0)
    return InferenceEngine(cfg, variables, **kw)


# -- chunked prefill --------------------------------------------------------


def test_chunked_prefill_greedy_parity_dense_and_paged(setup):
    """Chunked prefill must produce the monolithic path's exact greedy
    outputs — dense cache, paged cache, and paged+int8 all chunk the
    same way (the chunk program is verify_step, i.e. the decode
    program, by construction)."""
    cfg, _ = setup
    prompts = [p for p in _prompts(cfg, 3, 40)] + \
        [p for p in _prompts(cfg, 2, 7, seed=3)]

    def run(**kw):
        eng = _engine(setup, **kw)
        rids = [eng.add_request(p, 10) for p in prompts]
        res = eng.run()
        return [res[r] for r in rids]

    base = run()
    for extra in (
        dict(prefill_chunk=16),
        dict(prefill_chunk=16, paged=True, block_size=8),
    ):
        for a, b in zip(base, run(**extra)):
            np.testing.assert_array_equal(a, b)


def test_chunked_prefill_interleaves_decode_no_stall(setup):
    """THE stall-bound invariant: while a long prompt prefills chunk by
    chunk, every already-decoding slot gains tokens on EVERY step (no
    inter-token gap beyond one step), tokens stay in order, and the
    long prompt's cursor advances monotonically across dispatches."""
    cfg, _ = setup
    eng = _engine(setup, max_slots=3, prefill_chunk=16)
    short = _prompts(cfg, 2, 6)
    long_prompt = _prompts(cfg, 1, 64, seed=7)[0]
    short_reqs = [eng.add_request(p, 40) for p in short]
    # run until both shorts are decoding (small buckets may themselves
    # chunk-admit one slot per step — bounded work IS the contract)
    for _ in range(8):
        eng.step()
        reqs = {r.rid: r for r in eng._slot_req if r is not None}
        if set(reqs) == set(short_reqs) and all(
                not eng._prefilling[s]
                for s, r in enumerate(eng._slot_req) if r is not None):
            break
    assert set(reqs) == set(short_reqs)
    long_rid = eng.add_request(long_prompt, 4)
    prev_counts = {r: len(reqs[r].output) for r in short_reqs}
    prev_cursor = 0
    prefix_snapshots = {r: list(reqs[r].output) for r in short_reqs}
    steps_while_prefilling = 0
    while True:
        eng.step()
        slot = next(
            (s for s, r in enumerate(eng._slot_req)
             if r is not None and r.rid == long_rid), None)
        prefilling = slot is not None and eng._prefilling[slot]
        if prefilling:
            steps_while_prefilling += 1
            # the real_len cursor advances by exactly one bounded chunk
            cursor = int(eng._prefill_pos[slot])
            assert 0 < cursor - prev_cursor <= eng.prefill_chunk
            prev_cursor = cursor
            for r in short_reqs:
                out = reqs[r].output
                # cadence: every decoding slot gained tokens this step
                assert len(out) > prev_counts[r], (
                    "a decode slot stalled while the long prompt "
                    "prefilled"
                )
                # ordering: earlier tokens never rewritten
                assert out[: len(prefix_snapshots[r])] == \
                    prefix_snapshots[r]
                prev_counts[r] = len(out)
                prefix_snapshots[r] = list(out)
        else:
            break
    # a 64-token prompt at chunk 16 needs 4 chunk dispatches; the loop
    # observes the 3 that leave the slot still prefilling
    assert steps_while_prefilling >= 3
    res = eng.run()
    assert len(res[long_rid]) == 4
    for r in short_reqs:
        assert len(res[r]) == 40


def test_chunked_prefill_admissions_vs_dispatch_counters(setup):
    """The satellite fix: ``prefill_calls`` counts dispatches,
    ``prefill_admissions`` counts requests — batched short-prompt
    admission keeps calls < admissions, chunked long prompts push
    calls > admissions.  Both must be visible or the batched-prefill
    win is only inferrable."""
    cfg, _ = setup
    eng = _engine(setup, max_slots=4)
    for p in _prompts(cfg, 4, 12):
        eng.add_request(p, 2)
    eng.run()
    assert eng.stats.prefill_admissions == 4
    assert eng.stats.prefill_calls == 1  # one batched dispatch

    eng2 = _engine(setup, max_slots=2, prefill_chunk=8)
    rid = eng2.add_request(_prompts(cfg, 1, 64, seed=5)[0], 2)
    eng2.run()
    assert eng2.stats.prefill_admissions == 1
    assert eng2.stats.prefill_chunks == 8  # 64 tokens / 8 per chunk
    assert eng2.stats.prefill_calls == eng2.stats.prefill_chunks
    assert eng2.stats.prefill_chunk_seconds > 0.0
    assert rid is not None


def test_cancel_mid_prefill_reclaims_slot_and_blocks(setup):
    """PR 5's reclamation contract extended to half-prefilled slots:
    cancelling a request whose prompt is mid-chunked-prefill frees its
    slot AND its lifetime block allocation immediately, and the books
    still balance after a full drain."""
    from dlrover_tpu.serving.router.replica import InferenceEngineAdapter

    cfg, _ = setup
    eng = _engine(setup, max_slots=2, prefill_chunk=8, paged=True,
                  block_size=8)
    adapter = InferenceEngineAdapter(eng)
    total = eng._blockmgr.num_blocks - 1  # minus the trash sink
    long_rid = eng.add_request(_prompts(cfg, 1, 64, seed=9)[0], 8)
    eng.step()
    slot = next(s for s, r in enumerate(eng._slot_req)
                if r is not None and r.rid == long_rid)
    assert eng._prefilling[slot] and 0 < eng._prefill_pos[slot] < 64
    assert eng._blockmgr.available_blocks < total
    assert adapter.cancel(long_rid) is True
    assert eng._slot_req[slot] is None
    assert not eng._prefilling[slot]
    assert eng._blockmgr.available_blocks == total, (
        "cancel mid-prefill must free the lifetime block allocation"
    )
    # the slot is genuinely reusable: fresh traffic completes cleanly
    rids = [eng.add_request(p, 6) for p in _prompts(cfg, 3, 12)]
    res = eng.run()
    assert all(res[r].size == 6 for r in rids)
    assert eng._blockmgr.available_blocks == total


def test_cancel_queued_and_finished_via_engine(setup):
    """Engine-level cancel covers the queue (never admitted) and the
    already-finished no-op, same True contract as the adapter."""
    cfg, _ = setup
    eng = _engine(setup, max_slots=1)
    p = _prompts(cfg, 2, 8)
    r1 = eng.add_request(p[0], 2)
    r2 = eng.add_request(p[1], 2)  # waits in the engine queue
    assert eng.cancel(r2) is True
    res = eng.run()
    assert r2 not in res and res[r1].size == 2
    assert eng.cancel(r1) is True  # finished: delivered no-op


# -- int8 paged KV ----------------------------------------------------------


def test_kv_int8_roundtrip_bound():
    """Per-vector symmetric int8: |x - dq(q(x))| <= amax/127 plus the
    bf16 scale's rounding (2^-8 relative) — the numeric floor under
    the engine-level drift tests."""
    from dlrover_tpu.models.quantize import (
        dequantize_kv_int8,
        quantize_kv_int8,
    )

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(4, 6, 2, 16).astype(np.float32)) * 3.0
    q, scale = quantize_kv_int8(x)
    assert q.dtype == jnp.int8 and scale.shape == x.shape[:-1]
    back = dequantize_kv_int8(q, scale, jnp.float32)
    amax = np.max(np.abs(np.asarray(x)), axis=-1, keepdims=True)
    bound = amax / 127.0 * (1.0 + 2.0 ** -7) + amax * 2.0 ** -8
    assert np.all(np.abs(np.asarray(back) - np.asarray(x)) <= bound)


def test_kv_int8_logit_drift_bounded_vs_native(setup):
    """Seeded small model, identical prompts admitted into a native
    and an int8 paged engine: the next-token logits off the quantized
    cache must stay within a small fraction of the native logit range,
    and greedy generations must mostly agree (the 0.9 bar the int8
    weight path also meets)."""
    from dlrover_tpu.serving.model import verify_step

    cfg, variables = setup
    prompts = _prompts(cfg, 2, 24, seed=11)

    def admitted(kv_dtype):
        eng = _engine(setup, paged=True, block_size=8,
                      kv_dtype=kv_dtype)
        for p in prompts:
            eng.add_request(p, 8)
        eng._admit()
        if eng._table_dirty:
            eng._push_table()
        logits, _ = verify_step(
            eng.params, cfg, eng._cache,
            jnp.asarray(eng._tokens[:, None]),
            jnp.asarray(eng._positions),
        )
        return np.asarray(logits[:, 0, :]), eng

    ref, _ = admitted(None)
    quant, _ = admitted("int8")
    spread = float(ref.max() - ref.min())
    drift = float(np.max(np.abs(quant - ref)))
    assert drift <= 0.05 * spread, (drift, spread)

    def gen(kv_dtype):
        eng = _engine(setup, paged=True, block_size=8,
                      kv_dtype=kv_dtype)
        rids = [eng.add_request(p, 12) for p in prompts]
        res = eng.run()
        return [res[r] for r in rids]

    agree = np.mean([
        np.mean(a == b) for a, b in zip(gen(None), gen("int8"))
    ])
    assert agree >= 0.9, agree


def test_kv_int8_budget_multiplier_feeds_pool_and_ledger(setup):
    """The HBM story: the same ``cache_blocks`` budget yields >=1.9x
    the blocks under int8 pools, the engine's admission sees them, and
    the adapter's ``blocks_free`` (the router placement ledger's feed)
    reports the multiplied budget."""
    from dlrover_tpu.serving.router.replica import InferenceEngineAdapter

    budget = 12
    native = _engine(setup, paged=True, block_size=8,
                     cache_blocks=budget)
    quant = _engine(setup, paged=True, block_size=8,
                    cache_blocks=budget, kv_dtype="int8")
    assert native._blockmgr.num_blocks == budget
    assert quant.kv_budget_x >= 1.9
    assert quant._blockmgr.num_blocks == int(budget * quant.kv_budget_x)
    assert quant.kv_quant_blocks == quant._blockmgr.num_blocks
    assert native.kv_quant_blocks == 0
    free_n = InferenceEngineAdapter(native).blocks_free()
    free_q = InferenceEngineAdapter(quant).blocks_free()
    assert free_q >= 1.9 * free_n
    # int8 pool bytes stay within the native budget's bytes
    def pool_bytes(eng):
        c = eng._cache
        total = sum(x.size * x.dtype.itemsize for x in c["k_pool"])
        total += sum(x.size * x.dtype.itemsize for x in c["v_pool"])
        for key in ("k_scale", "v_scale"):
            if key in c:
                total += sum(
                    x.size * x.dtype.itemsize for x in c[key])
        return total

    assert pool_bytes(quant) <= pool_bytes(native) * 1.05


def test_kv_dtype_validation(setup):
    with pytest.raises(ValueError, match="paged=True"):
        _engine(setup, kv_dtype="int8")
    with pytest.raises(ValueError, match="paged=True"):
        _engine(setup, kv_dtype="int4")
    with pytest.raises(ValueError, match="not supported"):
        _engine(setup, paged=True, kv_dtype="fp8")


# -- speculative accept into paged KV --------------------------------------


def test_paged_spec_accept_books_balance(setup):
    """Speculative rounds commit accepted drafts through
    scatter_tokens into BlockManager blocks (incl. the spec-slack
    overflow): after a full drain every allocated block is back
    (available == usable pool), acceptance actually happened, and the
    accept-ratio stat is live."""
    cfg, _ = setup
    for kv_dtype in (None, "int8"):
        eng = _engine(setup, max_slots=2, speculative_k=4, paged=True,
                      block_size=8, kv_dtype=kv_dtype)
        prompt = np.tile(np.array([5, 6, 7], np.int32), 8)
        rids = [eng.add_request(prompt, 16) for _ in range(4)]
        res = eng.run()
        assert all(res[r].size == 16 for r in rids)
        assert eng.stats.spec_proposed > 0
        assert eng.stats.spec_accepted > 0, (
            "repetitive prompt must yield accepted drafts"
        )
        assert 0.0 < eng.stats.spec_accept_ratio <= 1.0
        assert eng._blockmgr.available_blocks == \
            eng._blockmgr.num_blocks - 1, (
            "paged speculative decode leaked blocks"
        )


def test_spec_chunked_prefill_composes(setup):
    """All three optimizations at once (spec + chunked prefill + int8
    paged KV) drain cleanly with balanced books and exact output
    lengths."""
    cfg, _ = setup
    eng = _engine(setup, max_slots=2, speculative_k=4, paged=True,
                  block_size=8, kv_dtype="int8", prefill_chunk=16)
    prompt = np.tile(np.array([5, 6, 7], np.int32), 16)  # 48 tokens
    rids = [eng.add_request(prompt, 12) for _ in range(3)]
    res = eng.run()
    assert all(res[r].size == 12 for r in rids)
    assert eng._blockmgr.available_blocks == \
        eng._blockmgr.num_blocks - 1


# -- metric plumbing --------------------------------------------------------


def test_engine_metrics_surface_on_router_metrics(setup):
    """EngineStats -> adapter.engine_metrics -> router sweep ->
    RouterMetrics.metrics(): the new families are live on the /metrics
    dict with real values after traffic on a real paged spec engine."""
    from dlrover_tpu.serving.router import (
        ContinuousBatchScheduler,
        RequestGateway,
        ServingRouter,
    )
    from dlrover_tpu.serving.router.replica import InferenceEngineAdapter

    cfg, _ = setup
    eng = _engine(setup, max_slots=2, speculative_k=4, paged=True,
                  block_size=8, kv_dtype="int8", prefill_chunk=16)
    router = ServingRouter(
        gateway=RequestGateway(max_pending=16),
        scheduler=ContinuousBatchScheduler(block_size=8),
    )
    router.join_replica("raw-0", InferenceEngineAdapter(eng))
    prompt = np.tile(np.array([5, 6, 7], np.int32), 16)
    reqs = [router.submit(prompt, 8) for _ in range(3)]
    router.run_until_idle()
    assert all(len(r.output) == 8 for r in reqs)
    m = router.metrics.metrics()
    assert m["serving_spec_accept_ratio"] > 0.0
    assert m["serving_kv_quant_blocks"] == eng.kv_quant_blocks > 0
    assert m["serving_prefill_chunk_seconds"] > 0.0
    # registry: every emitted name is declared with help text (DL006's
    # runtime twin)
    from dlrover_tpu.utils.metric_registry import METRIC_HELP

    for name in ("serving_spec_accept_ratio", "serving_kv_quant_blocks",
                 "serving_prefill_chunk_seconds"):
        assert name in m and name in METRIC_HELP


def test_engine_metrics_zero_when_reporters_leave():
    """Review finding: the fleet aggregates are recomputed every sweep
    — when the last reporting replica leaves, the gauges fall to zero
    instead of freezing at the dead fleet's values."""
    from dlrover_tpu.serving.router.metrics import RouterMetrics

    m = RouterMetrics()
    m.observe_engine_metrics([{"spec_accept_ratio": 0.5,
                               "kv_quant_blocks": 32.0,
                               "prefill_chunk_seconds": 1.5}])
    assert m.spec_accept_ratio == 0.5 and m.kv_quant_blocks == 32.0
    m.observe_engine_metrics([None])  # only non-reporters remain
    out = m.metrics()
    assert out["serving_spec_accept_ratio"] == 0.0
    assert out["serving_kv_quant_blocks"] == 0.0
    assert out["serving_prefill_chunk_seconds"] == 0.0


def test_engine_metrics_ride_stats_frames():
    """Remote twin of the plumbing: a worker whose engine reports
    engine_metrics ships them on STATS, the proxy caches them, and
    absent reporters (FakeEngine) leave the proxy returning None."""
    import threading

    from dlrover_tpu.serving.remote.proxy import RemoteReplicaHandle
    from dlrover_tpu.serving.remote.worker import FakeEngine, WorkerServer

    class MeteredFake(FakeEngine):
        def engine_metrics(self):
            return {"spec_accept_ratio": 0.25,
                    "kv_quant_blocks": 64.0,
                    "prefill_chunk_seconds": 0.5}

    import time as _time

    server = WorkerServer(MeteredFake(), stats_interval=0.05)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        proxy = RemoteReplicaHandle(server.addr, name="m-0")
        em, deadline = None, 100
        while deadline and em is None:
            em = proxy.engine_metrics()
            _time.sleep(0.05)
            deadline -= 1
        assert em == {"spec_accept_ratio": 0.25,
                      "kv_quant_blocks": 64.0,
                      "prefill_chunk_seconds": 0.5}
        proxy.close()
    finally:
        server.crash()
        t.join(timeout=2.0)

    plain = WorkerServer(FakeEngine(), stats_interval=0.05)
    t2 = threading.Thread(target=plain.serve_forever, daemon=True)
    t2.start()
    try:
        proxy2 = RemoteReplicaHandle(plain.addr, name="m-1")
        # a few STATS beats later the non-reporter still returns None
        _time.sleep(0.2)
        assert proxy2.engine_metrics() is None
        proxy2.close()
    finally:
        plain.crash()
        t2.join(timeout=2.0)


# -- nightly heavy-tail soak ------------------------------------------------


@pytest.mark.slow
def test_heavy_tail_chunked_prefill_soak(setup):
    """Long-prompt heavy-tail soak (nightly): Pareto prompt lengths
    from the loadgen distribution stream through a chunked-prefill
    paged int8 engine with seeded mid-flight cancels (the chaos).  The
    stall bound must hold in STEP terms — a decoding slot never goes a
    step without tokens while prompts prefill — and the block books
    must balance at the end."""
    from dlrover_tpu.serving.router.loadgen import (
        LoadgenConfig,
        OpenLoopGenerator,
    )

    cfg, _ = setup
    lg = LoadgenConfig(seed=13, rate_qps=60.0, duration_s=1.0,
                       prompt_mix="heavy_tail", prompt_min=8,
                       prompt_max=80, pareto_alpha=1.2)
    arrivals = list(OpenLoopGenerator(lg).arrivals())
    assert len(arrivals) >= 30
    assert max(a.prompt_len for a in arrivals) > 32, (
        "heavy tail must include long prompts"
    )
    eng = _engine(setup, max_slots=4, prefill_chunk=8, paged=True,
                  block_size=8, kv_dtype="int8", temperature=1.0)
    rng = np.random.RandomState(13)
    chaos = random.Random(13)
    pending = [
        rng.randint(0, cfg.vocab_size, a.prompt_len).astype(np.int32)
        for a in arrivals
    ]
    live = {}
    total = eng._blockmgr.num_blocks - 1
    counts = {}
    cancelled = 0
    while pending or eng.has_work:
        while pending:
            p = pending[0]
            gen = 8 + int(p.size) % 8
            if p.size + gen > eng.max_len:
                p = p[: eng.max_len - gen]
            try:
                rid = eng.add_request(p, gen)
            except ValueError:
                pending.pop(0)
                continue
            live[rid] = gen
            pending.pop(0)
            if len(live) >= 8:
                break
        before = {
            r.rid: len(r.output)
            for s, r in enumerate(eng._slot_req)
            if r is not None and not eng._prefilling[s]
        }
        eng.step()
        # stall bound: every slot that was decoding gained tokens
        # unless it finished this step
        after = {r.rid: len(r.output)
                 for r in eng._slot_req if r is not None}
        for rid, n in before.items():
            if rid in after:
                assert after[rid] > n or after[rid] >= live[rid], (
                    "decode slot stalled during heavy-tail prefill"
                )
        counts.update(after)
        # chaos: occasionally cancel something mid-flight (prefilling
        # slots included — the reclamation contract under fire)
        if chaos.random() < 0.15 and live:
            victim = chaos.choice(list(live))
            eng.cancel(victim)
            live.pop(victim, None)
            cancelled += 1
    assert cancelled > 0
    assert eng._blockmgr.available_blocks == total, (
        "soak leaked KV blocks"
    )
    done = {r.rid for r in eng._finished}
    assert done, "soak finished no requests"
    payload = {"finished": len(done), "cancelled": cancelled,
               "prefill_chunks": eng.stats.prefill_chunks}
    assert eng.stats.prefill_chunks > 0, payload
    json.dumps(payload)  # structured soak record stays serializable
