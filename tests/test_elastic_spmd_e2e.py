"""Elastic SPMD training across REAL jax.distributed processes.

The framework's central promise, proven end to end (VERDICT r2 #1;
reference: dlrover/python/tests/test_elastic_training_agent.py:51-63 +
elastic_agent/torch/training.py:577-728):

- a local master + two real `dlrover-tpu-run` agents (two simulated
  hosts, isolated DLROVER_JOB_UIDs = separate shm namespaces);
- each agent spawns a worker that joins ONE jax.distributed process
  group (2 procs x 2 virtual CPU devices = 4-device dp2xfsdp2 world,
  GSPMD collectives crossing process boundaries);
- node 1 is SIGKILLed mid-run: the jax coordination service declares
  the peer dead, node 0's worker aborts, its agent re-rendezvouses
  into a 1-node world, restores the dp-replicated state from ITS OWN
  shm, re-plans grad accumulation (2 -> 4), and finishes;
- the post-kill loss trajectory must continue the pre-kill one and
  match an uninterrupted single-process reference run step for step.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOTAL_STEPS = 10
KILL_AFTER_STEP = 3
SEQ, GB = 32, 8


def _agent_cmd(node_rank, master_addr, work, step_sleep=0.0):
    return [
        sys.executable, "-m", "dlrover_tpu.agent.launcher",
        "--nnodes=1:2", f"--node_rank={node_rank}",
        f"--master-addr={master_addr}",
        "--max-restarts=2", "--monitor-interval=1",
        "--rdzv-waiting-timeout=5",
        sys.executable, os.path.join(REPO, "examples/train_elastic_spmd.py"),
        "--steps", str(TOTAL_STEPS), "--global-batch", str(GB),
        "--seq-len", str(SEQ),
        "--ckpt-dir", os.path.join(work, "ckpt"),
        "--metrics-file", os.path.join(work, "metrics"),
        "--step-sleep", str(step_sleep),
    ]


def _read_metrics(path):
    rows = []
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                s, loss, world = line.split()
                rows.append((int(s), float(loss), int(world)))
    return rows


def assert_steps_consistent(rows, max_redos: int):
    """No work is redone EXCEPT the bounded, deterministic kill-boundary
    case: a SIGKILL can land between a step's metrics write and its shm
    save commit, so the resumed worker legitimately recomputes that
    step — and since the double-buffered engine (ISSUE 9) commits
    asynchronously at-most-one-behind, the step BEFORE it can need an
    identical redo too in the worst-case kill phase.  Allowed: at most
    ``max_redos`` duplicated steps (budget the caller sizes per
    membership change), each an IDENTICAL redo (same loss —
    determinism makes a divergent redo a real bug, not a timing
    artifact).  Returns the deduplicated step list."""
    steps = [s for s, _, _ in rows]
    assert steps == sorted(steps), f"steps went backwards: {steps}"
    dups = sorted({s for s in steps if steps.count(s) > 1})
    assert len(dups) <= max_redos, (
        f"{len(dups)} redone steps (allowed {max_redos}): {steps}"
    )
    for s in dups:
        losses = {round(ls, 5) for st, ls, _ in rows if st == s}
        assert len(losses) == 1, (
            f"step {s} redone with a DIFFERENT loss: {losses}"
        )
    return sorted(set(steps))


def test_kill_one_node_resumes_trajectory(tmp_path):
    work = str(tmp_path)
    from dlrover_tpu.common.rpc import find_free_port

    port = find_free_port()
    master = subprocess.Popen(
        [sys.executable, "-m", "dlrover_tpu.master.main",
         "--platform", "local", "--port", str(port), "--node_num", "2"],
        stdout=open(os.path.join(work, "master.log"), "w"),
        stderr=subprocess.STDOUT,
    )
    agents = []
    try:
        time.sleep(2)
        for rank in (0, 1):
            env = dict(os.environ)
            env.update(
                DLROVER_FORCE_CPU="1",
                XLA_FLAGS="--xla_force_host_platform_device_count=2",
                DLROVER_JAX_HEARTBEAT_TIMEOUT="15",
                DLROVER_JOB_UID=f"spmdE2e{rank}",
                DLROVER_MONITOR_INTERVAL="1",
                JAX_PLATFORMS="cpu",
            )
            agents.append(subprocess.Popen(
                _agent_cmd(rank, f"127.0.0.1:{port}", work),
                env=env, cwd=REPO,
                stdout=open(os.path.join(work, f"agent{rank}.log"), "w"),
                stderr=subprocess.STDOUT,
                # own process group so we can kill agent+worker together
                preexec_fn=os.setsid,
            ))

        # wait for the 2-proc world to pass KILL_AFTER_STEP
        m0 = os.path.join(work, "metrics.r0")
        deadline = time.time() + 300
        while time.time() < deadline:
            rows = _read_metrics(m0)
            if any(s >= KILL_AFTER_STEP and w == 2 for s, _, w in rows):
                break
            if agents[0].poll() is not None:
                pytest.fail("agent0 exited before reaching the kill step")
            time.sleep(1)
        else:
            pytest.fail(f"2-proc world never reached step {KILL_AFTER_STEP}")

        # simulate node-1 host death: SIGKILL its whole process group
        os.killpg(os.getpgid(agents[1].pid), signal.SIGKILL)
        agents[1].wait(30)

        # node 0 must recover and finish on the shrunk world
        rc = agents[0].wait(300)
        assert rc == 0, f"agent0 exited {rc}"

        rows = _read_metrics(m0)
        steps = assert_steps_consistent(rows, max_redos=2)  # 1 kill x at-most-one-behind commit
        assert steps[-1] == TOTAL_STEPS
        worlds = {s: w for s, _, w in rows}
        assert worlds[1] == 2, "run did not start on the 2-proc world"
        assert worlds[TOTAL_STEPS] == 1, "run did not shrink to 1 proc"
        shrink_step = min(s for s, w in worlds.items() if w == 1)
        assert shrink_step > KILL_AFTER_STEP

        # trajectory continuity: must match an uninterrupted reference
        # run (same fixed global batch and per-step data) step for step
        ref = _reference_losses()
        for s, loss, _ in rows:
            assert np.isclose(loss, ref[s - 1], rtol=1e-3, atol=1e-3), (
                s, loss, ref[s - 1]
            )

        # the master's goodput ledger saw the whole run (VERDICT r4 #2:
        # the elastic e2e emits the north-star metric)
        from dlrover_tpu.agent.master_client import MasterClient

        client = MasterClient(f"127.0.0.1:{port}", node_id=9,
                              node_type="worker")
        try:
            goodput = client.query_job_detail().get(
                "metrics", {}).get("goodput", {})
        finally:
            client.close()

        with open(os.path.join(REPO, "ELASTIC_SPMD_E2E.json"), "w") as f:
            json.dump(
                {
                    "steps": rows,
                    "killed_after_step": KILL_AFTER_STEP,
                    "shrink_step": shrink_step,
                    "world_before": 2,
                    "world_after": 1,
                    "reference_match_rtol": 1e-3,
                    "goodput": goodput,
                },
                f, indent=1,
            )
    finally:
        for p in agents:
            if p.poll() is None:
                try:
                    os.killpg(os.getpgid(p.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
        master.terminate()
        try:
            master.wait(10)
        except subprocess.TimeoutExpired:
            master.kill()


def _reference_losses():
    """Uninterrupted in-process run: 4 devices dp2xfsdp2, identical data."""
    import jax
    import jax.numpy as jnp

    from dlrover_tpu.accel.parallel.mesh import MeshSpec
    from dlrover_tpu.models.llama import LlamaConfig, LlamaModel
    from dlrover_tpu.trainer.elastic.trainer import ElasticTrainer

    cfg = LlamaConfig.tiny(max_seq_len=SEQ, dtype=jnp.float32)
    tr = ElasticTrainer(
        LlamaModel(cfg),
        global_batch_size=GB,
        micro_batch_per_shard=1,
        seq_len=SEQ,
        mesh_spec=MeshSpec(dp=2, fsdp=2),
    )
    tr.prepare(devices=jax.devices()[:4])
    tr.restore_or_init(jax.random.PRNGKey(0))
    losses = []
    for step in range(TOTAL_STEPS):
        rng = np.random.RandomState(1000 + step)
        batch = rng.randint(
            0, cfg.vocab_size, size=(GB, SEQ)
        ).astype(np.int32)
        losses.append(float(tr.train_step(batch)["loss"]))
    return losses


def test_scale_up_mid_run_grows_world(tmp_path):
    """Growth half of the elasticity story with REAL processes: node 0
    trains solo, node 1 joins mid-run, node 0's agent notices the
    waiting member, restarts into the 2-process jax.distributed world,
    and the run continues from shm with the same trajectory."""
    work = str(tmp_path)
    from dlrover_tpu.common.rpc import find_free_port

    port = find_free_port()
    master = subprocess.Popen(
        [sys.executable, "-m", "dlrover_tpu.master.main",
         "--platform", "local", "--port", str(port), "--node_num", "2"],
        stdout=open(os.path.join(work, "master.log"), "w"),
        stderr=subprocess.STDOUT,
    )
    agents = {}

    def start_agent(rank):
        env = dict(os.environ)
        env.update(
            DLROVER_FORCE_CPU="1",
            XLA_FLAGS="--xla_force_host_platform_device_count=2",
            DLROVER_JAX_HEARTBEAT_TIMEOUT="15",
            DLROVER_JOB_UID=f"spmdGrow{rank}",
            DLROVER_MONITOR_INTERVAL="1",
            JAX_PLATFORMS="cpu",
        )
        agents[rank] = subprocess.Popen(
            # slow steps: the solo phase must outlive the joiner's boot
            _agent_cmd(rank, f"127.0.0.1:{port}", work, step_sleep=2.0),
            env=env, cwd=REPO,
            stdout=open(os.path.join(work, f"agent{rank}.log"), "w"),
            stderr=subprocess.STDOUT,
            preexec_fn=os.setsid,
        )

    try:
        time.sleep(2)
        start_agent(0)
        # solo world forms after the last-call window; wait for steps
        m0 = os.path.join(work, "metrics.r0")
        deadline = time.time() + 300
        while time.time() < deadline:
            rows = _read_metrics(m0)
            if any(s >= 2 and w == 1 for s, _, w in rows):
                break
            if agents[0].poll() is not None:
                pytest.fail("agent0 exited before training solo")
            time.sleep(1)
        else:
            pytest.fail("solo world never trained")

        start_agent(1)  # join mid-run

        rc0 = agents[0].wait(400)
        assert rc0 == 0, "agent0 failed after scale-up"
        rc1 = agents[1].wait(60)
        assert rc1 == 0, "agent1 failed"

        rows = _read_metrics(m0)
        worlds = {s: w for s, _, w in rows}
        assert worlds[TOTAL_STEPS] == 2, (
            f"final steps did not run on the grown world: {rows}"
        )
        grow_step = min(s for s, w in worlds.items() if w == 2)
        assert grow_step > 1
        assert_steps_consistent(rows, max_redos=2)  # 1 growth restart x async commit
        ref = _reference_losses()
        for s, loss, _ in rows:
            assert np.isclose(loss, ref[s - 1], rtol=1e-3, atol=1e-3), (
                s, loss, ref[s - 1]
            )
    finally:
        for p in agents.values():
            if p.poll() is None:
                try:
                    os.killpg(os.getpgid(p.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
        master.terminate()
        try:
            master.wait(10)
        except subprocess.TimeoutExpired:
            master.kill()
