"""Hybrid host/device sparse-embedding training (reference parity: the
TFPlus python layer wiring KvVariable into the training graph)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.sparse import native

if native.check_toolchain() is not None:  # pragma: no cover
    pytest.skip("native toolchain unavailable", allow_module_level=True)

from dlrover_tpu.sparse.embedding import (
    KvEmbedding,
    SparseTrainStep,
    pad_bucket,
    unique_pad,
)
from dlrover_tpu.sparse.kv_variable import KvOptimizerConfig, KvVariable


def test_pad_bucket_shapes():
    assert pad_bucket(3, 512) == 512
    assert pad_bucket(512, 512) == 512
    assert pad_bucket(513, 512) == 1024
    assert pad_bucket(2000, 512) == 2048


def test_unique_pad_inverse():
    ids = np.array([[5, 9], [5, 5]], dtype=np.int64)
    uniq, inverse, padded_len = unique_pad(ids, bucket=8)
    assert len(uniq) == 2
    assert padded_len == 8
    np.testing.assert_array_equal(uniq, [5, 9])
    # inverse maps each position back to its unique row
    np.testing.assert_array_equal(uniq[inverse], ids)


def test_padding_does_not_inflate_frequency():
    """Bucket padding must not touch the hash table: a 2-unique batch in a
    bucket of 16 leaves frequencies at their true counts."""
    var = KvVariable(dim=4, init_scale=0.1, seed=1)
    emb = KvEmbedding(var, bucket=16)
    ids = np.array([5, 9, 5], dtype=np.int64)
    emb.lookup_for_step(ids)
    freqs = var.frequencies(np.array([5, 9], dtype=np.int64))
    assert list(freqs) == [1, 1]
    assert len(var) == 2


def test_kv_embedding_lookup_and_grad_routing():
    var = KvVariable(dim=4, optimizer="sgd", init_scale=0.1, seed=3,
                     opt_config=KvOptimizerConfig(learning_rate=1.0))
    emb = KvEmbedding(var, bucket=8)
    ids = np.array([2, 3, 2], dtype=np.int64)
    slab, inverse = emb.lookup_for_step(ids)
    assert slab.shape == (8, 4)
    # craft a slab grad: ones on row 0 (id 2), zeros elsewhere
    g = np.zeros((8, 4), np.float32)
    g[0] = 1.0
    before, _ = var.lookup(np.array([2, 3], dtype=np.int64), train=False)
    applied = emb.apply_slab_grad(g)
    assert applied == 2
    after, _ = var.lookup(np.array([2, 3], dtype=np.int64), train=False)
    np.testing.assert_allclose(after[0], before[0] - 1.0, rtol=1e-6)
    np.testing.assert_allclose(after[1], before[1], rtol=1e-6)


def test_sparse_train_step_learns():
    """Tiny recommender: score = <user_emb, item_emb> + dense bias; the
    hybrid step must reduce loss on a fixed batch."""
    dim = 8
    users = KvEmbedding(
        KvVariable(dim, optimizer="adagrad", init_scale=0.1, seed=1,
                   opt_config=KvOptimizerConfig(learning_rate=0.5)),
        bucket=16)
    items = KvEmbedding(
        KvVariable(dim, optimizer="adagrad", init_scale=0.1, seed=2,
                   opt_config=KvOptimizerConfig(learning_rate=0.5)),
        bucket=16)

    def loss_fn(dense, embs, batch):
        score = jnp.sum(embs["user"] * embs["item"], axis=-1) + dense["bias"]
        return jnp.mean((score - batch["label"]) ** 2)

    def dense_update(params, grads):
        return jax.tree.map(lambda p, g: p - 0.1 * g, params, grads)

    step = SparseTrainStep(loss_fn, {"user": users, "item": items},
                           dense_update)
    dense = {"bias": jnp.zeros(())}
    rng = np.random.RandomState(0)
    user_ids = rng.randint(0, 50, size=32).astype(np.int64)
    item_ids = rng.randint(0, 200, size=32).astype(np.int64)
    labels = rng.randn(32).astype(np.float32)
    batch = {"label": jnp.asarray(labels)}
    ids = {"user": user_ids, "item": item_ids}

    first = None
    for _ in range(30):
        loss, dense = step(dense, ids, batch)
        if first is None:
            first = float(loss)
    assert float(loss) < first * 0.5, (first, float(loss))
    # vocab grew dynamically to the ids actually seen
    assert len(users.var) == len(np.unique(user_ids))
    assert len(items.var) == len(np.unique(item_ids))


def test_sparse_step_compiles_once_per_bucket():
    """Changing the number of unique ids inside one bucket must not
    retrigger compilation (static shapes contract)."""
    var = KvVariable(dim=4, optimizer="sgd", init_scale=0.1, seed=9)
    emb = KvEmbedding(var, bucket=16)

    def loss_fn(dense, embs, batch):
        return jnp.sum(embs["f"] ** 2)

    step = SparseTrainStep(loss_fn, {"f": emb})
    dense = {}
    traces = []
    orig = step._device_step

    def counting(*a, **k):
        traces.append(1)
        return orig(*a, **k)

    step._jitted = jax.jit(counting)
    # same batch shape, different unique-id counts (3, 1, 8) — all pad to
    # the same bucket, so only the first call traces
    step(dense, {"f": np.array([1, 2, 3, 1, 2, 3, 1, 2], np.int64)}, {})
    step(dense, {"f": np.full(8, 7, np.int64)}, {})
    step(dense, {"f": np.arange(8, dtype=np.int64)}, {})
    assert len(traces) == 1, "retraced within one bucket"
