"""Decode raw-speed round two (ISSUE 14): the fused paged-attention
kernel that reads quantized KV in place, int4 KV pools, and same-step
batched prefill.

What's covered, and why each gate exists:

- **kernel parity** (bf16 / int8 / packed int4, Pallas
  ``interpret=True`` on CPU): the kernel's multi-page double-buffered
  DMA + in-kernel dequant must match the XLA gather reference exactly
  — tier-1 catches numerics regressions without TPU hardware;
- **auto-pick contract**: ``resolve_attention_impl`` provably never
  selects a slower impl (the pure decision the engine's one-shot
  build-time measurement feeds), and engine validation/resolution
  edges;
- **int4 pools**: pack/unpack identity, quantization round-trip bound,
  logit drift bounded vs the native twin (greedy agreement is gated in
  bench on a FITTED model — random-init margins are smaller than the
  honest 4-bit error floor, see serving_bench._fit_chain_model);
- **KV-budget single source**: ``paged.kv_budget_multiplier`` is THE
  formula — the engine's pool scaling, ``InferenceEngine.kv_budget_x``
  and the router-side adapter ledger are pinned to it for int8 AND
  int4, so admission and placement cannot disagree;
- **same-step batched prefill**: N concurrent long prompts reach first
  token in the SAME number of engine steps (no TTFT serialization),
  greedy outputs match the monolithic path, and cancel mid-batch
  reclaims every slot/block;
- **metric plumbing**: the new ``serving_attention_impl`` (labeled) /
  ``serving_paged_kernel_step_seconds`` / ``serving_kv_int4_blocks``
  families from EngineStats through the adapter to RouterMetrics.

The nightly soak (``-m slow``) is the int4 drift study: a Pareto
long-context mix, per-step logit-drift histogram asserted within
bound.  The TPU kernel microbench stub skips cleanly off-TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.models.llama import LlamaConfig, LlamaModel
from dlrover_tpu.models.quantize import (
    dequantize_kv_int4,
    pack_int4,
    quantize_kv_int4,
    quantize_kv_int8,
    unpack_int4,
)
from dlrover_tpu.ops.pallas.paged_attention import (
    gather_reference,
    measure_paged_attention,
    paged_decode_attention,
    resolve_attention_impl,
)
from dlrover_tpu.serving.engine import InferenceEngine
from dlrover_tpu.serving.paged import kv_budget_multiplier


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny(max_seq_len=96, dtype=jnp.float32)
    model = LlamaModel(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32))
    return cfg, variables


def _prompts(cfg, n, size, seed=0):
    rng = np.random.RandomState(seed)
    return rng.randint(0, cfg.vocab_size, (n, size)).astype(np.int32)


def _engine(setup, **kw):
    cfg, variables = setup
    kw.setdefault("max_slots", 2)
    kw.setdefault("chunk", 4)
    kw.setdefault("temperature", 0.0)
    return InferenceEngine(cfg, variables, **kw)


def _pool_setup(B=3, H=8, KV=2, D=32, bs=8, MB=5, seed=0):
    rng = np.random.RandomState(seed)
    nb = B * MB + 1
    q = jnp.asarray(rng.randn(B, H, D).astype(np.float32) * 0.3)
    kf = jnp.asarray(rng.randn(nb, bs, KV, D).astype(np.float32) * 0.3)
    vf = jnp.asarray(rng.randn(nb, bs, KV, D).astype(np.float32) * 0.3)
    table = jnp.asarray(
        (np.arange(B * MB) + 1).reshape(B, MB).astype(np.int32))
    lengths = jnp.asarray(
        np.array([1, MB * bs // 2 + 3, MB * bs], np.int32)[:B])
    return q, kf, vf, table, lengths


# -- fused kernel parity ----------------------------------------------------


def test_kernel_parity_bf16_pools():
    """Multi-page double-buffered groups (MB=5 does NOT divide the
    8-page default group — the trash-padded tail must mask clean)
    against the gather reference, odd lengths included."""
    q, kf, vf, table, lengths = _pool_setup()
    out = paged_decode_attention(q, kf, vf, table, lengths,
                                 interpret=True)
    ref = gather_reference(q, kf, vf, table, lengths)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=3e-5)


def test_kernel_parity_small_page_groups():
    """pages_per_block smaller than MB exercises >1 double-buffer
    round per slot (the DMA overlap path, not just the warm-up)."""
    q, kf, vf, table, lengths = _pool_setup(MB=6)
    out = paged_decode_attention(q, kf, vf, table, lengths,
                                 pages_per_block=2, interpret=True)
    ref = gather_reference(q, kf, vf, table, lengths)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=3e-5)


def test_kernel_parity_int8_in_place():
    """int8 code pools + block-shaped scales stream in place; the
    kernel's folded dequant must match the gather path that
    materializes the dequantized view (both read the SAME codes, so
    the comparison is float-exact, not quantization-tolerance)."""
    q, kf, vf, table, lengths = _pool_setup(seed=1)
    k8, ks = quantize_kv_int8(kf)
    v8, vs = quantize_kv_int8(vf)
    out = paged_decode_attention(q, k8, v8, table, lengths,
                                 k_scale=ks, v_scale=vs,
                                 interpret=True)
    ref = gather_reference(q, k8, v8, table, lengths, ks, vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=3e-5)


def test_kernel_parity_int4_packed_in_place():
    """Packed int4 pools (two codes/byte, split-half nibbles): the
    kernel unpacks + dequantizes in VMEM and must match the gather
    reference reading the same packed pool."""
    q, kf, vf, table, lengths = _pool_setup(seed=2)
    k4, ks = quantize_kv_int4(kf)
    v4, vs = quantize_kv_int4(vf)
    assert k4.shape[-1] * 2 == kf.shape[-1]
    out = paged_decode_attention(q, k4, v4, table, lengths,
                                 k_scale=ks, v_scale=vs,
                                 interpret=True)
    ref = gather_reference(q, k4, v4, table, lengths, ks, vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=3e-5)


def test_kernel_parity_mha_and_block_boundary():
    """MHA (KV == H) and a length on an exact page-group boundary."""
    q, kf, vf, table, _ = _pool_setup(B=2, H=4, KV=4, MB=4, seed=3)
    lengths = jnp.asarray(np.array([32, 8], np.int32))
    out = paged_decode_attention(q, kf, vf, table, lengths,
                                 pages_per_block=4, interpret=True)
    ref = gather_reference(q, kf, vf, table, lengths)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=3e-5)


# -- auto-pick contract -----------------------------------------------------


def test_resolve_attention_impl_never_picks_slower():
    """THE auto contract, on the pure decision: whichever side
    measures faster is picked; no measurement falls back to the
    always-available gather; explicit requests are honored."""
    assert resolve_attention_impl(
        "auto", {"xla": 2.0, "pallas": 1.0}) == "pallas"
    assert resolve_attention_impl(
        "auto", {"xla": 1.0, "pallas": 2.0}) == "xla"
    assert resolve_attention_impl("auto", None) == "xla"
    assert resolve_attention_impl("auto", {}) == "xla"
    assert resolve_attention_impl("xla", {"pallas": 0.0}) == "xla"
    assert resolve_attention_impl("pallas", None) == "pallas"
    with pytest.raises(ValueError, match="not supported"):
        resolve_attention_impl("fused", None)


def test_engine_attention_impl_resolution(setup):
    """Engine-side edges: auto on a non-TPU backend resolves to the
    gather path (the interpret-mode kernel is a parity harness, not a
    perf candidate), explicit pallas is honored anywhere paged,
    pallas without paging refuses, junk refuses."""
    eng = _engine(setup, paged=True, block_size=8)
    assert eng.attention_impl_requested == "auto"
    assert eng.attention_impl == "xla"       # CPU backend, no timings
    assert eng.attention_impl_us is None
    forced = _engine(setup, paged=True, block_size=8,
                     attention_impl="pallas")
    assert forced.attention_impl == "pallas"
    dense = _engine(setup)
    assert dense.attention_impl == "xla"
    with pytest.raises(ValueError, match="paged=True"):
        _engine(setup, attention_impl="pallas")
    with pytest.raises(ValueError, match="not supported"):
        _engine(setup, paged=True, attention_impl="cudnn")


def test_engine_greedy_parity_under_pallas_impl(setup):
    """End to end through the real engine: forcing the fused kernel
    (interpret mode on CPU) reproduces the gather engine's exact
    greedy outputs — bf16(f32), int8 and int4 pools."""
    cfg, _ = setup
    prompts = [p for p in _prompts(cfg, 2, 20)] + \
        [p for p in _prompts(cfg, 1, 7, seed=3)]

    def run(**kw):
        eng = _engine(setup, paged=True, block_size=8, **kw)
        rids = [eng.add_request(p, 6) for p in prompts]
        res = eng.run()
        return [res[r] for r in rids]

    for kv_dtype in (None, "int8", "int4"):
        base = run(kv_dtype=kv_dtype)
        kern = run(kv_dtype=kv_dtype, attention_impl="pallas")
        for a, b in zip(base, kern):
            np.testing.assert_array_equal(a, b)


def test_measure_paged_attention_reports_both_impls():
    """The measurement the auto-pick consumes: one positive wall time
    per impl on the supplied operands (interpret mode here — the
    numbers are meaningless as perf, which is exactly why engine auto
    refuses to use them off-TPU; the SHAPE of the evidence is what
    this pins)."""
    q, kf, vf, table, lengths = _pool_setup(B=2, MB=2)
    t = measure_paged_attention(q, kf, vf, table, lengths, trials=1,
                                interpret=True)
    assert set(t) == {"xla", "pallas"} and all(
        v > 0 for v in t.values())


# -- int4 codes -------------------------------------------------------------


def test_int4_pack_unpack_roundtrip():
    rng = np.random.RandomState(0)
    codes = rng.randint(-7, 8, (5, 3, 16)).astype(np.int8)
    packed = pack_int4(jnp.asarray(codes))
    assert packed.shape == (5, 3, 8) and packed.dtype == jnp.int8
    back = np.asarray(unpack_int4(packed))
    np.testing.assert_array_equal(back, codes)


def test_int4_quantize_roundtrip_bound():
    """|x - dq(q4(x))| <= amax/14 * (1 + eps) plus the bf16 scale's
    rounding — the 4-bit error floor the drift study sits on."""
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(4, 6, 2, 16).astype(np.float32)) * 3.0
    q, scale = quantize_kv_int4(x)
    assert q.dtype == jnp.int8 and q.shape == (4, 6, 2, 8)
    assert scale.shape == x.shape[:-1]
    back = dequantize_kv_int4(q, scale, jnp.float32)
    amax = np.max(np.abs(np.asarray(x)), axis=-1, keepdims=True)
    bound = amax / 14.0 * (1.0 + 2.0 ** -6) + amax * 2.0 ** -8
    assert np.all(np.abs(np.asarray(back) - np.asarray(x)) <= bound)


def test_int4_logit_drift_bounded_vs_native(setup):
    """Same-cache next-token logits, int4 pool vs native: drift stays
    a bounded fraction of the native logit spread.  GREEDY agreement
    is deliberately NOT asserted here — random-init margins sit below
    the honest 4-bit error floor, so it is gated in bench on the
    fitted chain model instead (kv4_ok)."""
    from dlrover_tpu.serving.model import verify_step

    cfg, _ = setup
    prompts = _prompts(cfg, 2, 24, seed=11)

    def admitted(kv_dtype):
        eng = _engine(setup, paged=True, block_size=8,
                      kv_dtype=kv_dtype)
        for p in prompts:
            eng.add_request(p, 8)
        eng._admit()
        if eng._table_dirty:
            eng._push_table()
        logits, _ = verify_step(
            eng.params, cfg, eng._cache,
            jnp.asarray(eng._tokens[:, None]),
            jnp.asarray(eng._positions),
        )
        return np.asarray(logits[:, 0, :])

    ref = admitted(None)
    quant = admitted("int4")
    spread = float(ref.max() - ref.min())
    drift = float(np.max(np.abs(quant - ref)))
    assert drift <= 0.2 * spread, (drift, spread)


# -- KV-budget single source ------------------------------------------------


def test_kv_budget_multiplier_is_the_single_source():
    """The formula itself at the serving head dims: bf16 int8 ~2x,
    bf16 int4 >= 3.5x (the acceptance bar), native 1.0, junk
    refused."""
    bf16 = jnp.bfloat16
    assert kv_budget_multiplier(bf16, 64, "int8") >= 1.9
    assert kv_budget_multiplier(bf16, 128, "int8") >= 1.9
    assert kv_budget_multiplier(bf16, 64, "int4") >= 3.5
    assert kv_budget_multiplier(bf16, 128, "int4") >= 3.5
    assert kv_budget_multiplier(bf16, 64, None) == 1.0
    assert kv_budget_multiplier(bf16, 64, "bf16") == 1.0
    with pytest.raises(ValueError, match="unknown kv_dtype"):
        kv_budget_multiplier(bf16, 64, "fp8")


def test_budget_feeds_pool_engine_and_ledger_identically(setup):
    """The dedupe regression: for int8 AND int4, the engine's pool
    scaling, ``InferenceEngine.kv_budget_x`` and the adapter's
    router-side ledger all derive from ``kv_budget_multiplier`` — no
    mirrored arithmetic anywhere to drift apart."""
    from dlrover_tpu.serving.router.replica import InferenceEngineAdapter

    cfg, _ = setup
    budget = 12
    native = _engine(setup, paged=True, block_size=8,
                     cache_blocks=budget)
    free_native = InferenceEngineAdapter(native).blocks_free()
    for kv_dtype in ("int8", "int4"):
        eng = _engine(setup, paged=True, block_size=8,
                      cache_blocks=budget, kv_dtype=kv_dtype)
        x = kv_budget_multiplier(cfg.dtype, cfg.head_dim_, kv_dtype)
        adapter = InferenceEngineAdapter(eng)
        # one source: the engine's multiplier IS the formula, and the
        # pool it scales is the only thing the ledger ever reads
        assert eng.kv_budget_x == x
        assert eng._blockmgr.num_blocks == int(budget * x)
        # and the placement ledger sees the multiplied pool
        assert adapter.blocks_free() == eng._blockmgr.num_blocks - 1
        assert adapter.blocks_free() >= (x / 1.05) * free_native
    # int4 pool bytes stay within the native budget's bytes
    eng4 = _engine(setup, paged=True, block_size=8,
                   cache_blocks=budget, kv_dtype="int4")

    def pool_bytes(e):
        c = e._cache
        total = 0
        for key in ("k_pool", "v_pool", "k_scale", "v_scale"):
            if key in c:
                total += sum(
                    x.size * x.dtype.itemsize for x in c[key])
        return total

    assert pool_bytes(eng4) <= pool_bytes(native) * 1.05


# -- same-step batched prefill ----------------------------------------------


def test_batched_prefill_deserializes_concurrent_ttft(setup):
    """N long prompts admitted together reach their first tokens in
    the SAME engine step (their chunks ride one batched dispatch per
    step) — the round-robin one-per-step scheme made the i-th prompt
    wait ~i times the first's TTFT.  The cursor invariant holds for
    every prefilling slot every step."""
    cfg, _ = setup
    eng = _engine(setup, max_slots=3, prefill_chunk=16, paged=True,
                  block_size=8)
    longs = [_prompts(cfg, 1, 64, seed=s)[0] for s in (7, 8, 9)]
    rids = [eng.add_request(p, 4) for p in longs]
    ttft_step = {}
    cursors = {r: 0 for r in rids}
    for step_n in range(1, 16):
        finished = eng.step()
        for s, r in enumerate(eng._slot_req):
            if r is None or r.rid not in cursors:
                continue
            if eng._prefilling[s]:
                cur = int(eng._prefill_pos[s])
                assert 0 < cur - cursors[r.rid] <= eng.prefill_chunk
                cursors[r.rid] = cur
        # a short-budget request can finish INSIDE the step its
        # prefill completes (first token + a decode chunk) — first
        # tokens are read from live slots AND the finished list
        for r in list(eng._slot_req) + list(finished):
            if r is not None and r.rid in cursors and r.output \
                    and r.rid not in ttft_step:
                ttft_step[r.rid] = step_n
        if len(ttft_step) == len(rids):
            break
    assert set(ttft_step) == set(rids)
    # all three first tokens on the SAME step: no serialization
    assert len(set(ttft_step.values())) == 1, ttft_step
    # one batched dispatch per step: chunks advanced 3 slot-chunks
    # per dispatch while all three prefilled
    assert eng.stats.prefill_chunk_slots > eng.stats.prefill_chunks
    res = eng.run()
    assert all(len(res[r]) == 4 for r in rids)


def test_batched_prefill_greedy_parity_vs_monolithic(setup):
    """Batched same-step chunks must produce the monolithic prefill's
    exact greedy outputs — the chunk program is verify_step rows,
    independent by construction (dense AND paged)."""
    cfg, _ = setup
    longs = [_prompts(cfg, 1, 48, seed=s)[0] for s in (4, 5)]
    shorts = [p for p in _prompts(cfg, 2, 6, seed=6)]

    def run(**kw):
        eng = _engine(setup, max_slots=4, **kw)
        rids = [eng.add_request(p, 8) for p in longs + shorts]
        res = eng.run()
        return [res[r] for r in rids]

    base = run()
    for extra in (dict(prefill_chunk=16),
                  dict(prefill_chunk=16, paged=True, block_size=8),
                  dict(prefill_chunk=16, paged=True, block_size=8,
                       kv_dtype="int8")):
        for a, b in zip(base, run(**extra)):
            np.testing.assert_array_equal(a, b)


def test_cancel_mid_batched_prefill_reclaims_everything(setup):
    """Cancelling ONE of several batch-prefilling prompts frees its
    slot + lifetime blocks immediately; the surviving prompts keep
    advancing and the books balance after the drain."""
    cfg, _ = setup
    eng = _engine(setup, max_slots=3, prefill_chunk=16, paged=True,
                  block_size=8)
    total = eng._blockmgr.num_blocks - 1
    longs = [_prompts(cfg, 1, 64, seed=s)[0] for s in (1, 2)]
    r1, r2 = [eng.add_request(p, 4) for p in longs]
    eng.step()
    assert int(eng._prefilling.sum()) == 2
    victim_slot = next(s for s, r in enumerate(eng._slot_req)
                       if r is not None and r.rid == r1)
    held = eng._blockmgr.available_blocks
    assert held < total
    assert eng.cancel(r1) is True
    assert eng._slot_req[victim_slot] is None
    assert not eng._prefilling[victim_slot]
    assert eng._blockmgr.available_blocks > held
    res = eng.run()
    assert r1 not in res and len(res[r2]) == 4
    assert eng._blockmgr.available_blocks == total, (
        "cancel mid-batched-prefill leaked blocks")


# -- metric plumbing --------------------------------------------------------


def test_new_metric_families_flow_to_router(setup):
    """attention impl + kernel seconds + int4 blocks: engine ->
    adapter.engine_metrics -> RouterMetrics -> /metrics dict + the
    labeled serving_attention_impl render; all names registered."""
    from dlrover_tpu.serving.router.metrics import RouterMetrics
    from dlrover_tpu.serving.router.replica import InferenceEngineAdapter
    from dlrover_tpu.utils.metric_registry import (
        METRIC_HELP,
        METRIC_LABELS,
    )

    eng = _engine(setup, paged=True, block_size=8, kv_dtype="int4",
                  attention_impl="pallas")
    for p in _prompts(setup[0], 2, 12):
        eng.add_request(p, 4)
    eng.run()
    em = InferenceEngineAdapter(eng).engine_metrics()
    assert em["attention_impl_pallas"] == 1.0
    assert em["paged_kernel_step_seconds"] > 0.0
    assert em["kv4_blocks"] == eng.kv4_blocks > 0

    m = RouterMetrics()
    m.observe_engine_metrics([em, None])
    out = m.metrics()
    assert out["serving_kv_int4_blocks"] == em["kv4_blocks"]
    assert out["serving_paged_kernel_step_seconds"] == \
        em["paged_kernel_step_seconds"]
    text = m.render_labeled()
    assert 'serving_attention_impl{impl="pallas"} 1' in text
    assert 'serving_attention_impl{impl="xla"} 0' in text
    for name in ("serving_attention_impl",
                 "serving_paged_kernel_step_seconds",
                 "serving_kv_int4_blocks"):
        assert name in METRIC_HELP
    assert METRIC_LABELS["serving_attention_impl"] == ("impl",)
    # reporters leaving zeroes the aggregates (no frozen dead-fleet
    # values) and drops both labeled series to 0
    m.observe_engine_metrics([None])
    assert m.metrics()["serving_kv_int4_blocks"] == 0.0
    assert 'serving_attention_impl{impl="pallas"} 0' in \
        m.render_labeled()


def test_dense_replicas_stay_out_of_the_impl_gauge(setup):
    """Review finding: a dense (non-paged) engine has NO paged
    attention path, so it must not report attention_impl keys at all
    — otherwise the labeled xla series could never reach zero and the
    fleet's xla->pallas crossover would be invisible."""
    from dlrover_tpu.serving.router.metrics import RouterMetrics
    from dlrover_tpu.serving.router.replica import InferenceEngineAdapter

    dense = _engine(setup)
    em = InferenceEngineAdapter(dense).engine_metrics()
    assert "attention_impl_pallas" not in em
    assert "paged_kernel_step_seconds" not in em
    m = RouterMetrics()
    m.observe_engine_metrics([em])
    assert m.attention_impls == {}
    assert 'serving_attention_impl{impl="xla"} 0' in m.render_labeled()


def test_worker_flags_reach_the_engine(monkeypatch):
    """--attention-impl / --kv-dtype int4 plumb end-to-end into the
    llama engine build (the worker-side half of the remote fleet's
    knob contract)."""
    import argparse

    from dlrover_tpu.serving.remote import worker as worker_mod

    captured = {}

    class _FakeEngine:
        def __init__(self, *a, **kw):
            captured.update(kw)
            raise RuntimeError("stop after capture")

    monkeypatch.setattr(
        "dlrover_tpu.serving.engine.InferenceEngine", _FakeEngine)
    args = argparse.Namespace(
        max_len=256, seed=0, slots=2, block_size=8,
        kv_dtype="int4", prefill_chunk=32, speculative_k=0,
        attention_impl="pallas")
    with pytest.raises(RuntimeError, match="stop after capture"):
        worker_mod._build_llama_engine(args)
    assert captured["kv_dtype"] == "int4"
    assert captured["attention_impl"] == "pallas"
    assert captured["prefill_chunk"] == 32


# -- nightly int4 drift study + TPU microbench ------------------------------


@pytest.mark.slow
def test_int4_drift_study_long_context_soak(setup):
    """The drift study the int4 budget claim rides on (nightly):
    Pareto heavy-tail prompt lengths decode through int4 and native
    twins in lockstep (teacher-forced: both see the NATIVE engine's
    committed tokens), building a per-step logit-drift histogram —
    p50 and p99 of drift/spread must stay within bound, so a drift
    regression shows up as a distribution shift, not a flaky argmax."""
    from dlrover_tpu.serving.model import verify_step
    from dlrover_tpu.serving.router.loadgen import (
        LoadgenConfig,
        OpenLoopGenerator,
    )

    cfg, _ = setup
    lg = LoadgenConfig(seed=29, rate_qps=40.0, duration_s=1.0,
                       prompt_mix="heavy_tail", prompt_min=8,
                       prompt_max=64, pareto_alpha=1.2)
    arrivals = list(OpenLoopGenerator(lg).arrivals())[:12]
    assert max(a.prompt_len for a in arrivals) > 32
    rng = np.random.RandomState(29)
    ratios = []
    for a in arrivals:
        plen = min(a.prompt_len, 64)
        prompt = rng.randint(0, cfg.vocab_size, plen).astype(np.int32)

        engs = {}
        for kv in (None, "int4"):
            e = _engine(setup, max_slots=1, paged=True, block_size=8,
                        kv_dtype=kv)
            e.add_request(prompt, 16)
            e._admit()
            if e._table_dirty:
                e._push_table()
            engs[kv] = e
        ref_e, q_e = engs[None], engs["int4"]
        tok = int(ref_e._tokens[0])
        for _ in range(8):   # teacher-forced decode steps
            outs = {}
            for kv, e in engs.items():
                logits, e._cache = verify_step(
                    e.params, cfg, e._cache,
                    jnp.asarray([[tok]], jnp.int32),
                    jnp.asarray(e._positions))
                outs[kv] = np.asarray(logits[0, 0])
            spread = float(outs[None].max() - outs[None].min())
            ratios.append(
                float(np.max(np.abs(outs["int4"] - outs[None])))
                / max(spread, 1e-9))
            tok = int(outs[None].argmax())
            for e in engs.values():
                e._positions[0] += 1
    ratios = np.asarray(ratios)
    assert ratios.size >= 90
    hist, _ = np.histogram(ratios, bins=10, range=(0.0, 0.5))
    assert hist.sum() == ratios.size, "drift beyond 50% of spread"
    assert float(np.percentile(ratios, 50)) <= 0.10, ratios
    assert float(np.percentile(ratios, 99)) <= 0.25, ratios


@pytest.mark.slow
def test_tpu_kernel_microbench_stub():
    """TPU-marked kernel microbench: on a TPU backend, measure the
    fused kernel vs the gather at a serving-class geometry and record
    the crossover evidence; anywhere else, skip cleanly — never a
    fake verdict."""
    if jax.default_backend() in ("cpu", "gpu"):
        pytest.skip("paged-attention microbench needs a TPU backend")
    rng = np.random.RandomState(0)
    B, H, KV, D, bs, MB = 8, 16, 4, 128, 16, 96
    nb = B * MB + 1
    q = jnp.asarray(rng.randn(B, H, D).astype(np.float32)).astype(
        jnp.bfloat16)
    kf = jnp.asarray(
        rng.randn(nb, bs, KV, D).astype(np.float32) * 0.3)
    k8, ks = quantize_kv_int8(kf)
    v8, vs = quantize_kv_int8(kf)
    table = jnp.asarray(
        (np.arange(B * MB) % (nb - 1) + 1)
        .reshape(B, MB).astype(np.int32))
    lengths = jnp.full((B,), MB * bs, jnp.int32)
    t = measure_paged_attention(q, k8, v8, table, lengths, ks, vs,
                                trials=5)
    assert t["xla"] > 0 and t["pallas"] > 0
    # the structural claim this PR makes: reading code-width bytes
    # once beats materialize-then-restream on quantized pools
    assert t["pallas"] <= t["xla"] * 1.2, t
