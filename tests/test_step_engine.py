"""Step-engine suite (ISSUE 15): the router data-plane rebuild.

Covers the seam itself (event loop vs historical sweep vs the sharded
front), the incremental placement index's no-rescan guarantee (the
scheduling-decision-count regression pin the acceptance criteria
name), the event-driven cancel/expiry sweeps, batched frame drains,
the step-phase/step-lock histograms on /metrics, the full-pipeline
open-loop rig, and the satellites (cached worker trace headers, the
sampled traceparent fast path).

The equivalence test is the safety net under the whole refactor: the
same seeded workload — mixed priorities, cancels, an expiry, a replica
failure — must reach the SAME terminal state and output per submitted
request under the old sweep, the event loop, and the sharded front.
"""

import threading
import time

import numpy as np
import pytest

msgpack = pytest.importorskip(
    "msgpack", reason="remote fabric frames are msgpack")

from dlrover_tpu.common.constants import (  # noqa: E402
    ServingRequestState,
)
from dlrover_tpu.serving.remote.protocol import (  # noqa: E402
    FrameConnection,
    FrameKind,
)
from dlrover_tpu.serving.remote.worker import (  # noqa: E402
    FakeEngine,
    WorkerServer,
)
from dlrover_tpu.serving.router import (  # noqa: E402
    PRIORITY_BATCH,
    PRIORITY_HIGH,
    PRIORITY_NORMAL,
    BrownoutPolicy,
    BrownoutShedError,
    ContinuousBatchScheduler,
    RequestGateway,
    RouterMetrics,
    ServingRouter,
    ShardedRouterFront,
)
from dlrover_tpu.serving.router.loadgen import (  # noqa: E402
    LoadgenConfig,
    run_router_rig,
)
from dlrover_tpu.serving.router.stepengine import shard_of  # noqa: E402


def _prompt(i, n=8):
    return np.full(n, i % 251, np.int32)


def _router(step_engine, **kw):
    return ServingRouter(
        scheduler=ContinuousBatchScheduler(block_size=4),
        step_engine=step_engine, **kw)


# -- the seam ----------------------------------------------------------------


def test_step_engine_validation():
    with pytest.raises(ValueError):
        ServingRouter(step_engine="warp")
    r = ServingRouter(step_engine="sweep")
    assert r.gateway.incremental is False
    assert r.scheduler.incremental is False
    r = ServingRouter()  # shipped default = the measured winner
    assert r.step_engine == "event"
    assert r.gateway.incremental is True
    assert r.scheduler.incremental is True


def test_sharded_front_partitions_by_rid_hash():
    front = ShardedRouterFront(num_shards=3)
    for i in range(3):
        front.shards[i].join_replica(
            f"r{i}", FakeEngine(slots=8, tokens_per_step=8))
    reqs = [front.submit(_prompt(i), 4) for i in range(30)]
    per_shard = [s.gateway.submitted for s in front.shards]
    assert sum(per_shard) == 30
    assert all(n > 0 for n in per_shard), per_shard
    front.run_until_idle()
    for r in reqs:
        assert r.state == ServingRequestState.DONE
    # the partition function itself is deterministic and total
    assert {shard_of(rid, 3) for rid in range(100)} == {0, 1, 2}


# -- placement fast path: the scheduling-decision-count pin ------------------


def test_placement_idle_cost_does_not_scale():
    """THE regression pin from the acceptance criteria: with R
    replicas all busy and Q queued requests nothing can place, the
    event engine's per-step placement cost must NOT scale with R x Q —
    after the round that blocks them, further steps do ZERO capacity
    evaluations until capacity actually grows.  The sweep twin shows
    the product the index kills."""
    R, Q = 32, 200
    evals = {}
    for engine in ("sweep", "event"):
        router = _router(engine)
        for i in range(R):
            router.join_replica(
                f"r{i}", FakeEngine(slots=1, tokens_per_step=1,
                                    max_len=4096))
        # pin every slot with a long job (well under max_len)
        pins = [router.submit(_prompt(i), 2000, timeout=None)
                for i in range(R)]
        for _ in range(2):
            router.step()
        assert all(p.state == ServingRequestState.RUNNING
                   for p in pins)
        blocked = [router.submit(_prompt(i), 8, timeout=None)
                   for i in range(Q)]
        router.step()  # the round that blocks them
        e0 = router.scheduler.capacity_evals
        for _ in range(10):
            router.step()
        evals[engine] = router.scheduler.capacity_evals - e0
        assert all(b.state == ServingRequestState.QUEUED
                   for b in blocked)
        if engine == "event":
            # the round short-circuit engaged for the idle steps
            assert router.scheduler.rounds_skipped >= 8
        else:
            assert router.scheduler.rounds_skipped == 0
    assert evals["event"] == 0, (
        f"idle entries must cost zero fit evaluations, got "
        f"{evals['event']}")
    # the sweep's cost is the (replicas x window) product, every step
    assert evals["sweep"] >= 10 * R * min(Q, 64) * 0.9


def test_capacity_growth_unblocks_requests():
    """The flip side of the pin: blocked requests MUST re-scan as soon
    as any replica's capacity grows — a stale blocked stamp that
    outlives freed capacity would strand the queue."""
    router = _router("event")
    eng = FakeEngine(slots=1, tokens_per_step=4, max_len=4096)
    router.join_replica("r0", eng)
    pin = router.submit(_prompt(0), 2000, timeout=None)
    router.step()
    assert pin.state == ServingRequestState.RUNNING
    blocked = [router.submit(_prompt(i), 8, timeout=None)
               for i in range(5)]
    for _ in range(5):
        router.step()
    assert all(b.state == ServingRequestState.QUEUED for b in blocked)
    # withdraw the pin -> slot frees -> capacity generation bumps ->
    # blocked requests place, one at a time, until all complete
    pin.cancel()
    deadline = time.monotonic() + 10.0
    while router.has_work and time.monotonic() < deadline:
        router.step()
    assert pin.state == ServingRequestState.CANCELLED
    for b in blocked:
        assert b.state == ServingRequestState.DONE, (b.rid, b.state)


def test_queue_removal_invalidates_idle_marker():
    """Review-found starvation regression: a window full of
    unplaceable requests blocks everything behind it; when they leave
    the queue WITHOUT a placement or an admission (deadline expiry
    here — cancellation and brown-out shed are the same class), the
    scheduler's idle short-circuit must invalidate, or the now-visible
    placeable requests behind the window starve forever while the
    fleet sits idle."""
    t = 3000.0
    router = _router("event")
    # one replica with a tiny KV budget: big requests can never fit
    eng = FakeEngine(slots=4, tokens_per_step=4, block_size=4,
                     blocks=20, max_len=4096)
    router.join_replica("r0", eng, now=t)
    # a full schedule window of unplaceable requests with a deadline
    big = [router.submit(_prompt(i, n=64), 512, timeout=1.0, now=t)
           for i in range(64)]
    # placeable requests stuck BEHIND the window
    small = [router.submit(_prompt(i), 4, timeout=None, now=t)
             for i in range(4)]
    router.step(now=t)
    assert all(b.state == ServingRequestState.QUEUED for b in big)
    assert all(s.state == ServingRequestState.QUEUED for s in small)
    # the big ones expire out of the queue; nothing else changes —
    # no admission, no capacity growth
    router.step(now=t + 1.5)
    assert all(b.state == ServingRequestState.TIMED_OUT for b in big)
    # the smalls must now enter the window and complete
    for _ in range(10):
        router.step(now=t + 2.0)
        if not router.has_work:
            break
    for s in small:
        assert s.state == ServingRequestState.DONE, (s.rid, s.state)


def test_affinity_reverse_index_consistency():
    """Affinity placement must survive the index rebuild: a replica
    that served a prefix wins its next request, and forgetting the
    replica cleans the reverse index."""
    sched = ContinuousBatchScheduler(block_size=4, prefix_tokens=8,
                                     incremental=True)
    gw = RequestGateway()
    gw.incremental = True
    engines = {name: FakeEngine(slots=4, tokens_per_step=8)
               for name in ("a", "b")}

    class H:
        def __init__(self, name, eng):
            self.name, self.eng = name, eng

        def slots_free(self):
            return self.eng.slots_free()

        def blocks_free(self):
            return self.eng.blocks_free()

    handles = [H(n, e) for n, e in engines.items()]
    prompt = np.arange(16, dtype=np.int32)
    r1 = gw.submit(prompt, 4)
    placed = sched.schedule(gw, handles)
    assert len(placed) == 1
    winner = placed[0][0].name
    key = sched.prefix_key(prompt)
    assert winner in sched._affinity_index[key]
    # same prefix again: the warm replica must win even if the other
    # is less loaded
    engines[winner].active[99] = {"remaining": 1, "output": [],
                                  "blocks": 0}
    r2 = gw.submit(prompt, 4)
    placed = sched.schedule(gw, handles)
    assert placed[0][0].name == winner
    sched.forget_replica(winner)
    assert key not in sched._affinity_index
    assert r1.state == r2.state  # both left the queue identically


# -- event-driven sweeps -----------------------------------------------------


@pytest.mark.parametrize("step_engine", ["event", "sweep"])
def test_cancel_queued_and_inflight_accounting(step_engine):
    """Queued and in-flight withdrawals answer their callers and
    balance the books identically under both engines."""
    router = _router(step_engine)
    eng = FakeEngine(slots=2, tokens_per_step=1, max_len=4096)
    router.join_replica("r0", eng)
    inflight = [router.submit(_prompt(i), 100) for i in range(2)]
    router.step()
    assert all(r.state == ServingRequestState.RUNNING
               for r in inflight)
    queued = [router.submit(_prompt(i), 8) for i in range(3)]
    assert inflight[0].cancel()
    assert queued[1].cancel()
    router.step()
    assert inflight[0].state == ServingRequestState.CANCELLED
    assert queued[1].state == ServingRequestState.CANCELLED
    assert router.gateway.cancelled == 2
    # the engine slot was reclaimed (CANCEL delivered locally)
    assert inflight[0].engine_rid not in eng.active
    # double-cancel of a terminal request is refused and changes
    # nothing
    assert not inflight[0].cancel()
    router.step()
    assert router.gateway.cancelled == 2


def test_double_cancel_counts_once_event_engine():
    """Review-found books regression: a client retrying cancel() (or
    racing threads) must not inflate the cancelled counter — cancel()
    is idempotent at the source and the event drain dedupes by
    identity as the belt."""
    router = _router("event")
    router.join_replica(
        "r0", FakeEngine(slots=1, tokens_per_step=1, max_len=4096))
    req = router.submit(_prompt(1), 8)
    assert req.cancel()
    assert req.cancel()  # retry: accepted, but one event only
    router.step()
    assert req.state == ServingRequestState.CANCELLED
    assert router.gateway.cancelled == 1
    assert router.gateway.submitted == 1


def test_duplicate_heap_entries_expire_once():
    """Review-found books regression: a failover requeue pushes a
    SECOND deadline-heap entry for the same request; when the deadline
    passes while it is QUEUED, expire() must count it once, not once
    per entry."""
    t = 2000.0
    gw = RequestGateway()
    req = gw.submit(_prompt(1), 4, timeout=5.0, now=t)
    gw.remove(req)
    req.state = ServingRequestState.RUNNING  # placed on a replica
    # the replica dies: requeue_front re-pushes a heap entry
    assert gw.requeue_front([req], now=t + 1.0) == []
    assert req.state == ServingRequestState.QUEUED
    expired = gw.expire(now=t + 6.0)
    assert expired == [req]
    assert gw.timed_out == 1
    assert req.state == ServingRequestState.TIMED_OUT


def test_deadline_heap_expiry_edges():
    """The event engine's heap must reproduce the sweep's strict
    ``now > deadline`` semantics: timeout=0 expires on the NEXT step
    (not at now == deadline), and a failover-requeued request whose
    deadline passed while RUNNING still expires promptly."""
    t = 1000.0
    router = _router("event")
    req = router.submit(_prompt(1), 4, timeout=0.0, now=t)
    router.step(now=t)   # now == deadline: strict >, stays queued
    assert req.state == ServingRequestState.QUEUED
    router.step(now=t + 0.001)
    assert req.state == ServingRequestState.TIMED_OUT

    # requeue-past-deadline: RUNNING through its deadline under the
    # let-it-finish policy, then the replica dies -> requeue -> the
    # replay must expire, not sit in the queue forever
    router = _router("event")
    eng = FakeEngine(slots=1, tokens_per_step=1, max_len=4096)
    router.join_replica("r0", eng, now=t)
    req = router.submit(_prompt(2), 1000, timeout=5.0, now=t)
    router.step(now=t)
    assert req.state == ServingRequestState.RUNNING
    router.step(now=t + 6.0)  # past deadline; policy lets it run
    assert req.state == ServingRequestState.RUNNING
    router.fail_replica("r0")
    router.step(now=t + 7.0)  # failover requeues...
    router.step(now=t + 7.1)  # ...and the re-armed heap expires it
    assert req.state == ServingRequestState.TIMED_OUT


def test_cancel_inflight_on_expiry_event_engine():
    """The expiry-cancel policy rides the deadline heap: a RUNNING
    request past its deadline aborts and frees its engine slot."""
    t = 1000.0
    router = _router("event", cancel_inflight_on_expiry=True)
    eng = FakeEngine(slots=1, tokens_per_step=1, max_len=4096)
    router.join_replica("r0", eng, now=t)
    req = router.submit(_prompt(1), 1000, timeout=2.0, now=t)
    router.step(now=t)
    assert req.state == ServingRequestState.RUNNING
    router.step(now=t + 2.5)
    assert req.state == ServingRequestState.TIMED_OUT
    assert req.engine_rid not in eng.active, "slot must be reclaimed"
    assert router.gateway.timed_out == 1


# -- equivalence: same seeded workload, same terminal states -----------------


def _replay_workload(router):
    """One seeded mixed workload: three priority bands, two cancels, a
    replica failure mid-run.  Returns the per-submission-index
    (state, output length) list — output VALUES differ legitimately
    across engines (FakeEngine tokens encode the engine-local rid, and
    placement distribution is allowed to differ); outcomes may not."""
    t = 5000.0
    engines = [FakeEngine(slots=2, tokens_per_step=2, max_len=4096)
               for _ in range(4)]
    for i, eng in enumerate(engines):
        router.join_replica(f"r{i}", eng, now=t)
    reqs = []
    bands = [PRIORITY_HIGH, PRIORITY_NORMAL, PRIORITY_NORMAL,
             PRIORITY_BATCH]
    for i in range(60):
        reqs.append(router.submit(
            _prompt(i), 8, priority=bands[i % 4],
            timeout=None if i % 7 else 300.0, now=t))
    for step in range(400):
        t += 0.05
        router.step(now=t)
        if step == 2:
            reqs[5].cancel()
            reqs[40].cancel()
        if step == 4:
            # kill one replica: its in-flight requests fail over
            target = (router.shard_of_replica("r1")
                      if isinstance(router, ShardedRouterFront)
                      else router)
            if target is not None:
                target.fail_replica("r1")
        if not router.has_work:
            break
    return [(r.state, len(r.output)) for r in reqs]


@pytest.mark.parametrize("candidate", ["sweep", "sharded"])
def test_step_engine_equivalence_terminal_states(candidate):
    """Same seeded workload -> same terminal state and output per
    submitted request under the event loop (the shipped default) and
    each other candidate.  Placement DISTRIBUTION may differ (the
    index breaks capacity ties by name, shards partition replicas);
    request OUTCOME may not."""
    baseline = _replay_workload(_router("event"))
    if candidate == "sweep":
        other = _replay_workload(_router("sweep"))
    else:
        front = ShardedRouterFront(
            num_shards=2, threaded=False,
            router_factory=lambda i: _router("event"))
        other = _replay_workload(front)
    assert len(baseline) == len(other)
    for i, (a, b) in enumerate(zip(baseline, other)):
        assert a == b, f"submission {i}: event={a} {candidate}={b}"
    # the workload exercised what it claims to
    states = {s for s, _ in baseline}
    assert ServingRequestState.DONE in states
    assert ServingRequestState.CANCELLED in states


def test_failover_equivalence_zero_lost():
    """A replica failure mid-run balances the books under every
    engine: every request terminal, requeues observed, zero poisoned."""
    for make in (
        lambda: _router("event"),
        lambda: _router("sweep"),
        lambda: ShardedRouterFront(
            num_shards=2, threaded=False,
            router_factory=lambda i: _router("event")),
    ):
        router = make()
        t = 7000.0
        for i in range(4):
            router.join_replica(
                f"r{i}", FakeEngine(slots=2, tokens_per_step=1,
                                    max_len=4096), now=t)
        reqs = [router.submit(_prompt(i), 12, now=t)
                for i in range(40)]
        for step in range(500):
            t += 0.05
            router.step(now=t)
            if step == 3:
                if isinstance(router, ShardedRouterFront):
                    victim = router.replica_names[0]
                    router.shard_of_replica(victim).fail_replica(
                        victim)
                else:
                    router.fail_replica("r0")
            if not router.has_work:
                break
        for r in reqs:
            assert r.state == ServingRequestState.DONE, (
                r.rid, r.state)
        if isinstance(router, ShardedRouterFront):
            counters = router.counters()
            assert counters["serving_requests_requeued_total"] >= 1
            assert counters["serving_requests_poisoned_total"] == 0
        else:
            m = router.metrics.metrics()
            assert m["serving_requests_requeued_total"] >= 1
            assert m["serving_requests_poisoned_total"] == 0


# -- sharded front: threads, shared brown-out, remote chaos ------------------


def test_sharded_front_threaded_books_balance():
    front = ShardedRouterFront(num_shards=2, threaded=True)
    for i in range(4):
        front.join_replica(
            f"r{i}", FakeEngine(slots=8, tokens_per_step=8))
    front.start()
    try:
        reqs = [front.submit(_prompt(i), 8) for i in range(200)]
        deadline = time.monotonic() + 30.0
        while front.has_work and time.monotonic() < deadline:
            time.sleep(0.005)
        for r in reqs:
            assert r.state == ServingRequestState.DONE, (
                r.rid, r.state)
        counters = front.counters()
        assert counters["serving_requests_submitted_total"] == 200
        assert counters["serving_requests_completed_total"] == 200
    finally:
        front.stop()


def test_sharded_front_shared_brownout_sheds_every_shard():
    """The shared brown-out view: the FRONT updates one policy with
    fleet-global pressure; once the ladder enters shed_batch, EVERY
    shard's gateway refuses BATCH — a shard with a locally-empty queue
    must shed too (per-shard watermarks would not)."""
    bo = BrownoutPolicy(enter_pressure=2.0, exit_pressure=0.5,
                        dwell_seconds=0.5)
    front = ShardedRouterFront(
        num_shards=2, threaded=False, brownout=bo,
        router_factory=lambda i: ServingRouter(
            scheduler=ContinuousBatchScheduler(block_size=4)))
    # capacity exists on shard 0 only; demand floods both queues
    front.shards[0].join_replica(
        "r0", FakeEngine(slots=1, tokens_per_step=1, max_len=4096))
    t = 9000.0
    front.step(now=t)
    for i in range(40):
        front.submit(_prompt(i), 500, priority=PRIORITY_NORMAL, now=t)
    front.step(now=t)
    front.step(now=t + 0.6)   # dwell earned -> stage 1
    assert bo.stage == 1
    for shard in front.shards:
        with pytest.raises(BrownoutShedError):
            shard.submit(_prompt(99), 4, priority=PRIORITY_BATCH,
                         now=t + 0.7)
    # both shards applied the externally-decided stage to metrics
    for shard in front.shards:
        assert shard.metrics.brownout_stage == 1.0


class _ThreadedWorker:
    def __init__(self, **engine_kw):
        self.engine = FakeEngine(**engine_kw)
        self.server = WorkerServer(self.engine)
        self.thread = threading.Thread(
            target=self.server.serve_forever, daemon=True)
        self.thread.start()

    def stop(self):
        self.server.crash()


def test_sharded_front_remote_chaos_zero_lost():
    """The sharded twin of the chaos acceptance: remote workers behind
    the front's independent (threaded) step loops, one killed abruptly
    mid-stream — zero lost requests, books balance fleet-wide."""
    from dlrover_tpu.serving.remote.proxy import RemoteReplicaHandle

    workers = [_ThreadedWorker(slots=4, tokens_per_step=2,
                               step_delay=0.002) for _ in range(4)]
    front = ShardedRouterFront(num_shards=2, threaded=True)
    try:
        for i, w in enumerate(workers):
            front.join_replica(
                f"w{i}", RemoteReplicaHandle(
                    w.server.addr, name=f"w{i}", frame_timeout=1.0))
        front.start()
        reqs = [front.submit(_prompt(i), 8) for i in range(120)]
        # kill one worker once it holds in-flight requests
        victim = None
        deadline = time.monotonic() + 20.0
        while victim is None and time.monotonic() < deadline:
            for i, w in enumerate(workers):
                shard = front.shard_of_replica(f"w{i}")
                handle = shard.manager.get(f"w{i}") if shard else None
                if handle is not None and handle.inflight:
                    victim = i
                    break
            time.sleep(0.005)
        assert victim is not None
        workers[victim].stop()
        deadline = time.monotonic() + 45.0
        while front.has_work and time.monotonic() < deadline:
            time.sleep(0.01)
        lost = [r for r in reqs
                if r.state != ServingRequestState.DONE]
        assert not lost, [(r.rid, r.state) for r in lost]
        counters = front.counters()
        assert counters["serving_requests_completed_total"] == 120
        assert counters["serving_requests_requeued_total"] >= 1
        assert counters["serving_requests_poisoned_total"] == 0
    finally:
        front.stop()
        for w in workers:
            w.stop()


# -- instrumentation on /metrics ---------------------------------------------


def test_step_phase_and_lock_histograms_render():
    """The measure-first half of the acceptance: step-lock hold time
    and per-phase step histograms are registered families rendered on
    the same surface as the latency histograms, with samples after one
    step."""
    from dlrover_tpu.serving.router.metrics import STEP_PHASES
    from dlrover_tpu.utils.metric_registry import (
        METRIC_HELP,
        METRIC_LABELS,
    )

    assert "serving_step_lock_hold_seconds" in METRIC_HELP
    assert "serving_step_phase_seconds" in METRIC_HELP
    assert METRIC_LABELS["serving_step_phase_seconds"] == ("phase",)

    router = _router("event")
    router.join_replica("r0", FakeEngine(slots=2, tokens_per_step=4))
    reqs = [router.submit(_prompt(i), 4) for i in range(4)]
    deadline = time.monotonic() + 10.0
    while router.has_work and time.monotonic() < deadline:
        router.step()
    assert all(r.state == ServingRequestState.DONE for r in reqs)
    text = router.metrics.render_histograms()
    assert "serving_step_lock_hold_seconds_bucket" in text
    # every phase renders as one labeled series of the SAME family,
    # with exactly one TYPE header for it
    for phasename in STEP_PHASES:
        assert f'serving_step_phase_seconds_bucket{{phase="{phasename}"' \
            in text, phasename
    assert text.count("# TYPE serving_step_phase_seconds ") == 1
    # the hot phases actually observed samples
    assert router.metrics.step_phase_hists["pump"].count > 0
    assert router.metrics.step_phase_hists["schedule"].count > 0
    assert router.metrics.step_lock_hist.count > 0
    # and the scheduler counters reached the scrape dict
    m = router.metrics.metrics()
    assert "serving_sched_capacity_evals_total" in m
    assert "serving_sched_rounds_skipped_total" in m


# -- batched frame drains ----------------------------------------------------


def test_recv_many_batches_and_defers_mid_batch_state():
    """recv_many returns the first frame plus everything buffered
    behind it; a clean EOF at a frame boundary ends the batch and the
    NEXT call reports it."""
    import socket

    a, b = socket.socketpair()
    tx = FrameConnection(a)
    rx = FrameConnection(b)
    for i in range(5):
        tx.send(FrameKind.TOKEN, rid=i, tokens=[i])
    time.sleep(0.05)  # let the bytes land in rx's kernel buffer
    frames = rx.recv_many(timeout=1.0)
    assert [f["rid"] for f in frames] == [0, 1, 2, 3, 4]
    tx.send(FrameKind.GOODBYE)
    a.close()
    frames = rx.recv_many(timeout=1.0)
    assert [f["kind"] for f in frames] == [FrameKind.GOODBYE]
    assert rx.recv_many(timeout=1.0) is None  # clean EOF
    rx.close()


def test_proxy_coalesces_token_storm_into_batches():
    """Under a token storm the proxy's reader crosses its lock once
    per BATCH: frames_received grows much faster than frame_batches,
    and the drained events still carry every token in order."""
    from dlrover_tpu.serving.remote.proxy import RemoteReplicaHandle

    w = _ThreadedWorker(slots=8, tokens_per_step=4)
    try:
        proxy = RemoteReplicaHandle(w.server.addr, name="storm")
        router = _router("event")
        router.join_replica("storm", proxy)
        reqs = [router.submit(_prompt(i), 64) for i in range(8)]
        deadline = time.monotonic() + 30.0
        while router.has_work and time.monotonic() < deadline:
            router.step()
            time.sleep(0.001)
        for r in reqs:
            assert r.state == ServingRequestState.DONE
            assert len(r.output) == 64
        assert proxy.frames_received > 50
        assert proxy.frame_batches < proxy.frames_received, (
            "batching never coalesced anything: "
            f"{proxy.frame_batches} batches for "
            f"{proxy.frames_received} frames")
        proxy.close()
    finally:
        w.stop()


# -- the full-pipeline rig ---------------------------------------------------


def test_router_rig_full_pipeline_books_balance():
    """The fast twin of the bench gate: a small open-loop schedule
    through the whole pipeline — zero lost, books balancing, e2e
    percentiles measured from the requests themselves."""
    router = _router("event", gateway=RequestGateway(
        max_pending=4096, default_timeout=10.0))
    for i in range(4):
        router.join_replica(
            f"r{i}", FakeEngine(slots=32, tokens_per_step=8,
                                blocks=500_000))
    rig = run_router_rig(
        router,
        LoadgenConfig(rate_qps=1500, duration_s=0.5, seed=3,
                      max_new_tokens=8))
    assert rig["router_admitted"] > 200
    assert rig["router_lost"] == 0
    assert rig["router_poisoned"] == 0
    assert rig["router_books_ok"]
    assert rig["router_completed"] == rig["router_admitted"]
    assert rig["router_qps"] > 0
    assert rig["router_e2e_p99_s"] > 0


def test_router_rig_mid_flight_cancels_keep_books():
    """cancel_every drives the withdrawal machinery at rate: books
    still balance with cancels in the mix."""
    router = _router("event", gateway=RequestGateway(
        max_pending=4096, default_timeout=10.0))
    for i in range(2):
        router.join_replica(
            f"r{i}", FakeEngine(slots=8, tokens_per_step=2,
                                blocks=500_000))
    rig = run_router_rig(
        router,
        LoadgenConfig(rate_qps=800, duration_s=0.5, seed=5,
                      max_new_tokens=16),
        cancel_every=10)
    assert rig["router_lost"] == 0
    assert rig["router_poisoned"] == 0
    assert rig["router_books_ok"]
    assert rig["router_cancel_attempts"] > 0
    assert rig["router_by_state"].get(
        ServingRequestState.CANCELLED, 0) > 0


# -- satellites --------------------------------------------------------------


def test_worker_trace_header_cached_per_request():
    """The TOKEN-frame trace echo is built once per request, not once
    per frame — and a sampled-out request ships no trace bytes."""
    server = WorkerServer(FakeEngine(slots=2))
    try:
        server._trace_by_erid[7] = {
            "trace": "00-" + "a" * 32 + "-" + "b" * 16 + "-01",
            "t0": 0.0, "t_first": None, "steps": 0, "engine_s": 0.0,
            "hdr": {"trace": "00-" + "a" * 32 + "-" + "b" * 16
                    + "-01"},
        }
        h1 = server._trace_header(7)
        h2 = server._trace_header(7)
        assert h1 is h2, "header must be the cached per-request dict"
        assert server._trace_header(99) == {}
    finally:
        server.crash()


def test_traceparent_sampled_fast_path(monkeypatch):
    """A sampled-IN trace builds its traceparent without consulting
    the tracer (no lock round trip per submit); a sampled-OUT one
    still honors the incident override through should_propagate."""
    from dlrover_tpu.utils.tracing import RequestTrace, Tracer

    tracer = Tracer(sample_rate=1.0)
    rt = RequestTrace(tracer, 1)
    assert rt.sampled is True
    calls = {"n": 0}
    real = tracer.should_propagate

    def counting(trace_id):
        calls["n"] += 1
        return real(trace_id)

    monkeypatch.setattr(tracer, "should_propagate", counting)
    assert rt.traceparent() is not None
    assert calls["n"] == 0, "sampled-in must skip the tracer lock"

    # sampled-out: propagation denied until the incident override
    tracer = Tracer(sample_rate=0.0)
    rt = RequestTrace(tracer, 2)
    assert rt.sampled is False
    assert rt.traceparent() is None
    tracer.mark_incident(rt.root.trace_id, "failover")
    assert rt.traceparent() is not None


def test_sampled_out_done_frames_skip_span_work():
    """End-to-end: at sample_rate=0.0 a remote completion carries no
    spans and grafts nothing — the frame path pays no tracing cost the
    knob was meant to shed; incidents still keep their trace."""
    from dlrover_tpu.serving.remote.proxy import RemoteReplicaHandle

    w = _ThreadedWorker(slots=4, tokens_per_step=4)
    try:
        router = ServingRouter(
            gateway=RequestGateway(trace_sample_rate=0.0),
            scheduler=ContinuousBatchScheduler(block_size=4))
        proxy = RemoteReplicaHandle(w.server.addr, name="w")
        router.join_replica("w", proxy)
        reqs = [router.submit(_prompt(i), 8) for i in range(4)]
        deadline = time.monotonic() + 20.0
        while router.has_work and time.monotonic() < deadline:
            router.step()
            time.sleep(0.002)
        for r in reqs:
            assert r.state == ServingRequestState.DONE
            assert r.trace.sampled is False
        assert router.tracer.orphan_spans_total == 0
        # nothing retained: the knob bit end to end
        assert router.tracer.dropped_total == 4
        proxy.close()
    finally:
        w.stop()


# -- the nightly soak --------------------------------------------------------


@pytest.mark.slow
def test_router_open_loop_soak_60s():
    """Nightly: 60s of full-router open-loop traffic — a bursty
    segment then a diurnal segment, heavy-tail prompts, mid-flight
    cancels every 50 admissions — books balance and nothing is lost
    or poisoned at the end of each segment."""
    for arrival, seed in (("bursty", 11), ("diurnal", 13)):
        router = ServingRouter(
            gateway=RequestGateway(
                max_pending=8192, default_timeout=10.0,
                trace_sample_rate=0.01),
            scheduler=ContinuousBatchScheduler(block_size=4),
            metrics=RouterMetrics(window_seconds=5.0),
        )
        for i in range(8):
            router.join_replica(
                f"r{i}", FakeEngine(slots=64, tokens_per_step=8,
                                    blocks=2_000_000))
        rig = run_router_rig(
            router,
            LoadgenConfig(
                rate_qps=4000, duration_s=30.0, seed=seed,
                arrival=arrival, prompt_mix="heavy_tail",
                max_new_tokens=8),
            cancel_every=50)
        assert rig["router_lost"] == 0, (arrival, rig)
        assert rig["router_poisoned"] == 0, (arrival, rig)
        assert rig["router_books_ok"], (arrival, rig)
        assert rig["router_cancel_attempts"] > 0
        assert rig["router_qps"] >= 1000, (arrival, rig)
