"""Diagnosis subsystem + paral-config tuner tests (reference parity:
master/diagnosis/diagnosis.py InferenceChain/operators, elastic_agent/
monitor/diagnosis.py collectors, config/paral_config_tuner.py)."""

import json
import time

import pytest

from dlrover_tpu.agent.config.paral_config_tuner import (
    ParalConfigTuner,
    read_paral_config,
    write_paral_config,
)
from dlrover_tpu.agent.monitor.diagnosis import (
    DiagnosisReporter,
    LogCollector,
    MetricsCollector,
)
from dlrover_tpu.common import comm
from dlrover_tpu.master.diagnosis.diagnosis import (
    CheckFailureNodeOperator,
    CheckTrainingHangOperator,
    DiagnosisDataManager,
    DiagnosisManager,
    InferenceChain,
    InferenceName,
)


def _metrics(node_id, age=0.0):
    return comm.DiagnosisReportData(
        data_cls="metrics", data_content='{"step": 5}',
        node_id=node_id, timestamp=time.time() - age)


def _log(node_id, text):
    return comm.DiagnosisReportData(
        data_cls="log", data_content=text, node_id=node_id,
        timestamp=time.time())


def test_hang_operator_detects_job_wide_stall():
    data = DiagnosisDataManager(expire_seconds=10_000)
    op = CheckTrainingHangOperator(hang_seconds=60)
    data.store(_metrics(0, age=120))
    data.store(_metrics(1, age=90))
    out = op.infer(data)
    assert len(out) == 1
    assert out[0].name == InferenceName.TRAINING_HANG
    assert out[0].severity == "critical"


def test_hang_operator_sees_through_data_expiry():
    """Evidence older than the expiry window is exactly the stale case:
    expiry (default 600s) shorter than hang threshold (900s) must not
    mask the hang."""
    data = DiagnosisDataManager(expire_seconds=60)
    op = CheckTrainingHangOperator(hang_seconds=120)
    data.store(_metrics(0, age=300))  # expired AND stale
    out = op.infer(data)
    assert out and out[0].name == InferenceName.TRAINING_HANG


def test_hang_operator_quiet_when_any_node_progresses():
    data = DiagnosisDataManager(expire_seconds=10_000)
    op = CheckTrainingHangOperator(hang_seconds=60)
    data.store(_metrics(0, age=120))
    data.store(_metrics(1, age=1))  # one live node => no job-wide hang
    assert op.infer(data) == []


def test_failure_operator_classifies_oom_and_fatal():
    data = DiagnosisDataManager()
    op = CheckFailureNodeOperator()
    data.store(_log(0, "...RESOURCE_EXHAUSTED: Out of memory on device..."))
    data.store(_log(1, "Segmentation fault (core dumped)"))
    data.store(_log(2, "all good here"))
    out = {i.node_id: i.name for i in op.infer(data)}
    assert out[0] == InferenceName.OOM
    assert out[1] == InferenceName.NODE_FAILURE
    assert 2 not in out


def test_diagnosis_manager_acts_on_inferences():
    acted = []
    mgr = DiagnosisManager(
        chain=InferenceChain([CheckFailureNodeOperator()]),
        on_inference=acted.append,
    )
    mgr.collect_diagnosis_data(_log(4, "oom-killed process"))
    out = mgr.diagnose_once()
    assert len(out) == 1 and acted == out
    assert acted[0].node_id == 4


def test_data_manager_expiry():
    data = DiagnosisDataManager(expire_seconds=0.05)
    data.store(_metrics(0))
    time.sleep(0.1)
    assert data.get(0) == []


# -- agent-side collectors --------------------------------------------------

def test_metrics_and_log_collectors(tmp_path):
    metrics_file = tmp_path / "rt.json"
    metrics_file.write_text(json.dumps({"step": 7}))
    log_file = tmp_path / "worker.log"
    log_file.write_text("x" * 100 + "\nOOM near the end\n")

    mc = MetricsCollector(node_id=1, path=str(metrics_file))
    d = mc.collect()
    assert d.data_cls == "metrics" and json.loads(d.data_content)["step"] == 7

    lc = LogCollector(node_id=1, log_path=str(log_file), max_bytes=32)
    d = lc.collect()
    assert d.data_cls == "log"
    assert "OOM near the end" in d.data_content
    assert len(d.data_content) <= 32


def test_diagnosis_reporter_e2e(local_master, master_client, tmp_path):
    """Collector -> client -> servicer -> master DiagnosisManager."""
    master, _ = local_master
    mgr = DiagnosisManager(
        chain=InferenceChain([CheckFailureNodeOperator()]))
    master.servicer._diagnosis_manager = mgr
    log_file = tmp_path / "w.log"
    log_file.write_text("FATAL: chip wedged, core dumped")
    reporter = DiagnosisReporter(
        master_client, [LogCollector(0, str(log_file))], interval=60)
    assert reporter.report_once() == 1
    out = mgr.diagnose_once()
    assert out and out[0].name == InferenceName.NODE_FAILURE


# -- error monitor ----------------------------------------------------------

def test_error_monitor_classification_and_events():
    from dlrover_tpu.common.constants import NodeExitReason
    from dlrover_tpu.common.node import Node
    from dlrover_tpu.master.monitor.error_monitor import (
        JobErrorMonitor,
        classify_error,
    )

    assert classify_error("RESOURCE_EXHAUSTED: OOM") == NodeExitReason.OOM
    assert classify_error("ICI link down on host") == \
        NodeExitReason.HARDWARE_ERROR
    assert classify_error("spot reclaim notice") == NodeExitReason.PREEMPTED
    assert classify_error("Segmentation fault") == NodeExitReason.FATAL_ERROR
    assert classify_error("???") == NodeExitReason.UNKNOWN_ERROR

    events = []
    mon = JobErrorMonitor(on_event=lambda *a: events.append(a))
    node = Node("worker", 2)
    reason, relaunchable = mon.process_error(node, 1, "worker OOMKilled")
    assert reason == NodeExitReason.OOM and relaunchable
    assert node.exit_reason == NodeExitReason.OOM
    assert events[0][0] == "node_oomkilled"
    # fatal errors are not relaunchable
    _, relaunchable = mon.process_error(node, 1, "core dumped")
    assert not relaunchable


# -- paral config tuner -----------------------------------------------------

def test_write_read_paral_config(tmp_path):
    path = str(tmp_path / "paral.json")
    cfg = comm.ParallelConfig(
        dataloader=comm.DataLoaderConfig(batch_size=64, num_workers=4,
                                         version=2))
    write_paral_config(cfg, path)
    data = read_paral_config(path)
    assert data["dataloader"]["batch_size"] == 64


def test_paral_config_tuner_e2e(local_master, master_client, tmp_path):
    """Master publishes a config -> tuner writes the file -> the
    ElasticDataLoader hot-reloads its batch size (the reference's
    auto-tuning loop)."""
    master, _ = local_master

    class _JM:  # minimal job-manager surface for the servicer get path
        def __init__(self):
            self._cfg = None

        def get_paral_config(self, node_id):
            return self._cfg

    jm = _JM()
    master.servicer._job_manager = jm
    path = str(tmp_path / "paral.json")
    tuner = ParalConfigTuner(master_client, interval=60, path=path)

    # no version bump -> no file
    tuner.check_once()
    first_write = read_paral_config(path)

    jm._cfg = comm.ParallelConfig(
        dataloader=comm.DataLoaderConfig(batch_size=16, num_workers=2,
                                         version=1))
    tuner.check_once()
    data = read_paral_config(path)
    assert data["dataloader"]["batch_size"] == 16

    # the dataloader picks the new batch size up
    from dlrover_tpu.trainer.elastic.dataloader import ElasticDataLoader
    from dlrover_tpu.trainer.elastic.sampler import ElasticDistributedSampler

    loader = ElasticDataLoader(
        dataset=list(range(64)), batch_size=4,
        sampler=ElasticDistributedSampler(64, num_replicas=1, rank=0),
        config_file=path)
    loader.load_config()
    assert loader.batch_size == 16
    assert first_write is None or first_write["dataloader"]["version"] == 0


# ---------------------------------------------------------------------------
# stack forensics (reference: cuda_log_collector.py py-spy-style dumps)
# ---------------------------------------------------------------------------


def test_stack_dump_names_stuck_function(tmp_path):
    """A real stalled subprocess: trigger_stack_dumps must return a
    traceback naming the function it is stuck in, and the summary line
    must carry it."""
    import subprocess
    import sys

    from dlrover_tpu.agent.monitor.stack_dump import (
        summarize_stacks,
        trigger_stack_dumps,
    )

    dump_dir = str(tmp_path / "stacks")
    code = (
        "import time\n"
        "from dlrover_tpu.agent.monitor.stack_dump import enable_stack_dump\n"
        f"enable_stack_dump({dump_dir!r})\n"
        "def definitely_stuck_here():\n"
        "    time.sleep(300)\n"
        "print('ready', flush=True)\n"
        "definitely_stuck_here()\n"
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", code], stdout=subprocess.PIPE, text=True)
    try:
        assert proc.stdout.readline().strip() == "ready"
        time.sleep(0.3)  # let it enter the sleep
        dumps = trigger_stack_dumps([proc.pid], dump_dir=dump_dir,
                                    wait=5.0)
        assert "definitely_stuck_here" in dumps[proc.pid]
        summary = summarize_stacks(dumps)
        assert "definitely_stuck_here" in summary
        assert str(proc.pid) in summary
    finally:
        proc.kill()
        proc.wait()


def test_stack_dump_reports_unresponsive_worker(tmp_path):
    """A pid that never handles the signal yields an explanatory
    placeholder, not a silent drop."""
    from dlrover_tpu.agent.monitor.stack_dump import trigger_stack_dumps

    # pid that exists but has no handler registered in our dump dir:
    # use a short-lived subprocess WITHOUT enable_stack_dump
    import subprocess
    import sys

    proc = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(30)"])
    try:
        dumps = trigger_stack_dumps(
            [proc.pid], dump_dir=str(tmp_path), wait=0.5)
        assert "no stack dump" in dumps[proc.pid]
    finally:
        proc.kill()
        proc.wait()


def test_hang_inference_includes_worker_stacks():
    """Stale metrics + shipped stack data: the hang conclusion's reason
    names the stuck frame (master half of the forensics chain)."""
    data = DiagnosisDataManager(expire_seconds=10_000)
    data.store(_metrics(0, age=120))
    data.store(comm.DiagnosisReportData(
        data_cls="stack",
        data_content=(
            'Current thread 0x1 (most recent call first):\n'
            '  File "/app/train.py", line 99 in blocked_allreduce\n'
        ),
        node_id=0, timestamp=time.time()))
    ops = CheckTrainingHangOperator(hang_seconds=60)
    out = ops.infer(data)
    assert out and out[0].name == InferenceName.TRAINING_HANG
    assert "blocked_allreduce" in out[0].reason
