"""Schema-check the shipped K8s manifests (VERDICT r3 weak #7).

No cluster and no kubernetes package in the image, so the check is
self-contained: structural CRD rules (the ones `kubectl apply` enforces
client-side) plus validating `example-job.yaml` against the ElasticJob
CRD's OWN openAPIV3Schema with a mini OpenAPI-v3 validator — exactly
the drift this guards against is a field renamed in the operator/CRD
but not in the example (or vice versa).
"""

import os

import pytest

yaml = pytest.importorskip("yaml")

DEPLOY = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "deploy")


def _load(path):
    with open(os.path.join(DEPLOY, path)) as f:
        return [d for d in yaml.safe_load_all(f) if d]


# ---------------------------------------------------------------------------
# mini OpenAPI v3 structural validator
# ---------------------------------------------------------------------------

_TYPES = {
    "object": dict,
    "array": list,
    "string": str,
    "boolean": bool,
}


def _validate(value, schema, path="$"):
    """Returns a list of violations of ``schema`` by ``value``."""
    errs = []
    typ = schema.get("type")
    if typ == "integer":
        if not isinstance(value, int) or isinstance(value, bool):
            return [f"{path}: expected integer, got {type(value).__name__}"]
    elif typ == "number":
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            return [f"{path}: expected number, got {type(value).__name__}"]
    elif typ in _TYPES and not isinstance(value, _TYPES[typ]):
        # k8s quantity convention: numbers often serialized as strings;
        # be exactly as strict as the schema
        return [f"{path}: expected {typ}, got {type(value).__name__}"]
    if typ == "object":
        props = schema.get("properties", {})
        required = schema.get("required", [])
        addl = schema.get("additionalProperties")
        for req in required:
            if req not in value:
                errs.append(f"{path}: missing required field {req!r}")
        for key, sub in value.items():
            if key in props:
                errs.extend(_validate(sub, props[key], f"{path}.{key}"))
            elif isinstance(addl, dict):
                errs.extend(_validate(sub, addl, f"{path}.{key}"))
            elif addl is False:
                errs.append(f"{path}: unknown field {key!r}")
            elif not props and addl is None:
                pass  # free-form object
            elif props and addl is None:
                # structural CRD semantics: unknown fields are PRUNED by
                # the API server — an example relying on one is drift
                errs.append(
                    f"{path}: field {key!r} not in CRD schema (would be "
                    "pruned by the API server)")
    elif typ == "array":
        item_schema = schema.get("items")
        if isinstance(item_schema, dict):
            for i, item in enumerate(value):
                errs.extend(_validate(item, item_schema, f"{path}[{i}]"))
    return errs


# ---------------------------------------------------------------------------
# CRDs
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("crd_file", [
    "crds/elasticjob-crd.yaml", "crds/scaleplan-crd.yaml",
])
def test_crd_structure(crd_file):
    docs = _load(crd_file)
    assert len(docs) == 1
    crd = docs[0]
    assert crd["apiVersion"] == "apiextensions.k8s.io/v1"
    assert crd["kind"] == "CustomResourceDefinition"
    spec = crd["spec"]
    # apiextensions rule: metadata.name == <plural>.<group>
    assert crd["metadata"]["name"] == (
        f"{spec['names']['plural']}.{spec['group']}")
    assert spec["scope"] in ("Namespaced", "Cluster")
    names = spec["names"]
    for field in ("kind", "plural", "singular"):
        assert names[field]
    versions = spec["versions"]
    assert versions
    # exactly one storage version; every served version carries a schema
    assert sum(1 for v in versions if v.get("storage")) == 1
    for v in versions:
        schema = v["schema"]["openAPIV3Schema"]
        assert schema["type"] == "object"
        for col in v.get("additionalPrinterColumns", []):
            assert col["jsonPath"].startswith(".")


def test_example_job_validates_against_crd_schema():
    crd = _load("crds/elasticjob-crd.yaml")[0]
    version = next(v for v in crd["spec"]["versions"] if v.get("storage"))
    schema = version["schema"]["openAPIV3Schema"]
    job = _load("example-job.yaml")[0]
    group = crd["spec"]["group"]
    assert job["apiVersion"] == f"{group}/{version['name']}"
    assert job["kind"] == crd["spec"]["names"]["kind"]
    errs = _validate(
        {k: v for k, v in job.items()
         if k not in ("apiVersion", "kind", "metadata")},
        schema,
    )
    assert not errs, "\n".join(errs)


def test_operator_manifest_wiring():
    """Deployment/RBAC/ServiceAccount must reference each other and the
    CRD group consistently (the drift kubectl would catch server-side)."""
    docs = _load("operator.yaml")
    by_kind = {}
    for d in docs:
        by_kind.setdefault(d["kind"], []).append(d)
    for kind in ("Deployment", "ServiceAccount", "ClusterRole",
                 "ClusterRoleBinding"):
        assert kind in by_kind, f"operator.yaml lacks a {kind}"

    dep = by_kind["Deployment"][0]
    tmpl = dep["spec"]["template"]
    sel = dep["spec"]["selector"]["matchLabels"]
    labels = tmpl["metadata"]["labels"]
    assert all(labels.get(k) == v for k, v in sel.items()), (
        "Deployment selector does not match pod template labels")
    containers = tmpl["spec"]["containers"]
    assert containers and containers[0]["image"]
    sa_name = by_kind["ServiceAccount"][0]["metadata"]["name"]
    assert tmpl["spec"].get("serviceAccountName") == sa_name

    crd_group = _load("crds/elasticjob-crd.yaml")[0]["spec"]["group"]
    role = by_kind["ClusterRole"][0]
    groups = {g for rule in role["rules"]
              for g in rule.get("apiGroups", [])}
    assert crd_group in groups, (
        f"ClusterRole grants no access to the CRD group {crd_group}")
    resources = {r for rule in role["rules"]
                 for r in rule.get("resources", [])}
    assert "elasticjobs" in resources
    assert {"pods", "services"} <= resources, (
        "operator needs pods+services access to launch masters")

    binding = by_kind["ClusterRoleBinding"][0]
    assert binding["roleRef"]["name"] == role["metadata"]["name"]
    subjects = binding["subjects"]
    assert any(s.get("name") == sa_name for s in subjects)


def test_scaleplan_matches_operator_emission():
    """The ScalePlan CRD schema must accept what the reconciler emits
    (operator/controller.py ScalePlan CRs)."""
    crd = _load("crds/scaleplan-crd.yaml")[0]
    version = next(v for v in crd["spec"]["versions"] if v.get("storage"))
    schema = version["schema"]["openAPIV3Schema"]
    # shape consumed by controller.py ScalePlanCR (scaleplan_types.go)
    plan = {
        "spec": {
            "elasticJob": "llama-pretrain",
            "replicaResourceSpecs": {
                "worker": {
                    "replicas": 4,
                    "resource": {"cpu": "8", "memory": "32Gi"},
                },
            },
        },
    }
    errs = _validate(plan, schema)
    assert not errs, "\n".join(errs)
