"""Estimator PS-failover e2e: the TF-session-rebuild counterpart.

Composes the whole PS-strategy chain against a REAL distributed master:
multi-role node groups (worker + 2 critical PS), the
PSClusterVersionCallback bumping the elastic-PS global version on PS
loss/relaunch, the worker's PsFailoverClient version handshake over
gRPC, and the estimator's PsFailoverHook rebuilding sharded KvVariable
state mid-training (reference:
dlrover/trainer/tensorflow/failover/tensorflow_failover.py:33 +
master/node/event_callback.py TFPSNodeHandlingCallback).
"""

import time

import numpy as np
import pytest

from dlrover_tpu.common.constants import NodeStatus, NodeType
from dlrover_tpu.common.node import NodeGroupResource
from dlrover_tpu.sparse import native

if native.check_toolchain() is not None:  # pragma: no cover
    pytest.skip("native toolchain unavailable", allow_module_level=True)

from dlrover_tpu.sparse.kv_variable import KvVariable


def _wait(cond, timeout=15.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


@pytest.fixture()
def ps_master():
    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.common.rpc import find_free_port
    from dlrover_tpu.master.dist_master import DistributedJobMaster
    from dlrover_tpu.scheduler.in_memory import (
        InMemoryCluster,
        InMemoryNodeWatcher,
        InMemoryScaler,
    )

    cluster = InMemoryCluster()
    port = find_free_port()
    master = DistributedJobMaster(
        port,
        scaler=InMemoryScaler(cluster),
        watcher=InMemoryNodeWatcher(cluster),
        heartbeat_timeout=30.0,
        node_groups={
            NodeType.WORKER: NodeGroupResource(1),
            NodeType.PS: NodeGroupResource(2),
        },
    )
    master.prepare()
    client = MasterClient(
        f"127.0.0.1:{port}", node_id=0, node_type=NodeType.WORKER
    )
    try:
        yield master, cluster, client
    finally:
        client.close()
        master.stop()


class ShardedKvState:
    """Worker-side view of KvVariable shards 'hosted' on the PS nodes:
    shard k owns ids with ``id % num_shards == k``.  Snapshots stand in
    for the PS checkpoint the reference restores from after a PS
    relaunch."""

    def __init__(self, num_shards: int = 2, dim: int = 4):
        self.dim = dim
        self.stores = {
            k: KvVariable(dim=dim, init_scale=0.1, seed=10 + k)
            for k in range(num_shards)
        }
        self.snapshots = {}

    def lookup(self, ids: np.ndarray) -> np.ndarray:
        out = np.zeros((len(ids), self.dim), np.float32)
        num = len(self.stores)
        for k, var in self.stores.items():
            mask = ids % num == k
            if mask.any():
                values, _ = var.lookup(ids[mask])
                out[mask] = values
        return out

    def checkpoint(self) -> None:
        self.snapshots = {
            k: var.export() for k, var in self.stores.items()
        }

    def rebuild(self, ps_nodes) -> None:
        """The session-rebuild analog: re-create each shard store from the
        last checkpoint for the new PS set (a relaunched PS lost its
        rows; survivors keep theirs)."""
        num = max(len(ps_nodes), 1)
        fresh = {}
        for k in range(num):
            var = KvVariable(dim=self.dim, init_scale=0.1, seed=10 + k)
            if k in self.snapshots:
                var.import_(self.snapshots[k])
                var.retain_shard(k, num)
            fresh[k] = var
        self.stores = fresh


def test_ps_loss_mid_training_rebuilds_and_continues(ps_master):
    import jax.numpy as jnp
    import optax

    from dlrover_tpu.agent.ps_failover import PsFailoverClient
    from dlrover_tpu.trainer.estimator import (
        EstimatorExecutor,
        PsFailoverHook,
        TrainSpec,
    )

    master, cluster, client = ps_master
    assert _wait(
        lambda: sum(
            n.status == NodeStatus.RUNNING
            for n in master.job_manager.job_nodes.get(NodeType.PS, {}).values()
        )
        == 2
    )
    # initial cluster formed at version 0 — worker adopts it
    assert master.elastic_ps_service.get_global_cluster_version() == 0

    kv = ShardedKvState(num_shards=2)
    ids = np.arange(8, dtype=np.int64)
    before = kv.lookup(ids)  # materializes rows on both shards
    kv.checkpoint()

    failover = PsFailoverClient(client, node_type=NodeType.WORKER, node_id=0)
    reshard_events = []

    def on_reshard(nodes):
        reshard_events.append([m.node_rank for m in nodes])
        kv.rebuild(nodes)

    hook = PsFailoverHook(failover, on_reshard=on_reshard)

    kill_at_step = 3
    total_steps = 12

    def input_fn():
        for step in range(total_steps):
            if step == kill_at_step:
                victim = next(
                    name
                    for name, n in cluster.nodes.items()
                    if n.type == NodeType.PS and n.rank_index == 0
                )
                cluster.fail_node(victim)
                # critical PS relaunches; version bumps on loss AND on the
                # replacement reaching RUNNING
                assert _wait(
                    lambda: master.elastic_ps_service
                    .get_global_cluster_version() >= 1
                )
                _wait(
                    lambda: sum(
                        n.status == NodeStatus.RUNNING
                        for n in master.job_manager.job_nodes[
                            NodeType.PS
                        ].values()
                    )
                    == 2
                )
            feats = kv.lookup(ids)  # host-side sparse gather
            labels = np.ones((len(ids), 1), np.float32)
            yield feats, labels

    def model_fn(params, features, labels):
        pred = features @ params["w"]
        loss = jnp.mean((pred - labels) ** 2)
        return loss, {}

    executor = EstimatorExecutor(
        model_fn=model_fn,
        init_params_fn=lambda key: {
            "w": jnp.zeros((kv.dim, 1), jnp.float32)
        },
        train_spec=TrainSpec(input_fn=input_fn),
        optimizer=optax.sgd(0.1),
        hooks=[hook],
    )
    metrics = executor.train_and_evaluate()

    # training ran to completion through the PS loss
    assert executor.global_step == total_steps
    assert np.isfinite(metrics["loss"])
    # the failover hook observed the version bump and rebuilt the shards
    assert hook.reshard_count >= 1
    assert reshard_events and reshard_events[0] == [0, 1]
    assert not failover.ps_cluster_changed()  # version adopted
    # shard-0 rows came back from the snapshot; shard-1 rows untouched
    np.testing.assert_allclose(kv.lookup(ids), before, atol=1e-6)

    # the relaunched PS is a *new scheduler node* with the same rank
    ps_nodes, ready, failure = master.job_manager.query_ps_nodes()
    assert ready and not failure
    assert [m.node_rank for m in ps_nodes] == [0, 1]
